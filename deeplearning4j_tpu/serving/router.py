"""Multi-replica serving router: a failure-tolerant, prefix-aware
front door over N :class:`~deeplearning4j_tpu.serving.ServingGateway`
replicas (ISSUE 9 tentpole — ROADMAP item 3).

One gateway owns one engine; millions of users need horizontal scale,
and horizontal scale means replicas DIE — a process crash today loses
every in-flight stream that replica owned. The router lifts the
guarantees PR 3/5 proved inside one process (seeded fault recovery,
drain-to-snapshot restore finishing bit-identical ids, per-request
``delta_sent`` high-water dedup) across process boundaries, the same
replay-on-survivor discipline vLLM-style fleets and Orca-style
continuous-batching servers need once they go horizontal:

**Health & liveness.** A background loop scrapes every replica's
``/v1/healthz`` (each tick) and ``/v1/metrics`` (every few ticks),
feeding a per-replica state machine::

        live ──failure──▶ degraded ──threshold──▶ dead
         ▲                   │                      │
         │◀────success───────┘          probe every probe_interval_s
         │                                          ▼
         └──────────probe succeeds────────── half-open

Consecutive failures (health scrapes AND data-plane stream breaks both
count) trip the circuit breaker at ``failure_threshold``; a dead
replica gets one half-open probe per ``probe_interval_s`` and rejoins
on success. A 429 + ``Retry-After`` from a replica is BACKPRESSURE,
not failure: the replica is healthy and said "later" — the router
parks it until the hint expires and routes the request to a sibling
instead of making the client wait (ISSUE 9 satellite).

**Prefix-affinity routing.** Shared-system-prompt traffic only pays
off when it lands where its radix/block cache is warm. The router
hashes the prompt's leading block-aligned tokens
(``affinity_block_tokens``-sized, matching the paged engine's block
granularity) and RENDEZVOUS-hashes (highest-random-weight) that key
against the live replica ids: every replica scores
``hash(prefix_key, replica_id)`` and the max wins, so replica death
remaps ONLY the dead replica's keyspace — survivors keep their warm
sets, unlike modular hashing where one death reshuffles everyone.
Prompts shorter than one block (no reusable prefix worth chasing)
fall back to queue-depth-weighted least-loaded using the scraped
per-replica load.

**The robustness core: journal + replay.** Every proxied request is
journaled (id, prompt, params, owning replica, streamed-token
high-water mark) and relayed through the router as SSE deltas — even
blocking client calls ride an internal stream, so the journal's
high-water mark is always live. When a replica dies mid-request (or a
drain hands its unfinished work back), the relay loop replays the
request onto a survivor: the FULL prompt is resubmitted (recompute
replay, the vLLM-preemption discipline — deterministic greedy decode
regenerates the same ids), the journal's high-water mark dedups the
already-streamed prefix (each regenerated token is CHECKED against the
streamed one, then discarded), and the client's stream resumes
bit-identically past where it stopped. Sampling requests that already
streamed tokens terminate ``finish_reason="fault"`` instead — a
redrawn RNG cannot splice onto a streamed prefix (the exact PR 3/5
contract, now across processes). Graceful scale-down is the same code
path: ``drain_replica`` routes ``/v1/drain`` through the replica,
whose unfinished streams end without a terminal event, and the relay
loops re-admit those requests on survivors.

**Fleet-wide observability (ISSUE 10 tentpole).** The router is the
only place that sees a request's WHOLE life across the fleet, so it
is where the fleet's observability lives:

- every journaled request carries a router-minted trace id
  (``r<rid>``) with per-attempt span ids (``a<n>``), propagated to
  the replica as the ``X-DL4J-Trace`` header + JSON ``trace`` field —
  the engine stamps its spans, flight-recorder record, and terminal
  with it;
- ``GET /v1/trace`` answers the STITCHED fleet trace: each replica's
  Chrome-trace window on its own process lane (live fetch when
  reachable, the health loop's incrementally-scraped cache for dead
  replicas — how a SIGKILLed victim's spans survive), skew-corrected
  onto the router clock by per-replica NTP-style offset estimates
  (healthz ``now_us`` sampled inside a timed scrape, error <= RTT/2),
  interleaved with the router's own ``router.route`` /
  ``router.queue_wait`` / ``router.replay`` spans and
  ``router.breaker`` instants — a failover reads as one request's
  monotone timeline spanning two replicas;
- ``GET /v1/fleet/metrics`` federates the replicas
  (:meth:`profiler.tracer.Tracer.merge_prometheus`): histograms
  merged bucket-wise + labeled per replica, counters summed, gauges
  labeled, plus the router's ``router_replay_gap_s`` histogram
  (stream break -> first post-replay token);
- ``GET /v1/requests/<id>/trace`` proxies the owner's flight record
  via the journal, or serves journal breadcrumbs with a
  ``replayed_to`` pointer when the owner died.

**Durability (ISSUE 15 tentpole).** The journal above is also a
crash ledger: with ``journal_path=`` every open/route/progress/done
transition (plus tenant bucket levels, warm-KV beliefs, and stable
replica ids) is appended to a length+CRC framed write-ahead journal
(serving/journal.py) BEFORE the router acts on it. A SIGKILLed
router restarted against the same file replays its open entries
through the very replay path above — full-prompt resubmit on
whichever replicas answer healthz, the recovered high-water mark
dedupping the regenerated prefix — restores bucket levels (a flooder
stays throttled through the crash) and warm beliefs, and emits a
``router.recover`` span on the stitched trace. Streams carry
monotone SSE event ids (= delivered-token count), so a dropped
client resumes via ``GET /v1/requests/<id>/stream`` +
``Last-Event-ID`` with zero duplicated and zero lost tokens;
``resumable: true`` on the generate body turns client disconnects
into detaches instead of cancels.

The router speaks the gateway's own protocol (``/v1/generate``,
``/v1/requests/<id>``, ``/v1/healthz``, ``/v1/metrics``, SSE framing),
so :class:`~deeplearning4j_tpu.serving.GatewayClient` drives a router
exactly like a single gateway — a one-replica router is bit-identical
to direct gateway access. Stdlib-only, on util/httpjson like the
gateway."""

from __future__ import annotations

import contextlib
import hashlib
import itertools
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.serving.client import (
    RETRYABLE_ERRORS,
    GatewayClient,
    GatewayError,
)
from deeplearning4j_tpu.serving.journal import (
    WriteAheadJournal,
    recover_state,
)
from deeplearning4j_tpu.util.httpjson import HttpService, JsonHandler

#: every state a replica can be in, as the router sees it:
#: ``live`` (routable), ``degraded`` (recent failures below the
#: breaker threshold — routable only when nothing live remains),
#: ``draining`` (finishing in-flight work, not routable for new
#: requests), ``dead`` (breaker open — not routable, in-flight
#: requests replayed), ``half-open`` (dead, one probe in flight).
REPLICA_STATES = ("live", "degraded", "draining", "dead", "half-open")


class _NoReplica(RuntimeError):
    """No replica can take the request (everyone dead/draining)."""


class _AllBackedOff(RuntimeError):
    """Every candidate replica is parked behind a 429 Retry-After."""

    def __init__(self, wait_s: float):
        super().__init__(f"all replicas backed off for {wait_s:.1f}s")
        self.wait_s = wait_s


class _ClientGone(Exception):
    """The ROUTER's own client vanished mid-relay (failed SSE write).
    Distinct from replica-side read failures on purpose: a client
    disconnect must cancel the request, never charge the replica's
    breaker or trigger a replay."""


class _RouteAround(Exception):
    """This attempt never started streaming — try another replica
    without charging the replay budget. ``deterministic`` carries a
    terminal to deliver instead when retrying elsewhere would just
    repeat the same rejection (bad params)."""

    def __init__(self, deterministic: Optional[Dict[str, Any]] = None):
        super().__init__()
        self.deterministic = deterministic


class _ReplayDiverged(RuntimeError):
    """A replayed greedy stream produced a token that differs from
    the already-streamed prefix — the survivors are not replicas of
    the dead engine (different weights/seed/config). Never expected
    in a correctly deployed fleet; terminates the request ``fault``
    rather than silently splicing wrong tokens."""


class _Replica:
    """Router-side state of one gateway replica. All mutable fields
    are guarded by the router's lock."""

    def __init__(self, address: str):
        self.address = address.split("://", 1)[-1]
        #: stable identity for rendezvous hashing; replaced by the
        #: replica's self-reported id at the first health scrape
        self.replica_id = self.address
        self.state = "live"  # optimistic until the breaker disagrees
        self.failures = 0
        #: disaggregation role (ISSUE 14), scraped from healthz:
        #: ``prefill`` replicas prefer admission-heavy traffic and
        #: serve as warm-KV donors, ``decode`` replicas prefer
        #: long-decode streams, ``any`` is the role-blind default
        self.role = "any"
        #: whether the replica can speak the KV transfer plane
        #: (paged engine + prefix trie) — scraped from healthz so a
        #: dense fleet never pays a 404 round-trip per affinity miss
        self.kv_capable = False
        #: resident spill-tier payload count (ISSUE 17), scraped from
        #: the healthz ``kv_tier`` block: a host/disk-tier-warm
        #: replica serves exports straight from the tier (zero device
        #: work), so the donor pick prefers it over a cold one
        self.kv_tier_entries = 0
        self.backoff_until = 0.0  # 429 Retry-After parking
        #: per-TENANT 429 parking (ISSUE 13): a replica's
        #: tenant-scoped 429 (its payload names the tenant) parks
        #: only that tenant's keyspace on this replica — other
        #: tenants keep routing here. ``backoff_until`` above stays
        #: the replica-wide park for tenant-blind (global queue
        #: full) backpressure.
        self.tenant_backoff: Dict[str, float] = {}
        self.next_probe_t = 0.0   # half-open probe schedule (dead)
        self.decommissioned = False  # drained away: never resurrected
        # scraped load + affinity figures
        self.queue_depth = 0
        self.active_slots = 0
        self.n_slots = 1
        self.prefix_tokens_reused = 0
        self.requests_routed = 0
        self.open_entries = 0  # journal entries currently assigned
        # -- idempotent drain (ISSUE 11 satellite): the fleet
        # controller and a human operator WILL race on scale-down —
        # the first drain owns the work, every later/concurrent drain
        # waits on the event and returns the first drain's summary
        self.drain_started = False
        self.drain_done = threading.Event()
        self.drain_summary: Optional[Dict[str, Any]] = None
        # -- fleet tracing state (ISSUE 10) ----------------------------
        #: estimated ``replica_tracer_now - router_tracer_now`` in µs,
        #: NTP-style: the replica reports its tracer clock inside a
        #: timed healthz scrape and the midpoint of the scrape window
        #: is the sample point, so the estimate's error is bounded by
        #: half the scrape RTT. The stitcher maps a replica event onto
        #: the router timeline as ``ts - clock_offset_us``.
        self.clock_offset_us: Optional[float] = None
        self.clock_rtt_us = float("inf")
        self.clock_age = 0      # scrapes since the estimate updated
        #: the offset that matches ``trace_cache``'s EPOCH: cached
        #: events and the offset that corrects them must come from
        #: the same process lifetime, so the pair is snapshotted
        #: together at scrape time — the live estimate above may be
        #: reset (death, restart detection) while the cache still
        #: holds the dead epoch's events
        self.cache_offset_us: Optional[float] = None
        #: scraped Chrome-trace window (the replica flight recorder's
        #: fleet-side shadow): when a replica is SIGKILLed its own
        #: tracer dies with it — this cache is the only place the
        #: victim's spans survive, and what puts the dead lane in a
        #: stitched failover trace. Filled INCREMENTALLY
        #: (``?since_seq=`` + the resume cursor below), so the
        #: periodic scrape pays for the delta, not the window.
        self.trace_cache: List[Dict[str, Any]] = []
        self.trace_cache_t = 0.0
        self.trace_seq = 0

    def status(self) -> Dict[str, Any]:
        return {
            "replica_id": self.replica_id,
            "address": self.address,
            "state": self.state,
            "consecutive_failures": self.failures,
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "n_slots": self.n_slots,
            "prefix_tokens_reused": self.prefix_tokens_reused,
            "requests_routed": self.requests_routed,
            "open_requests": self.open_entries,
            "decommissioned": self.decommissioned,
            "role": self.role,
            "kv_capable": self.kv_capable,
        }


class _JournalEntry:
    """One proxied request's journal record: everything replay needs
    (prompt + params), plus the streamed-token high-water mark that
    makes replay exactly-once from the client's point of view.
    ``tokens`` IS the high-water mark: every token in it has been
    relayed to the client (or accumulated for a blocking reply), and
    a replayed stream's regenerated prefix is checked against it and
    dropped instead of re-delivered."""

    __slots__ = ("rid", "prompt", "params", "temperature", "tokens",
                 "replays", "cancelled", "done", "result",
                 "replica_address", "replica_rid", "affinity",
                 "history", "submit_t", "trace", "done_t",
                 "replay_t0_us", "replay_hwm", "replay_from",
                 "tenant", "resumable", "recovered")

    def __init__(self, rid: int, prompt: List[int],
                 params: Dict[str, Any], submit_t: float):
        self.rid = rid
        self.prompt = prompt
        self.params = params
        self.temperature = float(params.get("temperature") or 0.0)
        #: tenancy identity (ISSUE 13) — rides ``params`` to the
        #: replica (so failover replay re-bills the same tenant) and
        #: keys the router's per-tenant parking/accounting
        self.tenant = str(params.get("tenant") or "default")
        self.tokens: List[int] = []
        self.replays = 0
        self.cancelled = False
        self.done = threading.Event()
        self.result: Optional[Dict[str, Any]] = None
        self.replica_address: Optional[str] = None
        self.replica_rid: Optional[int] = None
        self.affinity = False
        #: (t_s, event) breadcrumbs: routed/replayed/finished — the
        #: journal's audit trail the chaos soak asserts over
        self.history: List[Tuple[float, str]] = []
        self.submit_t = submit_t
        #: fleet trace id (ISSUE 10): the router-minted identity every
        #: hop stamps its spans with; per-attempt span ids extend it
        self.trace: Optional[str] = None
        self.done_t: Optional[float] = None
        # open replay window: set when a stream broke and the request
        # is being replayed; closed (-> router.replay span + the
        # router_replay_gap_s observation) by the first POST-replay
        # fresh token, or by the terminal/divergence
        self.replay_t0_us: Optional[float] = None
        self.replay_hwm = 0
        self.replay_from: Optional[str] = None
        #: ISSUE 15: a resumable stream's client disconnect DETACHES
        #: instead of cancelling — the relay keeps running with a
        #: buffering emit and the client reconnects via
        #: ``GET /v1/requests/<rid>/stream`` + ``Last-Event-ID``
        self.resumable = bool(params.get("resumable"))
        #: rebuilt from the write-ahead journal after a router
        #: restart (open entries re-enter the replay path; done
        #: entries serve polls/resumes from their recovered terminal)
        self.recovered = False

    def note(self, t: float, event: str) -> None:
        self.history.append((round(t, 4), event))


def parse_prometheus(text: str) -> Dict[str, float]:
    """Minimal Prometheus text parse: ``name value`` sample lines to a
    dict (comments/HELP/TYPE skipped, label-carrying and unparsable
    samples ignored). Enough for the gauge tracks the gateway
    exports."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.partition(" ")
        if "{" in name:
            continue
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


class _RouterHandler(JsonHandler):
    """One instance per connection; the owning router rides in as the
    ``router`` class attribute (HttpService)."""

    protocol_version = "HTTP/1.1"
    router: "ServingRouter"

    def do_POST(self):
        path, _, query = self.path.partition("?")
        if path == "/v1/generate":
            stream = "stream=1" in query.split("&")
            self.router._handle_generate(self, stream)
        elif path == "/v1/replicas/drain":
            self.router._handle_drain_replica(self)
        else:
            self.send_json({"error": f"no such endpoint {path}"}, 404,
                           close=True)

    def do_GET(self):
        path, _, query = self.path.partition("?")
        if path == "/v1/healthz":
            self.send_json(self.router._health(), 200, close=True)
        elif path == "/v1/metrics":
            self.send_bytes(self.router._metrics_text().encode(),
                            "text/plain; version=0.0.4", 200,
                            close=True)
        elif path == "/v1/fleet/metrics":
            self.router._handle_fleet_metrics(self)
        elif path == "/v1/trace":
            self.router._handle_fleet_trace(self)
        elif (path.startswith("/v1/requests/")
                and path.endswith("/trace")):
            self.router._handle_request_trace(self, path)
        elif (path.startswith("/v1/requests/")
                and path.endswith("/stream")):
            self.router._handle_stream_resume(self, path, query)
        elif path.startswith("/v1/requests/"):
            self.router._handle_poll(self, path)
        else:
            self.send_json({"error": f"no such endpoint {path}"}, 404,
                           close=True)

    def do_DELETE(self):
        path = self.path.partition("?")[0]
        if path.startswith("/v1/requests/"):
            self.router._handle_cancel(self, path)
        else:
            self.send_json({"error": f"no such endpoint {path}"}, 404,
                           close=True)

    # SSE framing (send_event / send_ping) inherited from JsonHandler


class RouterClient(GatewayClient):
    """GatewayClient plus the router-only admin surface. Generation,
    polling, cancel, healthz, and metrics are the plain gateway
    protocol — this subclass only adds what a single gateway does not
    have."""

    def drain_replica(self, replica_id: str,
                      timeout_s: Optional[float] = None
                      ) -> Dict[str, Any]:
        """Graceful scale-down of one replica through the router:
        drains it, fails its unfinished requests over to survivors,
        and decommissions it."""
        body: Dict[str, Any] = {"replica_id": replica_id}
        if timeout_s is not None:
            body["timeout_s"] = timeout_s
        return self._call("POST", "/v1/replicas/drain", body)

    def fleet_metrics(self) -> str:
        """``GET /v1/fleet/metrics`` — the federated Prometheus
        exposition (ISSUE 10): replica histogram families merged
        bucket-wise into fleet-wide distributions (plus per-replica
        ``{replica=...}``-labeled samples), counters summed, gauges
        labeled per replica, and the router's own tracks
        (``router_*`` including the ``router_replay_gap_s``
        histogram) appended."""
        return self._get_text("/v1/fleet/metrics")

    # ``trace_events()`` (inherited) against a ROUTER returns the
    # STITCHED fleet trace: every replica's window on its own process
    # lane, skew-corrected, with the router's route/replay/breaker
    # spans interleaved (ISSUE 10 tentpole).
    fleet_trace = GatewayClient.trace_events


class ServingRouter:
    """Failure-tolerant prefix-aware router over N gateway replicas.

    Parameters:

    - ``replicas`` — gateway addresses (``host:port`` or
      ``http://host:port``). All replicas must serve the SAME model
      with the same seed/config: greedy replay correctness depends on
      every replica producing bit-identical ids for the same request.
    - ``host``/``port`` — the router's own bind address (port 0 =
      ephemeral).
    - ``affinity_block_tokens`` — the affinity hash covers the
      prompt's leading ``floor(len/B)*B`` tokens; prompts shorter than
      one block route least-loaded instead. Match the replicas'
      ``block_tokens`` when they run paged KV.
    - ``health_interval_s`` / ``metrics_every`` — healthz scrape
      period, and how many health ticks between the heavier
      ``/v1/metrics`` scrapes.
    - ``failure_threshold`` — consecutive failures (scrape or
      data-plane) that trip a replica's breaker to ``dead``.
    - ``probe_interval_s`` — half-open probe period for dead replicas.
    - ``max_replays`` — replay budget per request across replica
      deaths; past it the request terminates ``fault``.
    - ``fleet_trace`` — fleet observability master switch (default
      ON; priced >= 0.97x by ``bench_fleet_trace_overhead``):
      trace-context propagation, router spans, the incremental
      per-replica trace cache, and clock-offset estimation.
    - ``kv_transfer`` — KV transfer plane master switch (ISSUE 14;
      default ON, capability-gated per replica via healthz so a
      dense fleet pays nothing): warm-import on affinity-miss /
      failover picks whose receiver is cold for the key, with
      fallback to full recompute on any fault.
    - ``replica_connect_timeout_s`` / ``replica_timeout_s`` — the
      router→replica connect and read bounds (a dead replica must
      fail fast, a healthy stream may idle up to the replica's
      keep-alive period between events).
    - ``journal_path`` — crash-safe write-ahead journal (ISSUE 15
      tentpole; default None = the memory-only PR 9 journal). Every
      open/route/progress/done transition, tenant bucket level, and
      warm-KV belief is appended BEFORE the router acts on it; a
      router restarted against the same path replays open entries on
      whichever replicas answer healthz (high-water dedup — zero
      lost, zero double-delivered tokens), restores bucket levels
      (a flooder stays throttled through a crash) and warm beliefs,
      and serves client resumes from the recovered breadcrumbs.
    - ``fsync`` — the WAL durability policy (``per_record`` /
      ``batched`` / ``off``; serving/journal.py). ``batched``
      (default) is SIGKILL-safe and priced >= 0.97x WAL-off by
      ``bench_router_wal_overhead``.
    - ``wal_compact_bytes`` — compaction threshold: past it the live
      state folds into one snapshot record and the file rewrites
      atomically, so the WAL stays bounded like ``journal_cap``.

    ``with ServingRouter([...]) as r: ...`` serves on entry and closes
    on exit; or ``start()``/``close()`` explicitly."""

    def __init__(self, replicas: Sequence[str],
                 host: str = "127.0.0.1", port: int = 0,
                 affinity_block_tokens: int = 16,
                 health_interval_s: float = 0.25,
                 metrics_every: int = 4,
                 failure_threshold: int = 3,
                 probe_interval_s: float = 1.0,
                 max_replays: int = 3,
                 keepalive_s: float = 0.5,
                 handler_timeout_s: float = 30.0,
                 replica_connect_timeout_s: float = 2.0,
                 replica_timeout_s: float = 120.0,
                 journal_cap: int = 4096,
                 fleet_trace: bool = True,
                 tracer=None,
                 tenants=None,
                 kv_transfer: bool = True,
                 journal_path: Optional[str] = None,
                 fsync: str = "batched",
                 wal_compact_bytes: int = 1 << 20,
                 wal_retain_done: int = 64):
        if not replicas:
            raise ValueError("router needs at least one replica")
        if affinity_block_tokens < 1:
            raise ValueError(
                f"affinity_block_tokens {affinity_block_tokens} < 1")
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold {failure_threshold} < 1")
        self._replicas = [_Replica(a) for a in replicas]
        seen: Set[str] = set()
        for r in self._replicas:
            if r.address in seen:
                raise ValueError(f"duplicate replica {r.address}")
            seen.add(r.address)
        self.affinity_block_tokens = int(affinity_block_tokens)
        self.health_interval_s = float(health_interval_s)
        self.metrics_every = max(int(metrics_every), 1)
        self.failure_threshold = int(failure_threshold)
        self.probe_interval_s = float(probe_interval_s)
        self.max_replays = int(max_replays)
        self.keepalive_s = float(keepalive_s)
        self.replica_connect_timeout_s = float(
            replica_connect_timeout_s)
        self.replica_timeout_s = float(replica_timeout_s)
        self.journal_cap = int(journal_cap)
        #: multi-tenant QoS front door (ISSUE 13; default None = the
        #: tenant-blind router): a
        #: :class:`~deeplearning4j_tpu.serving.tenancy.TenantRegistry`
        #: whose ``rate_rps``/``burst`` specs arm per-tenant token
        #: buckets — a flooder sheds AT THE DOOR with its own
        #: Retry-After (time to the next bucket token) before any
        #: replica sees it, and the ``system`` tenant is never
        #: throttled (warmup must always land)
        self.tenants = tenants
        self._buckets: Dict[str, Any] = {}
        #: fleet observability master switch (ISSUE 10; default ON —
        #: priced by bench_fleet_trace_overhead): trace-context
        #: propagation to replicas, router route/replay spans, the
        #: per-replica trace cache, and clock-offset estimation. Off,
        #: the router is the span-silent ISSUE 9 router (the
        #: /v1/trace and /v1/fleet/metrics endpoints still answer,
        #: with router-only lanes / unstamped requests).
        self.fleet_trace = bool(fleet_trace)
        if tracer is None:
            from deeplearning4j_tpu.profiler.tracer import Tracer

            tracer = Tracer(max_events=65536)
        self.tracer = tracer
        from deeplearning4j_tpu.profiler.tracer import Histogram

        #: replay-added latency: stream break -> first POST-replay
        #: token the client had not already seen (the failover cost a
        #: fleet operator actually pays — latency_report's --fleet
        #: ``replay_gap`` row)
        self._replay_gap = Histogram()
        if hasattr(self.tracer, "register_histogram"):
            self.tracer.register_histogram("router_replay_gap_s",
                                           self._replay_gap)
        if hasattr(self.tracer, "describe"):
            self.tracer.describe(
                "router_replay_gap_s",
                "stream-break to first post-replay fresh-token gap "
                "(replay-added latency per failover)")
        #: KV transfer plane master switch (ISSUE 14; default ON —
        #: capability-gated per replica via healthz ``kv_transfer``,
        #: so a dense fleet pays literally nothing): on an affinity
        #: miss / failover replay whose receiver is cold for the key,
        #: the router pulls the warm peer's exported prefix and
        #: imports it into the receiver BEFORE the attempt; any fault
        #: falls back to full recompute (correctness never depends on
        #: the transfer).
        self.kv_transfer = bool(kv_transfer)
        #: bounded warm-key map: affinity key -> {replica_id: stamp}
        #: — which replicas are believed warm for a key (admissions
        #: routed there, or a completed import). A belief, not a
        #: contract: a wrong entry costs one recompute, nothing else.
        self._warm: "Dict[bytes, Dict[str, float]]" = {}
        self._warm_cap = 1024
        #: end-to-end transfer wall (export fetch + import push) —
        #: the ``serving_kv_transfer_s`` row in latency_report
        #: --fleet (the router appends its own tracks to the
        #: federation)
        self._kv_transfer_hist = Histogram()
        if hasattr(self.tracer, "register_histogram"):
            self.tracer.register_histogram("serving_kv_transfer_s",
                                           self._kv_transfer_hist)
        if hasattr(self.tracer, "describe"):
            self.tracer.describe(
                "serving_kv_transfer_s",
                "cross-replica KV transfer wall (donor export fetch "
                "+ receiver import push, per shipped prefix)")
        self._lock = threading.RLock()
        self._rids = itertools.count()
        self._rid_hwm = 0  # next unminted rid (the WAL snapshot's)
        self._journal: Dict[int, _JournalEntry] = {}
        self._rr = 0  # least-loaded tie-break rotation
        self._t0 = time.monotonic()
        self.stats = {
            "requests": 0, "streams": 0, "affinity_routed": 0,
            "affinity_overflow": 0,
            "load_routed": 0, "replays": 0, "rerouted_429": 0,
            "replica_faults": 0, "request_faults": 0,
            "disconnect_cancels": 0, "drained_replicas": 0,
            "tenant_throttled": 0, "tenant_backoffs": 0,
            "kv_transfers": 0, "kv_transfer_failures": 0,
            "kv_transfer_declined": 0, "kv_transferred_tokens": 0,
            "recovered_entries": 0, "recovered_open": 0,
            "recovered_replayed": 0, "resumed_streams": 0,
            "detached_streams": 0, "wal_compactions": 0,
            "wal_errors": 0,
        }
        #: the crash ledger (ISSUE 15 tentpole): None = memory-only
        self._wal: Optional[WriteAheadJournal] = None
        self.wal_retain_done = int(wal_retain_done)
        self._recovered_buckets: Dict[str, Dict[str, float]] = {}
        self._recovery_open: List[_JournalEntry] = []
        self._recover_t0_us: Optional[float] = None
        self._recover_pending = 0
        self._compacting = False
        self._wal_deferred: List[Dict[str, Any]] = []
        self._wal_flush_lock = threading.Lock()
        if journal_path is not None:
            self._wal = WriteAheadJournal(
                journal_path, fsync=fsync,
                compact_bytes=wal_compact_bytes)
            if self._wal.recovered:
                self._restore_from_wal(
                    recover_state(self._wal.recovered))
        self._stopped = False
        self._service = HttpService(_RouterHandler, host, port,
                                    router=self,
                                    timeout=float(handler_timeout_s))
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="router-health")

    # -- lifecycle -----------------------------------------------------
    @property
    def address(self) -> str:
        return self._service.address

    def start(self) -> "ServingRouter":
        self._service.start()
        self._health_thread.start()
        if self._recovery_open:
            # re-enter the PR 9 replay path for every entry the WAL
            # says was open when the previous router died: full-prompt
            # resubmit on whichever replicas answer healthz, the
            # recovered high-water mark dedupping the already-streamed
            # prefix. Off-thread — clients reconnect through the
            # resume endpoint while replay runs.
            replays, self._recovery_open = self._recovery_open, []
            for entry in replays:
                threading.Thread(
                    target=self._recover_entry, args=(entry,),
                    daemon=True,
                    name=f"router-recover-{entry.rid}").start()
        elif self._recover_t0_us is not None:
            # a WAL with nothing open still recovered state (done
            # breadcrumbs, buckets, beliefs): the span records it
            self._emit_recover_span()
        return self

    def __enter__(self) -> "ServingRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Stop the router tier: health loop joined, HTTP service
        stopped, every still-open journal entry released (their
        handlers answer 503/end-of-stream). Replicas are NOT touched —
        they keep serving direct traffic."""
        self._stopped = True
        if self._health_thread.is_alive():
            self._health_thread.join(
                timeout=5.0 + 2 * self.health_interval_s)
        with self._lock:
            for entry in self._journal.values():
                entry.done.set()
        self._service.stop()
        if self._wal is not None:
            # drain deferred records, then flush + fsync — NO
            # clean-shutdown marker: the recovery path must be the
            # same one a SIGKILL exercises
            self._wal_flush()
            self._wal.close()

    def _now(self) -> float:
        return time.monotonic() - self._t0

    def _now_us(self) -> float:
        """The router's trace-event clock (µs) — the timeline every
        replica's events are skew-corrected onto."""
        f = getattr(self.tracer, "now_us", None)
        return float(f()) if f else (time.monotonic() - self._t0) * 1e6

    def _breaker_instant(self, replica: _Replica, frm: str,
                         to: str) -> None:
        """State-transition instant for the stitched trace (ISSUE 10):
        a failover timeline without the breaker's live→dead /
        dead→half-open→live instants cannot answer WHEN routing
        noticed. Caller holds the lock; the tracer has its own."""
        if frm != to and hasattr(self.tracer, "instant"):
            try:
                self.tracer.instant("router.breaker", scope="p",
                                    replica=replica.replica_id,
                                    frm=frm, to=to,
                                    failures=replica.failures)
            except TypeError:  # duck-typed tracer without scope
                self.tracer.instant("router.breaker",
                                    replica=replica.replica_id,
                                    frm=frm, to=to,
                                    failures=replica.failures)

    def _replica_client(self, replica: _Replica,
                        read_timeout_s: Optional[float] = None,
                        retries: int = 0) -> GatewayClient:
        return GatewayClient(
            replica.address,
            connect_timeout_s=self.replica_connect_timeout_s,
            read_timeout_s=(self.replica_timeout_s
                            if read_timeout_s is None
                            else read_timeout_s),
            retries=retries)

    # -- health / liveness tracking ------------------------------------
    def _health_loop(self) -> None:
        tick = 0
        while not self._stopped:
            tick += 1
            for replica in list(self._replicas):
                if self._stopped:
                    return
                try:
                    self._check_replica(
                        replica,
                        scrape_metrics=(
                            tick % self.metrics_every == 0))
                except Exception:
                    # the breaker thread must NEVER die: an exotic
                    # failure shape from a dying peer (anything the
                    # retryable classification missed) counts as a
                    # failed scrape, not a router outage
                    self._note_failure(replica)
                    self.tracer.incr("router_health_scrape_errors")
            # deferred warm/cold/rep records from lock-held sites
            # drain here at worst (most drain at their caller's seam)
            self._wal_flush()
            time.sleep(self.health_interval_s)

    def _check_replica(self, replica: _Replica,
                       scrape_metrics: bool) -> None:
        if replica.decommissioned:
            return
        now = time.monotonic()
        if replica.state in ("dead", "half-open"):
            if now < replica.next_probe_t:
                return
            with self._lock:
                self._breaker_instant(replica, replica.state,
                                      "half-open")
                replica.state = "half-open"
        # scrape timeouts well under the health interval budget: a
        # hung replica must not stall the whole loop for long
        probe = self._replica_client(
            replica, read_timeout_s=max(
                4 * self.health_interval_s, 1.0))
        t0_us = self._now_us()
        try:
            payload = probe.healthz()
        except (GatewayError, *RETRYABLE_ERRORS):
            self._note_failure(replica)
            return
        self._note_clock(replica, payload, t0_us, self._now_us())
        self._note_alive(replica, payload)
        if scrape_metrics and replica.state == "live":
            try:
                gauges = parse_prometheus(probe.metrics())
            except (GatewayError, *RETRYABLE_ERRORS):
                return  # healthz just succeeded; not a breaker event
            with self._lock:
                if "serving_gateway_queue_depth" in gauges:
                    replica.queue_depth = int(
                        gauges["serving_gateway_queue_depth"])
                if "serving_gateway_active_slots" in gauges:
                    replica.active_slots = int(
                        gauges["serving_gateway_active_slots"])
                if "serving_prefill_tokens_skipped" in gauges:
                    replica.prefix_tokens_reused = int(
                        gauges["serving_prefill_tokens_skipped"])
            if self.fleet_trace:
                self._scrape_trace(replica, probe)

    def _note_clock(self, replica: _Replica,
                    payload: Dict[str, Any], t0_us: float,
                    t1_us: float) -> None:
        """Fold one timed healthz scrape into the replica's clock-
        offset estimate. NTP midpoint: the replica read its tracer
        clock somewhere inside [t0, t1] on the router timeline, so
        ``offset = replica_now - (t0+t1)/2`` with error <= RTT/2. A
        lower-RTT sample always replaces a higher-RTT one (tighter
        bound); an AGED estimate (8 scrapes) is replaced regardless,
        so a one-off fast scrape cannot pin a stale offset while the
        clocks drift."""
        now_us = payload.get("now_us")
        if now_us is None:
            return
        rtt_us = t1_us - t0_us
        candidate = float(now_us) - (t0_us + t1_us) / 2.0
        with self._lock:
            replica.clock_age += 1
            # a candidate a full second away from the stored estimate
            # is not drift (µs between scrapes) — it is a NEW PROCESS
            # epoch on the same address (restart/resurrection):
            # accept immediately, or the stitcher would correct the
            # new epoch's events with the dead process's offset for
            # up to 8 scrapes
            epoch_jump = (replica.clock_offset_us is not None
                          and abs(candidate - replica.clock_offset_us)
                          > 1e6)
            if (rtt_us <= replica.clock_rtt_us or epoch_jump
                    or replica.clock_age >= 8):
                replica.clock_offset_us = candidate
                replica.clock_rtt_us = rtt_us
                replica.clock_age = 0

    #: trace-cache bound per replica (events): past it the oldest
    #: half drops, mirroring the tracer's own cap policy
    TRACE_CACHE_CAP = 65536

    def _scrape_trace(self, replica: _Replica,
                      probe: GatewayClient) -> None:
        """Refresh the replica's cached Chrome-trace window (the
        dead-lane source for stitched failover traces — a SIGKILLed
        replica's spans survive only here). INCREMENTAL: resumes from
        the last ``nextSeq`` cursor, so a busy replica costs one
        delta per scrape instead of a full 64k-event serialization
        (the difference between a free health tick and the 7% tax the
        fleet-overhead bench first measured). Failures are silent:
        the healthz that just succeeded owns liveness accounting, and
        a torn trace fetch must not shadow it."""
        since = replica.trace_seq
        try:
            doc = probe.trace_events(since_seq=since)
        except Exception:
            return
        self._merge_trace_delta(replica, doc, since_seq=since)

    def _merge_trace_delta(self, replica: _Replica,
                           doc: Dict[str, Any],
                           cache_offset_us: Optional[float] = None,
                           since_seq: Optional[int] = None
                           ) -> None:
        """Fold one ``/v1/trace?since_seq=`` delta into the replica's
        cache. ``cache_offset_us`` overrides the epoch-matched offset
        snapshotted alongside the cache — the last-gasp scrape passes
        the PRE-death estimate, because ``_note_failure`` has already
        reset the live one by the time the fetch lands. ``since_seq``
        is the cursor the fetch resumed from: a delta whose base no
        longer matches the cursor lost a race to a concurrent merge
        (periodic scrape vs last-gasp both fetching the same window)
        and is dropped rather than folded twice."""
        events = doc.get("traceEvents", [])
        next_seq = doc.get("nextSeq")
        with self._lock:
            if (since_seq is not None
                    and since_seq != replica.trace_seq):
                return
            if next_seq is None:
                replica.trace_cache = events  # legacy full window
            elif next_seq < replica.trace_seq:
                # the replica's tracer lifetime changed (restart on
                # the same address): its window IS the new truth,
                # and the old process's clock estimate must not
                # correct the new process's epoch
                replica.trace_cache = events
                replica.trace_seq = int(next_seq)
                replica.clock_offset_us = None
                replica.clock_rtt_us = float("inf")
                replica.clock_age = 0
            else:
                replica.trace_cache.extend(events)
                replica.trace_seq = int(next_seq)
            if len(replica.trace_cache) > self.TRACE_CACHE_CAP:
                del replica.trace_cache[
                    :len(replica.trace_cache) // 2]
            # the cache's correcting offset is whatever the clock
            # estimate says NOW — this scrape just talked to the same
            # process the events came from, so they share an epoch
            replica.cache_offset_us = (
                cache_offset_us if cache_offset_us is not None
                else replica.clock_offset_us)
            replica.trace_cache_t = time.monotonic()

    def _last_gasp_scrape(self, replica: _Replica,
                          epoch_offset_us: Optional[float]) -> None:
        """ISSUE 11 satellite — one immediate bounded
        ``/v1/trace?since_seq=`` delta fetch the moment the breaker
        opens, BEFORE giving the replica up: the periodic trace cache
        refreshes on the METRICS tick, so a replica that died within
        one metrics interval of a request's only spans would leave a
        thin dead lane in the stitched trace (the PR 10 known gap).
        A truly SIGKILLed process refuses the connection in
        milliseconds and we give up; a replica the breaker declared
        dead for softer reasons — wedged healthz, data-plane stream
        breaks, drain-then-die — often still answers its trace
        endpoint, and its final spans land in the cache with the
        pre-death epoch's clock offset."""
        self.tracer.incr("router_last_gasp_scrapes")
        probe = self._replica_client(replica, read_timeout_s=2.0)
        since = replica.trace_seq
        try:
            doc = probe.trace_events(since_seq=since)
        except Exception:
            return  # actually dead: the cache keeps what it had
        self._merge_trace_delta(replica, doc,
                                cache_offset_us=epoch_offset_us,
                                since_seq=since)
        self.tracer.incr("router_last_gasp_hits")

    def _note_alive(self, replica: _Replica,
                    payload: Dict[str, Any]) -> None:
        with self._lock:
            replica.failures = 0
            if replica.decommissioned:
                return
            to = "draining" if payload.get("draining") else "live"
            self._breaker_instant(replica, replica.state, to)
            replica.state = to
            rid = payload.get("replica_id")
            if rid and str(rid) != replica.replica_id:
                replica.replica_id = str(rid)
                # the id→address binding rides the WAL (ISSUE 15): a
                # restarted router re-seats stable ids BEFORE any
                # scrape, so the rendezvous keyspace holds from the
                # first post-restart pick and a dead-at-recovery
                # replica's breaker opens under the SAME id its
                # restored warm beliefs are keyed by
                self._wal_defer({"t": "rep", "r": str(rid),
                                 "addr": replica.address})
            replica.queue_depth = int(payload.get("queued", 0))
            replica.active_slots = int(
                payload.get("active_slots", 0))
            replica.n_slots = int(payload.get("n_slots", 1)) or 1
            replica.prefix_tokens_reused = int(
                payload.get("prefix_tokens_reused", 0))
            replica.role = str(payload.get("role") or "any")
            replica.kv_capable = bool(payload.get("kv_transfer"))
            replica.kv_tier_entries = int(
                (payload.get("kv_tier") or {}).get("entries", 0))

    def _note_failure(self, replica: _Replica) -> None:
        """One failed health scrape OR data-plane break: the breaker
        counts both, so a dying replica is detected by whichever
        surface hits it first."""
        became_dead = False
        epoch_offset_us: Optional[float] = None
        with self._lock:
            if replica.decommissioned:
                return
            replica.failures += 1
            was = replica.state
            if (replica.failures >= self.failure_threshold
                    or was in ("dead", "half-open")):
                became_dead = was not in ("dead", "half-open")
                epoch_offset_us = replica.clock_offset_us
                self._breaker_instant(replica, was, "dead")
                replica.state = "dead"
                replica.next_probe_t = (time.monotonic()
                                        + self.probe_interval_s)
                # the clock-offset estimate described a process now
                # presumed gone: a resurrected replica on the same
                # port has a FRESH perf_counter epoch, and correcting
                # its events with the dead process's offset would
                # scatter them across the stitched timeline. Drop the
                # estimate so the first post-resurrection scrape
                # always measures anew (a merely-slow replica just
                # re-measures — harmless).
                replica.clock_offset_us = None
                replica.clock_rtt_us = float("inf")
                replica.clock_age = 0
                if was not in ("dead", "half-open"):
                    self.stats["replica_faults"] += 1
                    self.tracer.incr("router_replica_dead")
                # a dead replica's warm-key beliefs die with it: a
                # resurrected process boots cold, and keeping them
                # would skip the one transfer that could re-warm it
                self._forget_warm(replica.replica_id)
            elif was == "live":
                self._breaker_instant(replica, was, "degraded")
                replica.state = "degraded"
        self._wal_flush()  # the cold record from _forget_warm
        if became_dead and self.fleet_trace and not self._stopped:
            # last-gasp trace scrape (ISSUE 11 satellite): off the
            # caller's thread — _note_failure fires from the health
            # loop AND data-plane relays, neither of which may stall
            # on a bounded fetch against a dying peer
            threading.Thread(
                target=self._last_gasp_scrape,
                args=(replica, epoch_offset_us), daemon=True,
                name=f"last-gasp-{replica.replica_id}").start()

    # -- routing -------------------------------------------------------
    def _affinity_key(self, prompt: Sequence[int]) -> Optional[bytes]:
        """The prompt's leading block-aligned tokens as a hash key;
        None when the prompt is shorter than one block (nothing worth
        keeping warm)."""
        b = self.affinity_block_tokens
        n = (len(prompt) // b) * b
        if n < b:
            return None
        return ",".join(str(int(t)) for t in prompt[:n]).encode()

    @staticmethod
    def _rendezvous_score(key: bytes, replica_id: str) -> int:
        return int.from_bytes(
            hashlib.blake2b(key + b"|" + replica_id.encode(),
                            digest_size=8).digest(), "big")

    def _pick(self, prompt: Sequence[int], exclude: Set[str],
              tenant: str = "default"
              ) -> Tuple[_Replica, Dict[str, Any]]:
        """Choose the replica for one (re)submission and claim one
        unit of its in-flight budget (``open_entries`` — the caller
        MUST release it when the attempt ends). Returns ``(replica,
        route_info)`` where ``route_info`` carries the
        ``router.route`` span's args: ``affinity`` (bool), the
        affinity ``key`` digest, and the chosen replica's rendezvous
        ``rank`` (0 = first choice; >0 = bounded-load overflow walked
        down the ranking). Raises :class:`_AllBackedOff` when every
        candidate is parked behind a 429 hint, :class:`_NoReplica`
        when nothing can serve at all.

        Affinity is BOUNDED-LOAD: rendezvous ranks the candidates for
        the prompt's prefix key, and the pick walks DOWN the ranking
        past replicas whose router-side in-flight count has reached
        their slot count. Pure rendezvous splits K distinct keys
        binomially — with 8 concurrent streams over 2 replicas a 6/2
        split is routine, and the overflow requests would queue a full
        generation behind busy slots while the sibling idles (measured
        0.61× direct on the bench before the bound). Walking the
        ranking keeps overflow DETERMINISTIC per key (the second-
        ranked replica, not a random sibling), so a key's overflow
        cache-warms one predictable place. The bound uses the
        router's OWN live accounting (claimed at pick time under the
        lock), not the scraped load — scrapes lag a burst by a whole
        health interval."""
        now = time.monotonic()
        with self._lock:
            def usable(r, state):
                return (r.state == state and not r.decommissioned
                        and r.address not in exclude)

            def parked_until(r):
                # a replica is parked for THIS pick when either its
                # replica-wide backoff or this TENANT's backoff
                # (ISSUE 13: a tenant-scoped 429 parks only that
                # tenant's keyspace) is still running
                return max(r.backoff_until,
                           r.tenant_backoff.get(tenant, 0.0))

            live = [r for r in self._replicas if usable(r, "live")]
            ready = [r for r in live if now >= parked_until(r)]
            if not ready:
                # degraded replicas are a LAST resort: recent
                # failures, but the breaker hasn't opened
                degraded = [r for r in self._replicas
                            if usable(r, "degraded")
                            and now >= parked_until(r)]
                if degraded:
                    ready = degraded
                elif live:
                    raise _AllBackedOff(
                        min(parked_until(r) for r in live) - now)
                else:
                    raise _NoReplica()
            key = self._affinity_key(prompt)
            if key is not None:
                # role-aware ranking (ISSUE 14): ``prefill``-role
                # replicas are the warm-KV donor tier — they stay out
                # of the rendezvous ranking for stream OWNERSHIP while
                # any decode-capable replica is ready (their caches
                # warm through the transfer plane's export pulls and
                # direct short-prompt traffic), so long decode streams
                # land on the decode tier. A fleet of ``any`` roles is
                # bit-identical to the role-blind PR 9 ranking.
                pool = ([r for r in ready if r.role != "prefill"]
                        or ready)
                ranked = sorted(
                    pool, reverse=True,
                    key=lambda r: self._rendezvous_score(
                        key, r.replica_id))
                chosen = next(
                    (r for r in ranked
                     if r.open_entries < max(r.n_slots, 1)),
                    ranked[0])  # all saturated: stay sticky
                info = {
                    "affinity": True,
                    "key": hashlib.blake2b(
                        key, digest_size=4).hexdigest(),
                    "rank": ranked.index(chosen),
                }
                if info["rank"] == 0:
                    self.stats["affinity_routed"] += 1
                else:
                    self.stats["affinity_overflow"] += 1
            else:
                # short prompts (no reusable prefix): least-loaded,
                # preferring the admission-heavy (non-``decode``)
                # tier when one exists — the inverse of the affinity
                # preference above
                pool = ([r for r in ready if r.role != "decode"]
                        or ready)
                self._rr += 1
                order = (self._rr + i for i in range(len(pool)))
                # live in-flight count first (exact, claimed under
                # this very lock), scraped load as the tiebreak,
                # rotation last
                chosen = min(
                    zip(pool, order),
                    key=lambda p: (p[0].open_entries,
                                   p[0].queue_depth
                                   + p[0].active_slots,
                                   p[1] % len(pool)))[0]
                info = {"affinity": False, "key": None, "rank": None}
                self.stats["load_routed"] += 1
            chosen.requests_routed += 1
            chosen.open_entries += 1
            return chosen, info

    # -- KV transfer plane (ISSUE 14) ----------------------------------
    def _note_warm(self, key: bytes, replica_id: str) -> None:
        """Record the belief that ``replica_id`` is (about to be)
        warm for ``key`` — set when an affinity request routes there
        (its admission inserts the prefix) and when an import lands.
        A belief, not a contract: a stale entry (replica restarted,
        trie evicted the key) costs one recompute, never
        correctness. Caller holds the lock."""
        warm = self._warm.get(key)
        if warm is None:
            warm = self._warm[key] = {}
            while len(self._warm) > self._warm_cap:
                self._warm.pop(next(iter(self._warm)))
        warm[replica_id] = time.monotonic()
        self._wal_defer({"t": "warm",
                         "k": key.decode("ascii", "replace"),
                         "r": replica_id,
                         "wall": round(time.time(), 3)})

    def _forget_warm(self, replica_id: str) -> None:
        """Drop every warm belief about a replica the breaker just
        declared dead: a resurrected process boots cold, and a stale
        belief would skip the one transfer that could re-warm it.
        Caller holds the lock."""
        for warm in self._warm.values():
            warm.pop(replica_id, None)
        self._wal_defer({"t": "cold", "r": replica_id})

    #: per-hop read bound for transfer traffic: the plane only buys
    #: admission latency, so a slow donor must cost LESS than the
    #: recompute it would have saved — a wedged peer times out in
    #: seconds, not the data-plane's stream budget
    KV_TRANSFER_TIMEOUT_S = 3.0

    def _fetch_kv_payload(self, donor: _Replica,
                          prompt: List[int]) -> Optional[bytes]:
        """Pull the donor's exported prefix (None = nothing cached).
        Factored out as the soak's fault-injection seam: truncating
        the returned payload models a torn transfer."""
        return self._replica_client(
            donor,
            read_timeout_s=self.KV_TRANSFER_TIMEOUT_S).kv_export(
                prompt)

    def _push_kv_payload(self, receiver_address: str,
                         payload: bytes) -> Dict[str, Any]:
        """Push one payload into the receiver (by address — upgrade
        warmup targets replicas not yet registered). The soak's
        second fault seam."""
        return GatewayClient(
            receiver_address,
            connect_timeout_s=self.replica_connect_timeout_s,
            read_timeout_s=self.KV_TRANSFER_TIMEOUT_S).kv_import(
                payload)

    def _maybe_kv_transfer(self, entry: _JournalEntry,
                           receiver: _Replica,
                           forward_ping=lambda: None,
                           rank: Optional[int] = None) -> None:
        """The warm-import hook (ISSUE 14 tentpole): called after
        ``_pick`` and before the attempt, when the chosen replica is
        believed COLD for the prompt's affinity key — an affinity
        miss (bounded-load overflow), a failover replay landing on a
        survivor, or plain cache churn. Pulls the warm peer's export
        and imports it into the receiver so the admission that
        follows splices instead of recomputing. EVERY failure mode —
        no donor, transfer fault, decline — falls through silently:
        the attempt's full-prompt recompute already covers
        correctness (the PR 9 discipline), the transfer only buys
        admission latency."""
        key = self._affinity_key(entry.prompt)
        if key is None:
            return
        with self._lock:
            warm = self._warm.get(key, {})
            wanted = (receiver.kv_capable
                      and receiver.replica_id not in warm)
            donors: List[_Replica] = []
            if wanted:
                # live/draining donors only: a DEGRADED peer (recent
                # failures, breaker not yet open) is exactly the one
                # whose export would eat the transfer timeout for
                # nothing — recompute is cheaper than probing it
                cands = [r for r in self._replicas
                         if r.kv_capable and not r.decommissioned
                         and r.address != receiver.address
                         and r.state in ("live", "draining")]
                # believed-warm peers first (newest belief first);
                # then the key's rendezvous-top capable replica (its
                # designated owner — warm whenever the key has seen
                # traffic, even if the belief map forgot)
                donors = sorted(
                    (r for r in cands if r.replica_id in warm),
                    key=lambda r: -warm[r.replica_id])
                # tier-warm replicas next (ISSUE 17): a replica whose
                # spill tier holds payloads serves exports straight
                # from host DRAM/disk with zero device work — a
                # strictly better bet than a believed-cold replica,
                # and the export falls through to the tier even when
                # the TRIE evicted the key (the exact case the
                # belief map cannot see)
                donors += sorted(
                    (r for r in cands
                     if r.kv_tier_entries > 0 and r not in donors),
                    key=lambda r: -r.kv_tier_entries)
                # the rendezvous-top fallback (the key's designated
                # owner, warm whenever the key has seen traffic even
                # if the belief map forgot) only makes sense when the
                # RECEIVER is not that owner: on a rank-0 pick with
                # no warm beliefs, nobody else can be warm — probing
                # the second-ranked replica would pay a guaranteed
                # 404 round-trip per first-touch key
                if rank is None or rank > 0:
                    ranked = sorted(
                        cands, reverse=True,
                        key=lambda r: self._rendezvous_score(
                            key, r.replica_id))
                    for r in ranked[:1]:
                        if r not in donors:
                            donors.append(r)
            # the attempt that follows warms the receiver either way
            # (import, or the admission's own insert)
            self._note_warm(key, receiver.replica_id)
            if wanted and not donors:
                self.stats["kv_transfer_declined"] += 1
        self._wal_flush()  # the warm note deferred under the lock
        if not wanted or not donors:
            return
        t0_us = self._now_us()
        landed = None
        for donor in donors[:2]:
            try:
                # keepalive before each bounded hop: the client sees
                # at most one KV_TRANSFER_TIMEOUT_S of silence, never
                # the whole donor walk
                forward_ping()
                payload = self._fetch_kv_payload(donor, entry.prompt)
                if payload is None:
                    continue  # donor turned out cold: next candidate
                forward_ping()
                out = self._push_kv_payload(receiver.address, payload)
            except Exception:
                # torn payload, timeout, 400 from a geometry
                # mismatch, receiver died — all the same outcome:
                # count it, recompute covers it
                with self._lock:
                    self.stats["kv_transfer_failures"] += 1
                self.tracer.incr("router_kv_transfer_failures")
                continue
            if out.get("imported"):
                landed = (donor, out, len(payload))
                break
            # soft decline (already warm / pool pressure): done —
            # "already warm" needs no second donor
            if out.get("reason") == "already_warm":
                landed = (donor, out, len(payload))
                break
        dur_us = max(self._now_us() - t0_us, 0.0)
        if landed is None:
            return
        donor, out, nbytes = landed
        self._kv_transfer_hist.observe(dur_us / 1e6)
        with self._lock:
            if out.get("imported"):
                self.stats["kv_transfers"] += 1
                self.stats["kv_transferred_tokens"] += int(
                    out.get("tokens") or 0)
            entry.note(self._now(),
                       f"kv_import:{donor.replica_id}"
                       f":{out.get('reason')}")
        if out.get("imported"):
            self.tracer.incr("router_kv_transfers")
        if hasattr(self.tracer, "complete"):
            self.tracer.complete(
                "router.kv_transfer", t0_us, dur_us,
                rid=entry.rid, trace=entry.trace,
                donor=donor.replica_id,
                receiver=receiver.replica_id,
                imported=bool(out.get("imported")),
                reason=out.get("reason"),
                tokens=out.get("tokens"), blocks=out.get("blocks"),
                bytes=nbytes)

    def warm_transfer(self, receiver_address: str,
                      prompts: Sequence[Sequence[int]],
                      receiver_id: Optional[str] = None
                      ) -> Dict[str, Any]:
        """Upgrade-warmup transfer (ISSUE 14): ship the fleet's warm
        prefixes for ``prompts`` into a BOOTING replica (addressed
        directly — it is not registered yet) instead of regenerating
        them (the PR 11 ``/v1/warmup`` handshake). Returns
        ``{"imported", "attempted", "failed", "cold"}`` where
        ``cold`` lists the prompts that could not be shipped — the
        controller falls back to greedy warmup generation for
        exactly those. ``receiver_id`` (the stable replica id the
        receiver will register under — the controller knows it)
        records each shipped key in the warm-belief map, so the
        receiver's first affinity request does not pay a redundant
        export+import just to hear ``already_warm``."""
        imported = attempted = failed = 0
        cold: List[List[int]] = []
        for prompt in prompts:
            prompt = [int(t) for t in prompt]
            key = self._affinity_key(prompt)
            with self._lock:
                warm = self._warm.get(key, {}) if key else {}
                cands = [r for r in self._replicas
                         if r.kv_capable and not r.decommissioned
                         and r.address != receiver_address.split(
                             "://", 1)[-1]
                         and r.state in ("live", "degraded",
                                         "draining")]
                donors = sorted(
                    (r for r in cands if r.replica_id in warm),
                    key=lambda r: -warm[r.replica_id])
                # tier-warm before cold (ISSUE 17): same ladder as
                # the affinity-miss pick — the spill tier answers
                # exports the trie already evicted
                donors += sorted(
                    (r for r in cands
                     if r.kv_tier_entries > 0 and r not in donors),
                    key=lambda r: -r.kv_tier_entries)
                donors += [r for r in cands if r not in donors]
            ok = False
            for donor in donors[:3]:
                attempted += 1
                try:
                    payload = self._fetch_kv_payload(donor, prompt)
                    if payload is None:
                        continue
                    out = self._push_kv_payload(receiver_address,
                                                payload)
                except Exception:
                    failed += 1
                    continue
                if out.get("imported") or out.get(
                        "reason") == "already_warm":
                    ok = True
                    imported += int(bool(out.get("imported")))
                    if receiver_id is not None and key is not None:
                        with self._lock:
                            self._note_warm(key, str(receiver_id))
                    break
            if not ok:
                cold.append(prompt)
        with self._lock:
            self.stats["kv_transfers"] += imported
            self.stats["kv_transfer_failures"] += failed
        self._wal_flush()  # warm notes deferred under the lock
        return {"imported": imported, "attempted": attempted,
                "failed": failed, "cold": cold}

    # -- write-ahead journal (ISSUE 15 tentpole) -----------------------
    def _wal_append(self, record: Dict[str, Any]) -> None:
        """Append one record to the crash ledger (no-op without a
        ``journal_path``). A failing disk must not take the data
        plane down with it: the error is counted and the stream keeps
        relaying — the operator sees ``router_wal_errors`` climb and
        knows recovery coverage is degrading."""
        wal = self._wal
        if wal is None:
            return
        try:
            wal.append(record)
        except (OSError, ValueError):
            with self._lock:
                self.stats["wal_errors"] += 1
            self.tracer.incr("router_wal_errors")

    def _wal_defer(self, record: Dict[str, Any]) -> None:
        """Queue one record from a LOCK-HELD site (warm/cold/rep
        notes fire inside ``self._lock``): file I/O must not run
        under the router's global lock, so the record is flushed by
        the nearest unlocked seam (:meth:`_wal_flush` — the caller's
        epilogue, or the health tick). These record types are
        advisory state (beliefs, bindings) folded last-wins, so the
        flush latency costs recovery fidelity only in the window a
        crash would anyway."""
        if self._wal is not None:
            self._wal_deferred.append(record)

    def _wal_flush(self) -> None:
        """Append every deferred record (caller must NOT hold the
        router lock). Flushers fully serialize on their own lock —
        two concurrent flushers interleaving their swapped batches
        could otherwise append a warm note AFTER the cold record
        that superseded it, and recovery's last-wins fold would
        resurrect a dead replica's belief."""
        if self._wal is None:
            return
        with self._wal_flush_lock:
            with self._lock:
                if not self._wal_deferred:
                    return
                pending, self._wal_deferred = self._wal_deferred, []
            for record in pending:
                self._wal_append(record)

    def _wal_snapshot(self) -> Dict[str, Any]:
        """The compaction snapshot: every OPEN entry (the crash
        ledger proper — never dropped), the most recent
        ``wal_retain_done`` terminals (resume/poll breadcrumbs),
        refreshed token-bucket levels, and the warm-belief map with
        stamps converted to wall time."""
        wall = time.time()
        mono = time.monotonic()
        with self._lock:
            entries = []
            done_kept = 0
            for rid in sorted(self._journal, reverse=True):
                e = self._journal[rid]
                done = e.done.is_set()
                if done:
                    if done_kept >= self.wal_retain_done:
                        continue
                    done_kept += 1
                entries.append({
                    "rid": e.rid, "prompt": e.prompt,
                    "params": e.params,
                    "tokens": list(e.tokens),
                    "replica": e.replica_address, "done": done,
                    "finish_reason": (e.result or {}).get(
                        "finish_reason"),
                    "status": (e.result or {}).get("status"),
                    "submit_wall": round(
                        wall - (self._now() - e.submit_t), 3),
                })
            buckets = {}
            for tenant, b in self._buckets.items():
                b.try_take(0.0)  # refresh the level to NOW
                buckets[tenant] = {
                    "tokens": round(b.tokens, 6),
                    "capacity": b.capacity, "rate": b.rate,
                    "wall": wall}
            warm = {
                k.decode("ascii", "replace"): {
                    r: round(wall - (mono - s), 3)
                    for r, s in v.items()}
                for k, v in self._warm.items() if v}
            return {"next_rid": self._rid_hwm, "wall": wall,
                    "entries": entries, "buckets": buckets,
                    "warm": warm,
                    "replicas": {r.address: r.replica_id
                                 for r in self._replicas
                                 if r.replica_id != r.address}}

    def _compact_wal(self) -> None:
        """Fold the live state into one snapshot record and rewrite
        the file (bounded WAL — the on-disk twin of ``journal_cap``).
        One compactor at a time; the microsecond window between
        snapshot and rewrite can drop a concurrent progress append,
        which is safe by construction: greedy replay regenerates the
        same tokens and the client's Last-Event-ID dedups delivery."""
        wal = self._wal
        if wal is None:
            return
        with self._lock:
            if self._compacting:
                return
            self._compacting = True
        try:
            # arm the carry-over buffer FIRST: any record appended
            # while the snapshot is being built rides into the
            # rewritten file verbatim (idempotent folds absorb the
            # possible duplication) — the rewrite can lose nothing
            wal.begin_compaction()
            wal.compact(self._wal_snapshot())
            with self._lock:
                self.stats["wal_compactions"] += 1
            self.tracer.incr("router_wal_compactions")
        except (OSError, ValueError):
            with self._lock:
                self.stats["wal_errors"] += 1
            self.tracer.incr("router_wal_errors")
        finally:
            with self._lock:
                self._compacting = False

    def _restore_from_wal(self, state: Dict[str, Any]) -> None:
        """Rebuild the in-memory journal from a recovered WAL fold
        (constructor path, before the HTTP service exists). Done
        entries come back poll/resume-servable; open entries queue
        for the replay pass :meth:`start` launches; bucket levels and
        warm beliefs come back as if the crash were a long GC pause."""
        self._recover_t0_us = self._now_us()
        now = self._now()
        wall = time.time()
        mono = time.monotonic()
        self._rid_hwm = int(state["next_rid"])
        self._rids = itertools.count(self._rid_hwm)
        # re-seat the replicas' stable ids before any health scrape:
        # the rendezvous keyspace holds from the first pick, and a
        # replica that died WITH the old router opens its breaker
        # under the same id its restored warm beliefs are keyed by
        for replica in self._replicas:
            rid_known = state["replica_ids"].get(replica.address)
            if rid_known:
                replica.replica_id = rid_known
        for rid, rec in sorted(state["entries"].items()):
            # the persisted submit WALL time folds back onto the new
            # process's monotonic timeline, so a recovered entry's
            # age (journal_audit, history, e2e) spans the crash
            # instead of resetting to zero
            submit_t = now
            if rec.get("submit_wall") is not None:
                submit_t = now - max(
                    0.0, wall - float(rec["submit_wall"]))
            entry = _JournalEntry(rid, rec["prompt"],
                                  dict(rec["params"]), submit_t)
            entry.recovered = True
            entry.tokens = list(rec["tokens"])
            entry.replica_address = rec.get("replica")
            if self.fleet_trace:
                entry.trace = f"r{rid}"
            entry.note(now, "recovered")
            if rec["done"]:
                entry.result = {
                    "id": rid, "tokens": list(entry.tokens),
                    "finish_reason": rec.get("finish_reason"),
                    "status": rec.get("status") or 200,
                    "prompt_len": len(entry.prompt),
                    "replays": 0, "recovered": True}
                if entry.trace:
                    entry.result["trace"] = entry.trace
                entry.done_t = now
                entry.done.set()
            else:
                self._recovery_open.append(entry)
                self.stats["recovered_open"] += 1
            self._journal[rid] = entry
        self.stats["recovered_entries"] = len(state["entries"])
        # warm-belief recovery (ISSUE 15 satellite): wall stamps back
        # to the monotonic clock `_note_warm` speaks. A replica whose
        # breaker opens during recovery drops these through the same
        # `_forget_warm` a live death fires — a resurrected replica
        # still boots cold.
        for k, beliefs in state["warm"].items():
            self._warm[k.encode()] = {
                r: mono - max(0.0, wall - w)
                for r, w in beliefs.items()}
        # token-bucket recovery (ISSUE 15 satellite): levels refill
        # only for the real wall-clock downtime — a flooded tenant is
        # still throttled the moment the restarted router answers
        self._recovered_buckets = dict(state["buckets"])
        self._arm_recovered_buckets()
        self._recover_pending = len(self._recovery_open)

    def _arm_recovered_buckets(self) -> None:
        if self.tenants is None or not self._recovered_buckets:
            return
        from deeplearning4j_tpu.serving.tenancy import TokenBucket

        wall = time.time()
        for tenant, saved in self._recovered_buckets.items():
            spec = self.tenants.spec_of(tenant)
            if spec.rate_rps is None:
                continue
            bucket = TokenBucket(spec.rate_rps, spec.burst)
            bucket.restore_level(
                saved.get("tokens", 0.0),
                age_s=max(0.0, wall - saved.get("wall", wall)))
            self._buckets[tenant] = bucket

    def _recover_entry(self, entry: _JournalEntry) -> None:
        """Replay one recovered OPEN entry to its terminal. No client
        is attached — the emit is a no-op, because `_relay_tokens`
        already extends ``entry.tokens`` (what resume followers and
        the final terminal serve) and journals the progress."""
        try:
            if entry.temperature > 0 and entry.tokens:
                # the PR 3/5 contract across the restart: a redrawn
                # sampling stream cannot splice onto the streamed
                # prefix — terminate ``fault`` with the partials
                entry.note(self._now(), "sampling_fault")
                self._finish(entry, self._fault_terminal(entry))
            else:
                self._run_entry(entry, lambda tokens: None,
                                lambda: None)
                with self._lock:
                    self.stats["recovered_replayed"] += 1
        except Exception:
            if not entry.done.is_set():
                self._finish(entry, self._fault_terminal(entry))
        finally:
            with self._lock:
                self._recover_pending -= 1
                last = self._recover_pending <= 0
            if last:
                self._emit_recover_span()

    def _emit_recover_span(self) -> None:
        """The ``router.recover`` span (ISSUE 15): one lane-0 span on
        the stitched trace covering WAL restore through the last
        recovered entry's terminal — a restart reads on the fleet
        timeline exactly like a failover reads as ``router.replay``."""
        t0 = self._recover_t0_us
        if t0 is None:
            return
        self._recover_t0_us = None
        now = self._now_us()
        if hasattr(self.tracer, "complete"):
            self.tracer.complete(
                "router.recover", t0, max(now - t0, 0.0),
                entries=self.stats["recovered_entries"],
                open=self.stats["recovered_open"],
                replayed=self.stats["recovered_replayed"],
                buckets=len(self._recovered_buckets),
                warm_keys=len(self._warm))
        self.tracer.incr("router_recoveries")

    # -- journal -------------------------------------------------------
    def _journal_entry(self, prompt: List[int],
                       params: Dict[str, Any]) -> _JournalEntry:
        with self._lock:
            rid = next(self._rids)
            self._rid_hwm = rid + 1
            entry = _JournalEntry(rid, prompt, params, self._now())
            if self.fleet_trace:
                # the fleet-level identity (ISSUE 10): every hop —
                # router spans, gateway, engine flight recorder —
                # stamps this id, so one grep of a stitched trace
                # yields the request's whole cross-process story
                entry.trace = f"r{rid}"
            entry.note(self._now(), "submitted")
            self._journal[rid] = entry
            # bounded journal: evict oldest DONE entries past the cap
            # (open entries are never evicted — they are the crash
            # ledger)
            if len(self._journal) > self.journal_cap:
                for old_rid in list(self._journal):
                    if len(self._journal) <= self.journal_cap:
                        break
                    old = self._journal[old_rid]
                    if old.done.is_set():
                        del self._journal[old_rid]
            self.stats["requests"] += 1
            self.tracer.incr("router_requests")
        # write-ahead (ISSUE 15): the open record lands BEFORE the
        # first routing attempt, so a crash a microsecond later still
        # recovers the request
        self._wal_append({"t": "open", "rid": rid,
                          "prompt": entry.prompt,
                          "params": entry.params,
                          "wall": round(time.time(), 3)})
        return entry

    def journal_audit(self) -> Dict[str, Any]:
        """The chaos-soak ledger: per-entry delivery accounting. A
        LOST request is an entry that never reached a terminal; a
        DOUBLE DELIVERY would show as a high-water mark short of the
        token count (some token went out twice without advancing the
        mark — structurally impossible through ``_relay_tokens``, and
        audited anyway)."""
        with self._lock:
            open_rids = [e.rid for e in self._journal.values()
                         if not e.done.is_set()]
            replayed = [e.rid for e in self._journal.values()
                        if e.replays > 0]
            return {
                "entries": len(self._journal),
                "open": open_rids,
                "replayed": replayed,
                "lost": [e.rid for e in self._journal.values()
                         if e.done.is_set() and e.result is None],
            }

    # -- the proxy / replay core ---------------------------------------
    def _result_of(self, entry: _JournalEntry,
                   terminal: Dict[str, Any]) -> Dict[str, Any]:
        """Client-facing terminal: the replica's result re-keyed to
        the ROUTER's request id, tokens replaced by the journal's
        high-water view (identical for healthy terminals — asserted
        by the dedup walk — and the authoritative partial list for
        faults), plus the router's replay accounting."""
        out = dict(terminal)
        out.pop("done", None)
        out["id"] = entry.rid
        out["tokens"] = list(entry.tokens)
        out["replays"] = entry.replays
        if entry.trace:
            out["trace"] = entry.trace
        return out

    def _fault_terminal(self, entry: _JournalEntry,
                        reason: str = "fault",
                        status: int = 500) -> Dict[str, Any]:
        out = {"id": entry.rid, "tokens": list(entry.tokens),
               "finish_reason": reason, "status": status,
               "prompt_len": len(entry.prompt),
               "replays": entry.replays}
        if entry.trace:
            out["trace"] = entry.trace
        return out

    def _finish(self, entry: _JournalEntry,
                result: Dict[str, Any]) -> Dict[str, Any]:
        self._close_replay_window(entry, outcome="terminal")
        with self._lock:
            entry.result = result
            entry.done_t = self._now()
            entry.note(entry.done_t,
                       f"terminal:{result.get('finish_reason')}")
            entry.done.set()
            if result.get("finish_reason") == "fault":
                self.stats["request_faults"] += 1
                self.tracer.incr("router_request_faults")
        self._wal_append({"t": "done", "rid": entry.rid,
                          "reason": result.get("finish_reason"),
                          "status": result.get("status"),
                          "n": len(entry.tokens)})
        if self._wal is not None and self._wal.needs_compaction():
            # off-thread: the relay that happened to trip the
            # threshold must not pay the snapshot + rewrite + fsyncs
            # before its client sees the terminal (_compacting keeps
            # it single-flight)
            threading.Thread(target=self._compact_wal, daemon=True,
                             name="router-wal-compact").start()
        return result

    def _open_replay_window(self, entry: _JournalEntry,
                            from_replica: str) -> None:
        """The stream broke and a replay begins: anchor the
        ``router.replay`` span (and the ``router_replay_gap_s``
        observation) at the BREAK, not at the resubmit — the client's
        dead air starts now."""
        if entry.replay_t0_us is None:
            entry.replay_t0_us = self._now_us()
            entry.replay_hwm = len(entry.tokens)
            entry.replay_from = from_replica

    def _close_replay_window(self, entry: _JournalEntry,
                             outcome: str,
                             overlap_ok: bool = True) -> None:
        """First fresh token after a replay (or the terminal, for a
        replay that only had its tail left / diverged / faulted):
        emit the bridging ``router.replay`` span — break to first
        post-replay delivery, the exact failover gap the client
        experienced — and feed the replay-gap histogram."""
        t0 = entry.replay_t0_us
        if t0 is None:
            return
        entry.replay_t0_us = None
        now = self._now_us()
        gap_s = max(now - t0, 0.0) / 1e6
        self._replay_gap.observe(gap_s)
        if hasattr(self.tracer, "complete"):
            self.tracer.complete(
                "router.replay", t0, max(now - t0, 0.0),
                rid=entry.rid, trace=entry.trace,
                high_water=entry.replay_hwm,
                overlap_ok=overlap_ok, outcome=outcome,
                from_replica=entry.replay_from,
                to_replica=(entry.replica_address or ""),
                replay=entry.replays)

    def _relay_tokens(self, entry: _JournalEntry, tokens: List[int],
                      seen: int) -> Tuple[int, List[int]]:
        """Advance one attempt's stream position through a delta.
        Tokens at positions the client already has are CHECKED against
        the journal (greedy replay must regenerate the exact streamed
        prefix) and dropped; tokens past the high-water mark extend
        the journal and are returned for delivery. This is the
        cross-process version of the engine's ``delta_sent`` dedup."""
        fresh: List[int] = []
        for t in tokens:
            t = int(t)
            seen += 1
            if seen <= len(entry.tokens):
                if t != entry.tokens[seen - 1]:
                    raise _ReplayDiverged(
                        f"request {entry.rid}: replay token {t} at "
                        f"position {seen - 1} != streamed "
                        f"{entry.tokens[seen - 1]}")
            else:
                entry.tokens.append(t)
                fresh.append(t)
        if fresh:
            # write-ahead: the high-water mark advances on disk
            # BEFORE the tokens go out to the client, so a crash
            # between the two can only under-count what was delivered
            # — replay then re-offers tokens the client dedups by
            # Last-Event-ID, and never loses ones it journaled.
            # ``at`` makes the record position-addressed (idempotent
            # under compaction carry-over duplication).
            self._wal_append({"t": "prog", "rid": entry.rid,
                              "at": len(entry.tokens) - len(fresh),
                              "toks": fresh})
        return seen, fresh

    def _ping_sleep(self, total_s: float, forward_ping) -> None:
        """Sleep ``total_s`` in ``keepalive_s`` slices, forwarding a
        keep-alive to the client before each slice — a replay wait
        must not look like a dead connection."""
        end = time.monotonic() + total_s
        while True:
            forward_ping()
            left = end - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(left, self.keepalive_s))

    def _attempt(self, entry: _JournalEntry, replica: _Replica,
                 client: GatewayClient, route_info: Dict[str, Any],
                 emit, forward_ping, attempt_no: int = 0,
                 wait_t0_us: Optional[float] = None
                 ) -> Tuple[Optional[Dict[str, Any]], bool]:
        """One streaming attempt against one replica. Returns
        ``(terminal, diverged)``; ``terminal is None`` means the
        stream ended WITHOUT a terminal event (replica death or drain
        handback — the replay policy in ``_run_entry`` decides what
        that means). Raises :class:`_RouteAround` when the attempt
        never started streaming (submit rejected/unreachable — try a
        sibling, no replay charged) and :class:`_ClientGone` when the
        router's own client vanished mid-relay."""
        by_affinity = bool(route_info.get("affinity"))
        params = entry.params
        if self.fleet_trace and entry.trace:
            # trace id + PER-ATTEMPT span id: a failover's two
            # attempts are two spans of one trace, so the replica
            # each served knows which chapter it was
            params = dict(params,
                          trace=f"{entry.trace}/a{attempt_no}")
        try:
            stream = client.stream(entry.prompt, **params)
        except GatewayError as e:
            if e.status == 429:
                # backpressure, not failure — and the SCOPE of the
                # park follows the reply (ISSUE 13): a reply naming
                # a tenant ("tenant queue full" from a
                # tenancy-enabled replica) parks only that TENANT's
                # keyspace on this replica, so an at-SLO victim keeps
                # routing here while the flooder waits out its own
                # hint; a tenant-blind 429 (global queue full) parks
                # the whole replica as before
                hinted = (e.payload or {}).get("tenant")
                with self._lock:
                    until = (time.monotonic()
                             + (e.retry_after_s or 1))
                    if hinted:
                        replica.tenant_backoff[str(hinted)] = until
                        # bounded map: drop expired parks once it
                        # grows past a handful of tenants
                        if len(replica.tenant_backoff) > 64:
                            now_m = time.monotonic()
                            replica.tenant_backoff = {
                                t: u for t, u
                                in replica.tenant_backoff.items()
                                if u > now_m}
                        self.stats["tenant_backoffs"] += 1
                        self.tracer.incr(
                            f'router_tenant_backoff{{tenant='
                            f'"{hinted}"}}')
                    else:
                        replica.backoff_until = until
                    self.stats["rerouted_429"] += 1
                    self.tracer.incr("router_rerouted_429")
                raise _RouteAround() from e
            if e.status == 503:
                # draining/closed: the health loop will catch up;
                # route around it meanwhile
                raise _RouteAround() from e
            # a deterministic rejection (400 bad params): replaying
            # elsewhere would just repeat it — relay to the client
            raise _RouteAround(deterministic={
                "id": entry.rid, "tokens": [],
                "finish_reason": "error", "status": e.status,
                "error": e.payload.get("error"),
                "replays": entry.replays}) from e
        except RETRYABLE_ERRORS as e:
            # could not even submit: breaker event, try a sibling
            self._note_failure(replica)
            raise _RouteAround() from e
        with self._lock:
            entry.replica_address = replica.address
            entry.replica_rid = stream.id
            entry.note(self._now(),
                       f"routed:{replica.replica_id}"
                       f"{':affinity' if by_affinity else ''}"
                       f":rid={stream.id}")
        # the ADDRESS, not the id: recovery folds this into
        # ``entry.replica_address`` (the same field the compaction
        # snapshot persists) — the id↔address binding has its own
        # ``rep`` records
        self._wal_append({"t": "route", "rid": entry.rid,
                          "replica": replica.address})
        if (self.fleet_trace and wait_t0_us is not None
                and hasattr(self.tracer, "complete")):
            # pick + backoff + submit handshake: everything between
            # "this attempt became runnable" and "the replica accepted
            # the stream" — the router-side analogue of the engine's
            # queue_wait phase
            now_us = self._now_us()
            self.tracer.complete(
                "router.queue_wait", wait_t0_us,
                max(now_us - wait_t0_us, 0.0), rid=entry.rid,
                trace=entry.trace, attempt=attempt_no,
                replica=replica.replica_id)
        terminal: Optional[Dict[str, Any]] = None
        diverged = False
        seen = 0
        try:
            if entry.cancelled and stream.id is not None:
                # cancel raced the submit: forward it now that the
                # replica-side id exists
                with contextlib.suppress(Exception):
                    client.cancel(stream.id)
            for kind, event in stream.raw_events():
                if kind == "ping":
                    forward_ping()
                    continue
                toks = event.get("tokens")
                if toks and not event.get("done"):
                    seen, fresh = self._relay_tokens(
                        entry, toks, seen)
                    if fresh:
                        emit(fresh)
                        # the first fresh token after a failover ends
                        # the client-visible replay gap: the dedup
                        # walk verified the regenerated prefix, new
                        # content is flowing again
                        self._close_replay_window(
                            entry, outcome="fresh_token")
                    continue
                if event.get("done"):
                    # the terminal may carry committed tokens the
                    # per-delta events did not (flushed tail) — run
                    # them through the same dedup before trusting it
                    if toks and len(toks) >= len(entry.tokens):
                        _, fresh = self._relay_tokens(
                            entry, toks, 0)
                        if fresh:
                            emit(fresh)
                    terminal = event
                    break
        except _ClientGone:
            raise  # _stream_response cancels; not a replica event
        except _ReplayDiverged:
            diverged = True
        except (*RETRYABLE_ERRORS, ValueError):
            # mid-stream death (or a torn frame from a dying peer):
            # the replay policy decides
            terminal = None
        finally:
            stream.close()
        return terminal, diverged

    def _run_entry(self, entry: _JournalEntry, emit,
                   forward_ping) -> Dict[str, Any]:
        """Drive one journaled request to its terminal: route, relay,
        and — on replica death or drain handback — replay onto a
        survivor with high-water dedup. ``emit(tokens)`` delivers
        fresh tokens to the client (SSE event or blocking
        accumulator); ``forward_ping()`` relays replica keep-alives.
        Returns the client-facing terminal dict (also journaled)."""
        exclude: Set[str] = set()
        attempts = 0
        # router-side queue-wait anchor: submit (or the previous
        # attempt's break) -> the replica accepting the stream
        wait_t0_us = self._now_us() if self.fleet_trace else None
        while True:
            if entry.cancelled:
                return self._finish(
                    entry, self._fault_terminal(
                        entry, "cancelled", 499))
            attempts += 1
            if attempts > self.max_replays + 2 * len(self._replicas):
                # absolute bound on the route-submit loop: repeated
                # submit-time connection failures (distinct from
                # replays, which count mid-stream deaths)
                return self._finish(entry,
                                    self._fault_terminal(entry))
            t_route_us = self._now_us() if self.fleet_trace else None
            try:
                replica, route_info = self._pick(entry.prompt,
                                                 exclude,
                                                 tenant=entry.tenant)
            except _AllBackedOff as e:
                if not entry.tokens:
                    wait = max(1, int(e.wait_s + 0.999))
                    shed = {
                        "id": entry.rid, "tokens": [],
                        "finish_reason": "shed", "status": 429,
                        "prompt_len": len(entry.prompt),
                        "retry_after_s": wait,
                        "replays": entry.replays}
                    if self.tenants is not None:
                        # the wait was computed over THIS tenant's
                        # parks (ISSUE 13) — name it, so the caller
                        # knows whose hint this is
                        shed["tenant"] = entry.tenant
                    return self._finish(entry, shed)
                # mid-replay with streamed tokens: waiting is better
                # than faulting — the backoff hints are short. The
                # wait is pinged at keepalive_s cadence: the CLIENT
                # connection sees no replica traffic during this gap,
                # and a silent gap longer than its read timeout would
                # drop a request that was about to complete
                self._ping_sleep(min(max(e.wait_s, 0.05), 2.0),
                                 forward_ping)
                exclude.clear()
                continue
            except _NoReplica:
                if exclude:
                    # every healthy replica is excluded from THIS
                    # request (each failed it once): clear and let the
                    # state machine filter instead
                    exclude.clear()
                    continue
                return self._finish(entry, {
                    "id": entry.rid, "tokens": list(entry.tokens),
                    "finish_reason": ("fault" if entry.tokens
                                      else "shed"),
                    "status": (500 if entry.tokens else 503),
                    "prompt_len": len(entry.prompt),
                    "replays": entry.replays})
            entry.affinity = (entry.affinity
                              or bool(route_info.get("affinity")))
            if (self.fleet_trace and t_route_us is not None
                    and hasattr(self.tracer, "complete")):
                # the routing decision itself, with the evidence:
                # affinity key digest + the chosen replica's
                # rendezvous rank (>0 = bounded-load overflow)
                now_us = self._now_us()
                self.tracer.complete(
                    "router.route", t_route_us,
                    max(now_us - t_route_us, 0.0), rid=entry.rid,
                    trace=entry.trace, attempt=attempts,
                    replica=replica.replica_id,
                    affinity=route_info.get("affinity"),
                    affinity_key=route_info.get("key"),
                    rendezvous_rank=route_info.get("rank"))
            if self.kv_transfer and route_info.get("affinity"):
                # warm import BEFORE the attempt (ISSUE 14): an
                # affinity miss / failover replay whose receiver is
                # cold pulls the warm peer's KV so the admission
                # splices instead of recomputing; every transfer
                # fault falls through to the recompute the attempt
                # does anyway
                self._maybe_kv_transfer(
                    entry, replica, forward_ping=forward_ping,
                    rank=route_info.get("rank"))
            client = self._replica_client(replica)
            try:
                # _pick claimed one unit of the replica's in-flight
                # budget; the outer finally releases it however this
                # attempt ends (bounded-load affinity reads it live)
                terminal, diverged = self._attempt(
                    entry, replica, client, route_info, emit,
                    forward_ping, attempt_no=attempts,
                    wait_t0_us=wait_t0_us)
            except _RouteAround as ra:
                exclude.add(replica.address)
                if ra.deterministic is not None:
                    return self._finish(entry, ra.deterministic)
                continue
            finally:
                with self._lock:
                    replica.open_entries -= 1
            if terminal is not None:
                return self._finish(entry,
                                    self._result_of(entry, terminal))
            if diverged:
                entry.note(self._now(), "replay_diverged")
                # the overlap check FAILED: the bridging replay span
                # records it (a silent splice is the one thing the
                # dedup walk exists to prevent)
                self._close_replay_window(entry, outcome="diverged",
                                          overlap_ok=False)
                return self._finish(entry,
                                    self._fault_terminal(entry))
            # ---- the stream ended WITHOUT a terminal ---------------
            if entry.cancelled:
                return self._finish(
                    entry, self._fault_terminal(
                        entry, "cancelled", 499))
            draining = replica.state in ("draining", "dead")
            if not draining:
                # unannounced death: charge the breaker so routing
                # reacts before the next health tick
                self._note_failure(replica)
            if entry.temperature > 0 and entry.tokens:
                # the PR 3/5 contract, across processes: a redrawn
                # sampling stream cannot splice onto the streamed
                # prefix — terminate "fault" with the partial tokens
                entry.note(self._now(), "sampling_fault")
                return self._finish(entry,
                                    self._fault_terminal(entry))
            with self._lock:
                entry.replays += 1
                self.stats["replays"] += 1
                self.tracer.incr("router_replays")
                entry.note(self._now(),
                           f"replay:{entry.replays}:"
                           f"from={replica.replica_id}")
            if entry.replays > self.max_replays:
                return self._finish(entry,
                                    self._fault_terminal(entry))
            if self.fleet_trace:
                # anchor the bridging router.replay span (and the
                # replay-gap histogram) at the break; the next
                # attempt's queue_wait restarts here too
                self._open_replay_window(entry, replica.replica_id)
                wait_t0_us = self._now_us()
            # keep the client connection warm across the failover
            # gap (route + resubmit + survivor prefill before its
            # first event)
            forward_ping()
            exclude.add(replica.address)

    # -- endpoint bodies -----------------------------------------------
    def _parse_generate(self, body: Dict[str, Any]
                        ) -> Tuple[List[int], Dict[str, Any]]:
        prompt = [int(t) for t in body.get("prompt", [])]
        params: Dict[str, Any] = {
            "max_new_tokens": int(body.get("max_new_tokens", 16))}
        for knob in ("temperature", "top_k", "eos_id", "deadline_s",
                     "queue_timeout_s", "tenant", "priority"):
            if body.get(knob) is not None:
                params[knob] = body[knob]
        if body.get("resumable"):
            # ISSUE 15: a resumable stream's client disconnect
            # detaches instead of cancelling (resume via
            # GET /v1/requests/<id>/stream + Last-Event-ID). Kept in
            # params so the WAL open record carries it and a
            # recovered entry stays resumable; replicas ignore it.
            params["resumable"] = True
        if params.get("tenant") is not None:
            # validate HERE, inside the caller's 400-mapping
            # try/except: a malformed name must answer 400 like the
            # gateway surface does, not explode the rate-limit path
            # (spec_of builds a TenantSpec) with a connection reset —
            # and the reserved system tenant is never accepted from
            # the wire (it is quota/rate/priority-exempt: one JSON
            # field would otherwise bypass the whole QoS layer)
            from deeplearning4j_tpu.serving.tenancy import (
                validate_tenant,
            )

            params["tenant"] = validate_tenant(params["tenant"])
            if params["tenant"] == "system":
                raise ValueError(
                    "tenant 'system' is reserved for infrastructure "
                    "traffic")
        return prompt, params

    def _tenant_throttle(self, tenant: str) -> float:
        """Per-tenant token-bucket check (ISSUE 13): 0.0 = admitted,
        else seconds until the tenant's next token accrues — the
        seed of its OWN Retry-After. The reserved ``system`` tenant
        (warmup/boot handshakes) and tenants without a configured
        rate are never throttled."""
        from deeplearning4j_tpu.serving.tenancy import (
            SYSTEM_TENANT,
            TokenBucket,
        )

        if self.tenants is None or tenant == SYSTEM_TENANT:
            return 0.0
        spec = self.tenants.spec_of(tenant)
        if spec.rate_rps is None:
            return 0.0
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    spec.rate_rps, spec.burst)
            wait = bucket.try_take()
            # ISSUE 15 satellite: the level rides the WAL, so a
            # restarted router refills only for real downtime — a
            # flooder's bucket comes back as empty as it died. The
            # record is DEFERRED from under the lock (build order =
            # level order, and the serialized flushers preserve it —
            # two racing appends could otherwise land a stale fuller
            # level after the newer one) and flushed right below,
            # outside the lock.
            self._wal_defer({"t": "bucket", "tenant": tenant,
                             "tokens": round(bucket.tokens, 6),
                             "capacity": bucket.capacity,
                             "rate": bucket.rate,
                             "wall": round(time.time(), 3)})
        self._wal_flush()
        return wait

    def _tenant_queue_share_s(self, tenant: str) -> float:
        """The tenant's open-request share priced in replica waves —
        folded into its Retry-After so a flooder with a deep
        in-flight backlog hears a longer hint than the bucket alone
        would say."""
        with self._lock:
            open_t = sum(1 for e in self._journal.values()
                         if not e.done.is_set()
                         and e.tenant == tenant)
            slots = sum(max(r.n_slots, 1) for r in self._replicas
                        if r.state == "live"
                        and not r.decommissioned) or 1
        return open_t / slots

    def _handle_generate(self, handler: _RouterHandler,
                         stream: bool) -> None:
        try:
            body = handler.read_json()
            if not isinstance(body, dict):
                raise ValueError(f"expected a JSON object, got "
                                 f"{type(body).__name__}")
            prompt, params = self._parse_generate(body)
            if not prompt:
                raise ValueError("empty prompt")
        except (ValueError, TypeError, UnicodeDecodeError) as e:
            handler.send_json({"error": f"bad JSON body: {e}"}, 400,
                              close=True)
            return
        tenant = str(params.get("tenant") or "default")
        wait = self._tenant_throttle(tenant)
        if wait > 0:
            # the front-door shed (ISSUE 13): over its rate quota,
            # the tenant is 429'd BEFORE journaling or any replica
            # traffic, with a Retry-After priced from ITS bucket
            # refill plus ITS queue share — never the global hint
            retry = max(1, math.ceil(
                wait + self._tenant_queue_share_s(tenant)))
            with self._lock:
                self.stats["tenant_throttled"] += 1
            self.tracer.incr("router_tenant_429")
            self.tracer.incr(
                f'router_tenant_429{{tenant="{tenant}"}}')
            handler.send_json(
                {"error": "tenant rate limit", "tenant": tenant,
                 "retry_after_s": retry, "finish_reason": "shed",
                 "status": 429},
                429, close=True,
                headers=(("Retry-After", retry),))
            return
        entry = self._journal_entry(prompt, params)
        if stream:
            self._stream_response(handler, entry)
        else:
            self._blocking_response(handler, entry)

    def _blocking_response(self, handler, entry: _JournalEntry
                           ) -> None:
        acc: List[int] = []
        result = self._run_entry(entry, acc.extend, lambda: None)
        headers: Tuple = ()
        if result.get("retry_after_s"):
            headers = (("Retry-After", result["retry_after_s"]),)
        handler.send_json(result, int(result.get("status", 200)),
                          close=True, headers=headers)

    def _stream_response(self, handler, entry: _JournalEntry) -> None:
        with self._lock:
            self.stats["streams"] += 1
        detached = [False]
        try:
            handler.start_stream("text/event-stream")
            handler.send_event({"id": entry.rid,
                                "resumable": entry.resumable},
                               event_id=0)

            # client-facing writes raise _ClientGone so _run_entry
            # can tell "my client left" apart from "the replica
            # died" — EXCEPT on a resumable stream (ISSUE 15), where
            # a vanished client DETACHES: the relay keeps running
            # with these emits degraded to no-ops, every token still
            # lands in the journal, and the client reconnects via
            # GET /v1/requests/<rid>/stream + Last-Event-ID
            def gone(e: OSError) -> None:
                if not entry.resumable:
                    raise _ClientGone() from e
                if not detached[0]:
                    detached[0] = True
                    with self._lock:
                        self.stats["detached_streams"] += 1
                    self.tracer.incr("router_detached_streams")
                    entry.note(self._now(), "client_detached")

            def emit(tokens: List[int]) -> None:
                if detached[0]:
                    return
                try:
                    # the SSE id is the cumulative delivered-token
                    # count — entry.tokens already includes this
                    # delta (extended by _relay_tokens before emit)
                    handler.send_event({"id": entry.rid,
                                        "tokens": tokens},
                                       event_id=len(entry.tokens))
                except OSError as e:
                    gone(e)

            def ping() -> None:
                if detached[0]:
                    return
                try:
                    handler.send_ping()
                except OSError as e:
                    gone(e)

            result = self._run_entry(entry, emit, ping)
            if not detached[0]:
                out = dict(result)
                out["done"] = True
                handler.send_event(out,
                                   event_id=len(entry.tokens))
                handler.end_stream()
        except (_ClientGone, BrokenPipeError, ConnectionResetError,
                OSError):
            # the ROUTER's client vanished: cancel on the replica and
            # close out the journal entry
            with self._lock:
                self.stats["disconnect_cancels"] += 1
                self.tracer.incr("router_disconnect_cancelled")
                entry.cancelled = True
                addr, rrid = entry.replica_address, entry.replica_rid
            if addr is not None and rrid is not None:
                with contextlib.suppress(Exception):
                    GatewayClient(
                        addr,
                        connect_timeout_s=self.replica_connect_timeout_s,
                        read_timeout_s=5.0).cancel(rrid)
            if not entry.done.is_set():
                self._finish(entry, self._fault_terminal(
                    entry, "cancelled", 499))

    def _handle_stream_resume(self, handler, path: str,
                              query: str) -> None:
        """``GET /v1/requests/<rid>/stream`` (ISSUE 15 tentpole): a
        dropped client reconnects and resumes its stream from the
        journal — ``Last-Event-ID`` (or ``?from=N``) names the last
        token position it received, and the reply replays everything
        past it from the entry's high-water mark, then FOLLOWS the
        live entry (replay after a replica death, recovery after a
        router restart) until the terminal. Zero duplicated and zero
        lost tokens: the journal is the single source of truth and
        the cursor is an exact token position. Works on any journaled
        entry (a blocking submit's progress is followable too); a
        vanished resume consumer just ends — it never cancels the
        underlying request."""
        parsed = handler.read_resume_cursor(path, query)
        if parsed is None:
            return
        rid, cursor = parsed
        with self._lock:
            entry = self._journal.get(rid)
        if entry is None:
            handler.send_json({"error": f"unknown request {rid}"},
                              404, close=True)
            return
        with self._lock:
            self.stats["resumed_streams"] += 1
        self.tracer.incr("router_resumed_streams")
        entry.note(self._now(), f"resumed:from={cursor}")

        def poll(at):
            with self._lock:
                total = len(entry.tokens)
                tail = ([int(t) for t in entry.tokens[at:]]
                        if total > at else [])
                return (tail, total,
                        entry.done.is_set() or self._stopped,
                        entry.result)

        try:
            handler.follow_stream(rid, cursor, poll,
                                  entry.done.wait, self.keepalive_s)
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the resume consumer vanished: nothing to cancel — the
            # underlying request belongs to its primary stream (or
            # to the recovery replay), and another resume may follow
            pass

    def _handle_cancel(self, handler, path: str) -> None:
        tail = path.rsplit("/", 1)[-1]
        try:
            rid = int(tail)
        except ValueError:
            handler.send_json({"error": f"bad request id {tail!r}"},
                              400, close=True)
            return
        with self._lock:
            entry = self._journal.get(rid)
            if entry is not None:
                entry.cancelled = True
                addr, rrid = entry.replica_address, entry.replica_rid
                done = entry.done.is_set()
        if entry is None:
            handler.send_json({"id": rid, "cancelled": False,
                               "done": False}, 404, close=True)
            return
        if not done and addr is not None and rrid is not None:
            with contextlib.suppress(Exception):
                GatewayClient(
                    addr,
                    connect_timeout_s=self.replica_connect_timeout_s,
                    read_timeout_s=5.0).cancel(rrid)
        handler.send_json({"id": rid, "cancelled": not done,
                           "done": done}, 200, close=True)

    def _handle_poll(self, handler, path: str) -> None:
        tail = path.rsplit("/", 1)[-1]
        try:
            rid = int(tail)
        except ValueError:
            handler.send_json({"error": f"bad request id {tail!r}"},
                              400, close=True)
            return
        with self._lock:
            entry = self._journal.get(rid)
            result = entry.result if entry is not None else None
        if result is not None:
            # poll is ALWAYS 200 for a stored result, whatever its
            # mapped generate-time status — the gateway's contract
            handler.send_json(result, 200, close=True)
        elif entry is not None:
            handler.send_json({"id": rid, "running": True}, 202,
                              close=True)
        else:
            handler.send_json({"error": f"unknown request {rid}"},
                              404, close=True)

    # -- health / metrics / admin --------------------------------------
    def replica_status(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.status() for r in self._replicas]

    def _health(self) -> Dict[str, Any]:
        with self._lock:
            statuses = [r.status() for r in self._replicas]
            open_n = sum(1 for e in self._journal.values()
                         if not e.done.is_set())
        routable = any(s["state"] in ("live", "degraded")
                       for s in statuses)
        out = {"ok": routable and not self._stopped,
               "state": "stopped" if self._stopped else (
                   "live" if routable else "dead"),
               "replicas": statuses,
               "journal_entries": len(self._journal),
               "journal_open": open_n}
        if self._wal is not None:
            out["wal"] = {"path": self._wal.path,
                          "fsync": self._wal.fsync,
                          "bytes": self._wal.size_bytes,
                          "compactions":
                              self.stats["wal_compactions"],
                          "recovered_entries":
                              self.stats["recovered_entries"],
                          "recovered_open":
                              self.stats["recovered_open"]}
        return out

    def _metrics_text(self) -> str:
        with self._lock:
            gauge = getattr(self.tracer, "gauge", self.tracer.counter)
            for key, value in self.stats.items():
                gauge(f"router_{key}", value)
            by_state = {s: 0 for s in REPLICA_STATES}
            for r in self._replicas:
                by_state[r.state] += 1
            for state, n in by_state.items():
                gauge(f"router_replicas_{state.replace('-', '_')}", n)
            gauge("router_journal_open",
                  sum(1 for e in self._journal.values()
                      if not e.done.is_set()))
            if self.tenants is not None:
                # per-tenant open-request share (ISSUE 13): what the
                # per-tenant Retry-After prices, exported so an
                # operator can see WHOSE requests fill the fleet
                open_by: Dict[str, int] = {}
                for e in self._journal.values():
                    if not e.done.is_set():
                        open_by[e.tenant] = (
                            open_by.get(e.tenant, 0) + 1)
                for tenant, n in open_by.items():
                    gauge(f'router_journal_open{{tenant='
                          f'"{tenant}"}}', n)
            return self.tracer.prometheus_text()

    # -- fleet observability (ISSUE 10 tentpole) ------------------------
    def fleet_metrics_text(self) -> str:
        """``GET /v1/fleet/metrics`` body: every reachable replica's
        ``/v1/metrics`` exposition federated through
        :meth:`profiler.tracer.Tracer.merge_prometheus` — histogram
        families merged bucket-wise into fleet-wide distributions
        (plus ``{replica=...}``-labeled per-replica samples), counters
        summed, gauges labeled per replica — with the router's own
        tracks (``router_*`` + the ``router_replay_gap_s`` histogram)
        appended. Replicas that cannot contribute — dead or
        decommissioned (no live scrape exists), or in-state but
        failing the fetch — are skipped and NAMED in a comment line,
        so a fleet-aggregate discontinuity is explained by the scrape
        itself: it must degrade, not 500, while a replica is
        mid-death. Replica fetches run in PARALLEL, so one frozen
        replica costs the scrape one timeout, not one per replica."""
        from deeplearning4j_tpu.profiler.tracer import Tracer

        with self._lock:
            targets = [(r.replica_id, r.address)
                       for r in self._replicas
                       if not r.decommissioned
                       and r.state in ("live", "degraded",
                                       "draining")]
            skipped = [r.replica_id for r in self._replicas
                       if r.decommissioned
                       or r.state not in ("live", "degraded",
                                          "draining")]
        results: Dict[str, str] = {}

        def fetch(rid: str, addr: str) -> None:
            with contextlib.suppress(GatewayError,
                                     *RETRYABLE_ERRORS):
                results[rid] = GatewayClient(
                    addr,
                    connect_timeout_s=self.replica_connect_timeout_s,
                    read_timeout_s=5.0).metrics()

        threads = [threading.Thread(target=fetch, args=t,
                                    daemon=True,
                                    name=f"fleet-metrics-{t[0]}")
                   for t in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        sources = {rid: results[rid] for rid, _ in targets
                   if rid in results}
        skipped += [rid for rid, _ in targets if rid not in results]
        parts = []
        if skipped:
            parts.append("# fleet: replicas skipped (dead, "
                         "decommissioned, or scrape failed): "
                         + ", ".join(sorted(skipped)))
        parts.append(Tracer.merge_prometheus(sources))
        parts.append(self._metrics_text())
        return "\n".join(p.rstrip("\n") for p in parts if p) + "\n"

    def _handle_fleet_metrics(self, handler) -> None:
        handler.send_bytes(self.fleet_metrics_text().encode(),
                           "text/plain; version=0.0.4", 200,
                           close=True)

    def fleet_trace_events(self) -> List[Dict[str, Any]]:
        """The STITCHED fleet trace (ISSUE 10 tentpole): one
        Perfetto-loadable event list where

        - lane (Chrome ``pid``) 0 is the ROUTER — its
          ``router.route`` / ``router.queue_wait`` / ``router.replay``
          spans and ``router.breaker`` instants;
        - lane ``i+1`` is replica ``i`` — its live ``/v1/trace``
          window when reachable, else the health loop's last cached
          window (how a SIGKILLed replica's spans survive onto the
          stitched timeline);
        - every replica event's ``ts`` is skew-corrected onto the
          router's clock by that replica's scrape-RTT offset estimate
          (``ts - clock_offset_us``), so a failover reads MONOTONE:
          the dead lane's spans end, the bridging ``router.replay``
          span runs, the survivor lane's spans begin;
        - ``process_name`` metadata labels every lane, and a final
          ``fleet.stitch`` instant records per-replica offset / RTT /
          source (live vs cache) — the trace describes its own
          stitching."""
        with self._lock:
            snap = [(i, r, r.state, r.decommissioned,
                     list(r.trace_cache), r.clock_offset_us,
                     r.clock_rtt_us, r.cache_offset_us)
                    for i, r in enumerate(self._replicas)]
        events: List[Dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "router"}},
            {"name": "process_sort_index", "ph": "M", "pid": 0,
             "args": {"sort_index": 0}},
        ]
        if hasattr(self.tracer, "events"):
            for e in self.tracer.events():
                e2 = dict(e)
                e2["pid"] = 0
                events.append(e2)
        # live fetches (window + any missing clock measurement) run
        # in PARALLEL: a frozen replica costs the stitch one timeout,
        # not one per replica — this endpoint exists for incidents,
        # which is exactly when a replica is likely to be sick
        fetched: Dict[int, Tuple[List[Dict[str, Any]],
                                 Optional[float], float]] = {}

        def fetch(i: int, replica: _Replica,
                  offset: Optional[float], rtt: float) -> None:
            probe = self._replica_client(replica, read_timeout_s=5.0)
            evts = None
            with contextlib.suppress(GatewayError,
                                     *RETRYABLE_ERRORS):
                evts = probe.trace_events().get("traceEvents", [])
            if evts is not None and offset is None:
                # replica never completed a clock-bearing scrape
                # (e.g. stitch requested before the first health
                # tick): measure once, inline
                with contextlib.suppress(GatewayError,
                                         *RETRYABLE_ERRORS):
                    t0 = self._now_us()
                    payload = probe.healthz()
                    t1 = self._now_us()
                    if payload.get("now_us") is not None:
                        offset = (float(payload["now_us"])
                                  - (t0 + t1) / 2.0)
                        rtt = t1 - t0
            if evts is not None:
                fetched[i] = (evts, offset, rtt)

        threads = [
            threading.Thread(
                target=fetch, args=(i, replica, offset, rtt),
                daemon=True, name=f"fleet-trace-{replica.replica_id}")
            for i, replica, state, dec, _, offset, rtt, _c in snap
            if not dec and state in ("live", "degraded", "draining")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=12.0)

        stitch: List[Dict[str, Any]] = []
        for (i, replica, state, dec, cache, offset, rtt,
                cache_offset) in snap:
            lane = i + 1
            if i in fetched:
                evts, offset, rtt = fetched[i]
                source = "live"
            else:
                # cached events belong to the epoch the cache was
                # scraped from: correct them with the offset
                # snapshotted ALONGSIDE the cache, not the live
                # estimate (which a death/restart may have reset)
                evts, source = cache, "cache"
                offset = cache_offset
            dead = dec or state in ("dead", "half-open")
            label = (f"replica {replica.replica_id}"
                     + (" (dead)" if dead else ""))
            events.append({"name": "process_name", "ph": "M",
                           "pid": lane, "args": {"name": label}})
            events.append({"name": "process_sort_index", "ph": "M",
                           "pid": lane,
                           "args": {"sort_index": lane}})
            for e in evts:
                e2 = dict(e)
                e2["pid"] = lane
                if offset is not None and "ts" in e2:
                    e2["ts"] = e2["ts"] - offset
                events.append(e2)
            stitch.append({
                "replica_id": replica.replica_id,
                "lane": lane, "state": state,
                "decommissioned": dec, "source": source,
                "events": len(evts),
                "clock_offset_us": offset,
                "clock_rtt_us": (None if rtt == float("inf")
                                 else rtt),
                "skew_corrected": offset is not None,
            })
        events.append({"name": "fleet.stitch", "ph": "i",
                       "ts": self._now_us(), "pid": 0, "tid": 0,
                       "s": "g", "args": {"replicas": stitch}})
        return events

    def _handle_fleet_trace(self, handler) -> None:
        """``GET /v1/trace``: the stitched fleet trace, chunk-streamed
        512 events at a time (``JsonHandler.send_trace_events`` — the
        same framing as the gateway's trace export: one downloads a
        replica, the other the fleet)."""
        handler.send_trace_events(self.fleet_trace_events())

    def _handle_request_trace(self, handler, path: str) -> None:
        """``GET /v1/requests/<id>/trace`` (ISSUE 10 satellite):
        resolve the request's owning replica through the journal and
        PROXY its flight-recorder trace — the router id maps to the
        replica-side id the journal recorded. When the owner is dead
        or has evicted the record, answer with the journal's own
        breadcrumbs (routing/replay history + the streamed high-water
        mark) and a ``replayed_to`` pointer instead of a blind 404:
        the router watched every attempt, so it always has SOMETHING
        true to say about a request it journaled."""
        tail = path[len("/v1/requests/"):-len("/trace")]
        try:
            rid = int(tail)
        except ValueError:
            handler.send_json({"error": f"bad request id {tail!r}"},
                              400, close=True)
            return
        with self._lock:
            entry = self._journal.get(rid)
            if entry is None:
                addr = rrid = replica = None
            else:
                addr, rrid = entry.replica_address, entry.replica_rid
                replica = next(
                    (r for r in self._replicas if r.address == addr),
                    None)
                reachable = (replica is not None
                             and not replica.decommissioned
                             and replica.state in ("live", "degraded",
                                                   "draining"))
                router_info = {
                    "trace": entry.trace,
                    "replays": entry.replays,
                    "tokens_high_water": len(entry.tokens),
                    "finish_reason": (entry.result or {}).get(
                        "finish_reason"),
                    "e2e_s": (round(entry.done_t - entry.submit_t, 6)
                              if entry.done_t is not None else None),
                    "history": [list(h) for h in entry.history],
                }
        if entry is None:
            handler.send_json({"error": f"unknown request {rid}"},
                              404, close=True)
            return
        replayed_to = (replica.replica_id
                       if entry.replays and replica is not None
                       else None)
        if reachable and rrid is not None:
            try:
                out = GatewayClient(
                    addr,
                    connect_timeout_s=self.replica_connect_timeout_s,
                    read_timeout_s=5.0).trace(rrid)
                status = 202 if out.get("running") else 200
                out = dict(out)
                out["id"] = rid
                out["replica_id"] = replica.replica_id
                out["replica_rid"] = rrid
                if replayed_to:
                    out["replayed_to"] = replayed_to
                out["router"] = router_info
                handler.send_json(out, status, close=True)
                return
            except (GatewayError, *RETRYABLE_ERRORS):
                pass  # owner died / evicted: journal breadcrumbs
        handler.send_json({
            "id": rid, "source": "journal",
            "replayed_to": replayed_to,
            "owner": (replica.replica_id if replica is not None
                      else None),
            "owner_reachable": bool(rrid is not None and replica
                                    is not None and reachable),
            "router": router_info,
        }, 200, close=True)

    # -- elastic fleet surface (ISSUE 11 tentpole) -----------------------
    def add_replica(self, address: str,
                    replica_id: Optional[str] = None) -> str:
        """Runtime scale-up: register one more gateway replica and
        atomically swap it into the rendezvous set — the append
        happens under the router lock, the same lock every ``_pick``
        ranks candidates under, so a pick sees either the old set or
        the new set, never a torn one. By the rendezvous property the
        new replica claims ONLY the affinity keys that rank it first;
        every other key keeps its owner, and streams already in
        flight stay pinned to the replica they were picked onto (no
        mid-stream migration — routing is decided per attempt, not
        per token).

        ``replica_id`` should be the replica's configured stable id:
        affinity keys hash against it, and passing it here (instead
        of waiting for the first health scrape to learn it) means the
        keyspace the new replica will own is its FINAL keyspace from
        the first pick. The newcomer joins DEGRADED — routable, but
        ``live`` is earned by its first successful health scrape, so
        a caller that waits for ``replica_status`` to show ``live``
        (the fleet controller does, after its warmup handshake) is
        waiting on a real health round-trip, not the optimistic
        default a dead-on-arrival replica would also show."""
        replica = _Replica(address)
        replica.state = "degraded"
        if replica_id is not None:
            replica.replica_id = str(replica_id)
        with self._lock:
            for r in self._replicas:
                if r.decommissioned:
                    continue
                if r.address == replica.address:
                    raise ValueError(
                        f"replica {replica.address} already "
                        "registered")
                if r.replica_id == replica.replica_id:
                    raise ValueError(
                        f"replica id {replica.replica_id!r} already "
                        "registered (affinity keys hash against ids "
                        "— duplicates would fork one keyspace)")
            self._replicas.append(replica)
            self._breaker_instant(replica, "new", "degraded")
        self.tracer.incr("router_replicas_added")
        return replica.replica_id

    def remove_replica(self, replica_id: str) -> Dict[str, Any]:
        """Forget a replica that is already out of rotation
        (decommissioned or dead): the health loop stops probing it,
        it stops occupying a stitched-trace lane, and its address
        becomes reusable. Removing a live/draining replica is
        refused — drain it first (``drain_replica``), so its
        in-flight work hands off through the replay path instead of
        vanishing with the registration."""
        with self._lock:
            matches = [r for r in self._replicas
                       if replica_id in (r.replica_id, r.address)]
            if not matches:
                raise KeyError(f"unknown replica {replica_id!r}")
            # when a reused address/id matches both a stale
            # decommissioned entry and a live replica, removal means
            # the out-of-rotation one
            removable = [r for r in matches
                         if r.decommissioned or r.state == "dead"]
            replica = (removable or matches)[0]
            if not (replica.decommissioned
                    or replica.state == "dead"):
                raise ValueError(
                    f"replica {replica.replica_id} is "
                    f"{replica.state}; drain it before removing")
            self._replicas.remove(replica)
            status = replica.status()
        self.tracer.incr("router_replicas_removed")
        return status

    def live_affinity_prompts(self, cap: int = 8
                              ) -> List[List[int]]:
        """The fleet's WARM working set, from the journal: the
        block-aligned prompt prefixes of the most recently submitted
        affinity-eligible requests, deduped by affinity key, newest
        first. The fleet controller feeds these to a booting
        replica's ``/v1/warmup`` so a rolling upgrade's replacement
        joins the rendezvous set with its prefix cache already
        holding the keys it is about to own."""
        out: List[List[int]] = []
        seen: Set[bytes] = set()
        with self._lock:
            entries = list(self._journal.values())
        for entry in reversed(entries):
            key = self._affinity_key(entry.prompt)
            if key is None or key in seen:
                continue
            seen.add(key)
            b = self.affinity_block_tokens
            n = (len(entry.prompt) // b) * b
            out.append([int(t) for t in entry.prompt[:n]])
            if len(out) >= cap:
                break
        return out

    def drain_replica(self, replica_id: str,
                      timeout_s: Optional[float] = None
                      ) -> Dict[str, Any]:
        """Graceful scale-down of one replica: stop routing to it,
        ``/v1/drain`` it (in-flight work settles within the budget),
        and decommission it. Requests the drain could NOT settle end
        their relayed streams without a terminal — their relay loops
        fail over to survivors through the normal replay path, so
        from every client's point of view the requests simply
        continue. Returns the replica's drain summary plus the
        journal entries that were still open on it at drain time.

        IDEMPOTENT (ISSUE 11 satellite): the fleet controller and an
        operator will race on this. The first drain owns the work;
        any later or concurrent drain of the same replica waits for
        it and returns the FIRST drain's summary (same
        ``carried_ids``) instead of double-draining or erroring."""
        with self._lock:
            matches = [r for r in self._replicas
                       if replica_id in (r.replica_id, r.address)]
            if not matches:
                raise KeyError(f"unknown replica {replica_id!r}")
            # a reused address/id may leave a RETAINED decommissioned
            # registration alongside the live one (add_replica allows
            # the reuse); the drain the caller means is the active
            # replica's, never the stale entry's already-done summary
            active = [r for r in matches if not r.decommissioned]
            replica = (active or matches)[0]
            # capture the latch under the SAME lock that reads
            # drain_started: the failure path swaps in a fresh Event,
            # and a waiter that saw drain_started must wait on the
            # one that path will set
            done = replica.drain_done
            if replica.drain_started:
                already = True
            else:
                already = False
                replica.drain_started = True
                self._breaker_instant(replica, replica.state,
                                      "draining")
                replica.state = "draining"
                handed_off = [e.rid for e in self._journal.values()
                              if not e.done.is_set()
                              and e.replica_address
                              == replica.address]
        if already:
            done.wait(timeout=600.0)
            with self._lock:
                if replica.drain_summary is not None:
                    return dict(replica.drain_summary)
                owner_failed = not replica.drain_started
            if owner_failed:
                # the owning drain raised and released the latch —
                # retry as the new owner rather than hand the caller
                # a success-shaped dict for a drain that never ran
                return self.drain_replica(replica_id, timeout_s)
            return {"replica_id": replica.replica_id,
                    "address": replica.address, "drained": False,
                    "in_progress": True}
        try:
            try:
                summary = self._replica_client(replica).drain(
                    timeout_s)
            except (GatewayError, *RETRYABLE_ERRORS) as e:
                # failed drain = unplanned death: the breaker path
                # takes over and the same replay machinery rescues
                # the work
                self._note_failure(replica)
                summary = {"drained": False, "error": repr(e)}
        except BaseException:
            # anything unexpected must release the latch retryably —
            # a permanently-armed drain_started with no summary would
            # wedge every later drain of this replica
            with self._lock:
                replica.drain_started = False
                done, replica.drain_done = (replica.drain_done,
                                            threading.Event())
            done.set()
            raise
        with self._lock:
            self._breaker_instant(replica, replica.state, "dead")
            replica.state = "dead"
            replica.decommissioned = True
            self.stats["drained_replicas"] += 1
            self.tracer.incr("router_drained_replicas")
            out = {"replica_id": replica.replica_id,
                   "address": replica.address,
                   "open_requests_handed_off": handed_off,
                   "drain": summary}
            replica.drain_summary = out
            replica.drain_done.set()
        return dict(out)

    def _handle_drain_replica(self, handler) -> None:
        try:
            body = handler.read_json()
            replica_id = body["replica_id"]
            timeout = body.get("timeout_s")
            timeout = None if timeout is None else float(timeout)
        except (ValueError, KeyError, TypeError, AttributeError,
                UnicodeDecodeError) as e:
            handler.send_json({"error": f"bad drain body: {e}"}, 400,
                              close=True)
            return
        try:
            summary = self.drain_replica(replica_id, timeout)
        except KeyError as e:
            handler.send_json({"error": str(e)}, 404, close=True)
            return
        handler.send_json(summary, 200, close=True)
