"""Multi-replica serving router: a failure-tolerant, prefix-aware
front door over N :class:`~deeplearning4j_tpu.serving.ServingGateway`
replicas (ISSUE 9 tentpole — ROADMAP item 3).

One gateway owns one engine; millions of users need horizontal scale,
and horizontal scale means replicas DIE — a process crash today loses
every in-flight stream that replica owned. The router lifts the
guarantees PR 3/5 proved inside one process (seeded fault recovery,
drain-to-snapshot restore finishing bit-identical ids, per-request
``delta_sent`` high-water dedup) across process boundaries, the same
replay-on-survivor discipline vLLM-style fleets and Orca-style
continuous-batching servers need once they go horizontal:

**Health & liveness.** A background loop scrapes every replica's
``/v1/healthz`` (each tick) and ``/v1/metrics`` (every few ticks),
feeding a per-replica state machine::

        live ──failure──▶ degraded ──threshold──▶ dead
         ▲                   │                      │
         │◀────success───────┘          probe every probe_interval_s
         │                                          ▼
         └──────────probe succeeds────────── half-open

Consecutive failures (health scrapes AND data-plane stream breaks both
count) trip the circuit breaker at ``failure_threshold``; a dead
replica gets one half-open probe per ``probe_interval_s`` and rejoins
on success. A 429 + ``Retry-After`` from a replica is BACKPRESSURE,
not failure: the replica is healthy and said "later" — the router
parks it until the hint expires and routes the request to a sibling
instead of making the client wait (ISSUE 9 satellite).

**Prefix-affinity routing.** Shared-system-prompt traffic only pays
off when it lands where its radix/block cache is warm. The router
hashes the prompt's leading block-aligned tokens
(``affinity_block_tokens``-sized, matching the paged engine's block
granularity) and RENDEZVOUS-hashes (highest-random-weight) that key
against the live replica ids: every replica scores
``hash(prefix_key, replica_id)`` and the max wins, so replica death
remaps ONLY the dead replica's keyspace — survivors keep their warm
sets, unlike modular hashing where one death reshuffles everyone.
Prompts shorter than one block (no reusable prefix worth chasing)
fall back to queue-depth-weighted least-loaded using the scraped
per-replica load.

**The robustness core: journal + replay.** Every proxied request is
journaled (id, prompt, params, owning replica, streamed-token
high-water mark) and relayed through the router as SSE deltas — even
blocking client calls ride an internal stream, so the journal's
high-water mark is always live. When a replica dies mid-request (or a
drain hands its unfinished work back), the relay loop replays the
request onto a survivor: the FULL prompt is resubmitted (recompute
replay, the vLLM-preemption discipline — deterministic greedy decode
regenerates the same ids), the journal's high-water mark dedups the
already-streamed prefix (each regenerated token is CHECKED against the
streamed one, then discarded), and the client's stream resumes
bit-identically past where it stopped. Sampling requests that already
streamed tokens terminate ``finish_reason="fault"`` instead — a
redrawn RNG cannot splice onto a streamed prefix (the exact PR 3/5
contract, now across processes). Graceful scale-down is the same code
path: ``drain_replica`` routes ``/v1/drain`` through the replica,
whose unfinished streams end without a terminal event, and the relay
loops re-admit those requests on survivors.

The router speaks the gateway's own protocol (``/v1/generate``,
``/v1/requests/<id>``, ``/v1/healthz``, ``/v1/metrics``, SSE framing),
so :class:`~deeplearning4j_tpu.serving.GatewayClient` drives a router
exactly like a single gateway — a one-replica router is bit-identical
to direct gateway access. Stdlib-only, on util/httpjson like the
gateway."""

from __future__ import annotations

import contextlib
import hashlib
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.serving.client import (
    RETRYABLE_ERRORS,
    GatewayClient,
    GatewayError,
)
from deeplearning4j_tpu.util.httpjson import HttpService, JsonHandler

#: every state a replica can be in, as the router sees it:
#: ``live`` (routable), ``degraded`` (recent failures below the
#: breaker threshold — routable only when nothing live remains),
#: ``draining`` (finishing in-flight work, not routable for new
#: requests), ``dead`` (breaker open — not routable, in-flight
#: requests replayed), ``half-open`` (dead, one probe in flight).
REPLICA_STATES = ("live", "degraded", "draining", "dead", "half-open")


class _NoReplica(RuntimeError):
    """No replica can take the request (everyone dead/draining)."""


class _AllBackedOff(RuntimeError):
    """Every candidate replica is parked behind a 429 Retry-After."""

    def __init__(self, wait_s: float):
        super().__init__(f"all replicas backed off for {wait_s:.1f}s")
        self.wait_s = wait_s


class _ClientGone(Exception):
    """The ROUTER's own client vanished mid-relay (failed SSE write).
    Distinct from replica-side read failures on purpose: a client
    disconnect must cancel the request, never charge the replica's
    breaker or trigger a replay."""


class _RouteAround(Exception):
    """This attempt never started streaming — try another replica
    without charging the replay budget. ``deterministic`` carries a
    terminal to deliver instead when retrying elsewhere would just
    repeat the same rejection (bad params)."""

    def __init__(self, deterministic: Optional[Dict[str, Any]] = None):
        super().__init__()
        self.deterministic = deterministic


class _ReplayDiverged(RuntimeError):
    """A replayed greedy stream produced a token that differs from
    the already-streamed prefix — the survivors are not replicas of
    the dead engine (different weights/seed/config). Never expected
    in a correctly deployed fleet; terminates the request ``fault``
    rather than silently splicing wrong tokens."""


class _Replica:
    """Router-side state of one gateway replica. All mutable fields
    are guarded by the router's lock."""

    def __init__(self, address: str):
        self.address = address.split("://", 1)[-1]
        #: stable identity for rendezvous hashing; replaced by the
        #: replica's self-reported id at the first health scrape
        self.replica_id = self.address
        self.state = "live"  # optimistic until the breaker disagrees
        self.failures = 0
        self.backoff_until = 0.0  # 429 Retry-After parking
        self.next_probe_t = 0.0   # half-open probe schedule (dead)
        self.decommissioned = False  # drained away: never resurrected
        # scraped load + affinity figures
        self.queue_depth = 0
        self.active_slots = 0
        self.n_slots = 1
        self.prefix_tokens_reused = 0
        self.requests_routed = 0
        self.open_entries = 0  # journal entries currently assigned

    def status(self) -> Dict[str, Any]:
        return {
            "replica_id": self.replica_id,
            "address": self.address,
            "state": self.state,
            "consecutive_failures": self.failures,
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "n_slots": self.n_slots,
            "prefix_tokens_reused": self.prefix_tokens_reused,
            "requests_routed": self.requests_routed,
            "open_requests": self.open_entries,
        }


class _JournalEntry:
    """One proxied request's journal record: everything replay needs
    (prompt + params), plus the streamed-token high-water mark that
    makes replay exactly-once from the client's point of view.
    ``tokens`` IS the high-water mark: every token in it has been
    relayed to the client (or accumulated for a blocking reply), and
    a replayed stream's regenerated prefix is checked against it and
    dropped instead of re-delivered."""

    __slots__ = ("rid", "prompt", "params", "temperature", "tokens",
                 "replays", "cancelled", "done", "result",
                 "replica_address", "replica_rid", "affinity",
                 "history", "submit_t")

    def __init__(self, rid: int, prompt: List[int],
                 params: Dict[str, Any], submit_t: float):
        self.rid = rid
        self.prompt = prompt
        self.params = params
        self.temperature = float(params.get("temperature") or 0.0)
        self.tokens: List[int] = []
        self.replays = 0
        self.cancelled = False
        self.done = threading.Event()
        self.result: Optional[Dict[str, Any]] = None
        self.replica_address: Optional[str] = None
        self.replica_rid: Optional[int] = None
        self.affinity = False
        #: (t_s, event) breadcrumbs: routed/replayed/finished — the
        #: journal's audit trail the chaos soak asserts over
        self.history: List[Tuple[float, str]] = []
        self.submit_t = submit_t

    def note(self, t: float, event: str) -> None:
        self.history.append((round(t, 4), event))


def parse_prometheus(text: str) -> Dict[str, float]:
    """Minimal Prometheus text parse: ``name value`` sample lines to a
    dict (comments/HELP/TYPE skipped, label-carrying and unparsable
    samples ignored). Enough for the gauge tracks the gateway
    exports."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.partition(" ")
        if "{" in name:
            continue
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


class _RouterHandler(JsonHandler):
    """One instance per connection; the owning router rides in as the
    ``router`` class attribute (HttpService)."""

    protocol_version = "HTTP/1.1"
    router: "ServingRouter"

    def do_POST(self):
        path, _, query = self.path.partition("?")
        if path == "/v1/generate":
            stream = "stream=1" in query.split("&")
            self.router._handle_generate(self, stream)
        elif path == "/v1/replicas/drain":
            self.router._handle_drain_replica(self)
        else:
            self.send_json({"error": f"no such endpoint {path}"}, 404,
                           close=True)

    def do_GET(self):
        path = self.path.partition("?")[0]
        if path == "/v1/healthz":
            self.send_json(self.router._health(), 200, close=True)
        elif path == "/v1/metrics":
            self.send_bytes(self.router._metrics_text().encode(),
                            "text/plain; version=0.0.4", 200,
                            close=True)
        elif path.startswith("/v1/requests/"):
            self.router._handle_poll(self, path)
        else:
            self.send_json({"error": f"no such endpoint {path}"}, 404,
                           close=True)

    def do_DELETE(self):
        path = self.path.partition("?")[0]
        if path.startswith("/v1/requests/"):
            self.router._handle_cancel(self, path)
        else:
            self.send_json({"error": f"no such endpoint {path}"}, 404,
                           close=True)

    # SSE framing (send_event / send_ping) inherited from JsonHandler


class RouterClient(GatewayClient):
    """GatewayClient plus the router-only admin surface. Generation,
    polling, cancel, healthz, and metrics are the plain gateway
    protocol — this subclass only adds what a single gateway does not
    have."""

    def drain_replica(self, replica_id: str,
                      timeout_s: Optional[float] = None
                      ) -> Dict[str, Any]:
        """Graceful scale-down of one replica through the router:
        drains it, fails its unfinished requests over to survivors,
        and decommissions it."""
        body: Dict[str, Any] = {"replica_id": replica_id}
        if timeout_s is not None:
            body["timeout_s"] = timeout_s
        return self._call("POST", "/v1/replicas/drain", body)


class ServingRouter:
    """Failure-tolerant prefix-aware router over N gateway replicas.

    Parameters:

    - ``replicas`` — gateway addresses (``host:port`` or
      ``http://host:port``). All replicas must serve the SAME model
      with the same seed/config: greedy replay correctness depends on
      every replica producing bit-identical ids for the same request.
    - ``host``/``port`` — the router's own bind address (port 0 =
      ephemeral).
    - ``affinity_block_tokens`` — the affinity hash covers the
      prompt's leading ``floor(len/B)*B`` tokens; prompts shorter than
      one block route least-loaded instead. Match the replicas'
      ``block_tokens`` when they run paged KV.
    - ``health_interval_s`` / ``metrics_every`` — healthz scrape
      period, and how many health ticks between the heavier
      ``/v1/metrics`` scrapes.
    - ``failure_threshold`` — consecutive failures (scrape or
      data-plane) that trip a replica's breaker to ``dead``.
    - ``probe_interval_s`` — half-open probe period for dead replicas.
    - ``max_replays`` — replay budget per request across replica
      deaths; past it the request terminates ``fault``.
    - ``replica_connect_timeout_s`` / ``replica_timeout_s`` — the
      router→replica connect and read bounds (a dead replica must
      fail fast, a healthy stream may idle up to the replica's
      keep-alive period between events).

    ``with ServingRouter([...]) as r: ...`` serves on entry and closes
    on exit; or ``start()``/``close()`` explicitly."""

    def __init__(self, replicas: Sequence[str],
                 host: str = "127.0.0.1", port: int = 0,
                 affinity_block_tokens: int = 16,
                 health_interval_s: float = 0.25,
                 metrics_every: int = 4,
                 failure_threshold: int = 3,
                 probe_interval_s: float = 1.0,
                 max_replays: int = 3,
                 keepalive_s: float = 0.5,
                 handler_timeout_s: float = 30.0,
                 replica_connect_timeout_s: float = 2.0,
                 replica_timeout_s: float = 120.0,
                 journal_cap: int = 4096,
                 tracer=None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        if affinity_block_tokens < 1:
            raise ValueError(
                f"affinity_block_tokens {affinity_block_tokens} < 1")
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold {failure_threshold} < 1")
        self._replicas = [_Replica(a) for a in replicas]
        seen: Set[str] = set()
        for r in self._replicas:
            if r.address in seen:
                raise ValueError(f"duplicate replica {r.address}")
            seen.add(r.address)
        self.affinity_block_tokens = int(affinity_block_tokens)
        self.health_interval_s = float(health_interval_s)
        self.metrics_every = max(int(metrics_every), 1)
        self.failure_threshold = int(failure_threshold)
        self.probe_interval_s = float(probe_interval_s)
        self.max_replays = int(max_replays)
        self.keepalive_s = float(keepalive_s)
        self.replica_connect_timeout_s = float(
            replica_connect_timeout_s)
        self.replica_timeout_s = float(replica_timeout_s)
        self.journal_cap = int(journal_cap)
        if tracer is None:
            from deeplearning4j_tpu.profiler.tracer import Tracer

            tracer = Tracer(max_events=65536)
        self.tracer = tracer
        self._lock = threading.RLock()
        self._rids = itertools.count()
        self._journal: Dict[int, _JournalEntry] = {}
        self._rr = 0  # least-loaded tie-break rotation
        self._t0 = time.monotonic()
        self.stats = {
            "requests": 0, "streams": 0, "affinity_routed": 0,
            "affinity_overflow": 0,
            "load_routed": 0, "replays": 0, "rerouted_429": 0,
            "replica_faults": 0, "request_faults": 0,
            "disconnect_cancels": 0, "drained_replicas": 0,
        }
        self._stopped = False
        self._service = HttpService(_RouterHandler, host, port,
                                    router=self,
                                    timeout=float(handler_timeout_s))
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="router-health")

    # -- lifecycle -----------------------------------------------------
    @property
    def address(self) -> str:
        return self._service.address

    def start(self) -> "ServingRouter":
        self._service.start()
        self._health_thread.start()
        return self

    def __enter__(self) -> "ServingRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Stop the router tier: health loop joined, HTTP service
        stopped, every still-open journal entry released (their
        handlers answer 503/end-of-stream). Replicas are NOT touched —
        they keep serving direct traffic."""
        self._stopped = True
        if self._health_thread.is_alive():
            self._health_thread.join(
                timeout=5.0 + 2 * self.health_interval_s)
        with self._lock:
            for entry in self._journal.values():
                entry.done.set()
        self._service.stop()

    def _now(self) -> float:
        return time.monotonic() - self._t0

    def _replica_client(self, replica: _Replica,
                        read_timeout_s: Optional[float] = None,
                        retries: int = 0) -> GatewayClient:
        return GatewayClient(
            replica.address,
            connect_timeout_s=self.replica_connect_timeout_s,
            read_timeout_s=(self.replica_timeout_s
                            if read_timeout_s is None
                            else read_timeout_s),
            retries=retries)

    # -- health / liveness tracking ------------------------------------
    def _health_loop(self) -> None:
        tick = 0
        while not self._stopped:
            tick += 1
            for replica in list(self._replicas):
                if self._stopped:
                    return
                try:
                    self._check_replica(
                        replica,
                        scrape_metrics=(
                            tick % self.metrics_every == 0))
                except Exception:
                    # the breaker thread must NEVER die: an exotic
                    # failure shape from a dying peer (anything the
                    # retryable classification missed) counts as a
                    # failed scrape, not a router outage
                    self._note_failure(replica)
                    self.tracer.incr("router_health_scrape_errors")
            time.sleep(self.health_interval_s)

    def _check_replica(self, replica: _Replica,
                       scrape_metrics: bool) -> None:
        if replica.decommissioned:
            return
        now = time.monotonic()
        if replica.state in ("dead", "half-open"):
            if now < replica.next_probe_t:
                return
            with self._lock:
                replica.state = "half-open"
        # scrape timeouts well under the health interval budget: a
        # hung replica must not stall the whole loop for long
        probe = self._replica_client(
            replica, read_timeout_s=max(
                4 * self.health_interval_s, 1.0))
        try:
            payload = probe.healthz()
        except (GatewayError, *RETRYABLE_ERRORS):
            self._note_failure(replica)
            return
        self._note_alive(replica, payload)
        if scrape_metrics and replica.state == "live":
            try:
                gauges = parse_prometheus(probe.metrics())
            except (GatewayError, *RETRYABLE_ERRORS):
                return  # healthz just succeeded; not a breaker event
            with self._lock:
                if "serving_gateway_queue_depth" in gauges:
                    replica.queue_depth = int(
                        gauges["serving_gateway_queue_depth"])
                if "serving_gateway_active_slots" in gauges:
                    replica.active_slots = int(
                        gauges["serving_gateway_active_slots"])
                if "serving_prefill_tokens_skipped" in gauges:
                    replica.prefix_tokens_reused = int(
                        gauges["serving_prefill_tokens_skipped"])

    def _note_alive(self, replica: _Replica,
                    payload: Dict[str, Any]) -> None:
        with self._lock:
            replica.failures = 0
            if replica.decommissioned:
                return
            replica.state = ("draining"
                             if payload.get("draining") else "live")
            rid = payload.get("replica_id")
            if rid:
                replica.replica_id = str(rid)
            replica.queue_depth = int(payload.get("queued", 0))
            replica.active_slots = int(
                payload.get("active_slots", 0))
            replica.n_slots = int(payload.get("n_slots", 1)) or 1
            replica.prefix_tokens_reused = int(
                payload.get("prefix_tokens_reused", 0))

    def _note_failure(self, replica: _Replica) -> None:
        """One failed health scrape OR data-plane break: the breaker
        counts both, so a dying replica is detected by whichever
        surface hits it first."""
        with self._lock:
            if replica.decommissioned:
                return
            replica.failures += 1
            was = replica.state
            if (replica.failures >= self.failure_threshold
                    or was in ("dead", "half-open")):
                replica.state = "dead"
                replica.next_probe_t = (time.monotonic()
                                        + self.probe_interval_s)
                if was not in ("dead", "half-open"):
                    self.stats["replica_faults"] += 1
                    self.tracer.incr("router_replica_dead")
            elif was == "live":
                replica.state = "degraded"

    # -- routing -------------------------------------------------------
    def _affinity_key(self, prompt: Sequence[int]) -> Optional[bytes]:
        """The prompt's leading block-aligned tokens as a hash key;
        None when the prompt is shorter than one block (nothing worth
        keeping warm)."""
        b = self.affinity_block_tokens
        n = (len(prompt) // b) * b
        if n < b:
            return None
        return ",".join(str(int(t)) for t in prompt[:n]).encode()

    @staticmethod
    def _rendezvous_score(key: bytes, replica_id: str) -> int:
        return int.from_bytes(
            hashlib.blake2b(key + b"|" + replica_id.encode(),
                            digest_size=8).digest(), "big")

    def _pick(self, prompt: Sequence[int],
              exclude: Set[str]) -> Tuple[_Replica, bool]:
        """Choose the replica for one (re)submission and claim one
        unit of its in-flight budget (``open_entries`` — the caller
        MUST release it when the attempt ends). Returns ``(replica,
        by_affinity)``. Raises :class:`_AllBackedOff` when every
        candidate is parked behind a 429 hint, :class:`_NoReplica`
        when nothing can serve at all.

        Affinity is BOUNDED-LOAD: rendezvous ranks the candidates for
        the prompt's prefix key, and the pick walks DOWN the ranking
        past replicas whose router-side in-flight count has reached
        their slot count. Pure rendezvous splits K distinct keys
        binomially — with 8 concurrent streams over 2 replicas a 6/2
        split is routine, and the overflow requests would queue a full
        generation behind busy slots while the sibling idles (measured
        0.61× direct on the bench before the bound). Walking the
        ranking keeps overflow DETERMINISTIC per key (the second-
        ranked replica, not a random sibling), so a key's overflow
        cache-warms one predictable place. The bound uses the
        router's OWN live accounting (claimed at pick time under the
        lock), not the scraped load — scrapes lag a burst by a whole
        health interval."""
        now = time.monotonic()
        with self._lock:
            def usable(r, state):
                return (r.state == state and not r.decommissioned
                        and r.address not in exclude)

            live = [r for r in self._replicas if usable(r, "live")]
            ready = [r for r in live if now >= r.backoff_until]
            if not ready:
                # degraded replicas are a LAST resort: recent
                # failures, but the breaker hasn't opened
                degraded = [r for r in self._replicas
                            if usable(r, "degraded")
                            and now >= r.backoff_until]
                if degraded:
                    ready = degraded
                elif live:
                    raise _AllBackedOff(
                        min(r.backoff_until for r in live) - now)
                else:
                    raise _NoReplica()
            key = self._affinity_key(prompt)
            if key is not None:
                ranked = sorted(
                    ready, reverse=True,
                    key=lambda r: self._rendezvous_score(
                        key, r.replica_id))
                chosen = next(
                    (r for r in ranked
                     if r.open_entries < max(r.n_slots, 1)),
                    ranked[0])  # all saturated: stay sticky
                by_affinity = True
                if chosen is ranked[0]:
                    self.stats["affinity_routed"] += 1
                else:
                    self.stats["affinity_overflow"] += 1
            else:
                self._rr += 1
                order = (self._rr + i for i in range(len(ready)))
                # live in-flight count first (exact, claimed under
                # this very lock), scraped load as the tiebreak,
                # rotation last
                chosen = min(
                    zip(ready, order),
                    key=lambda p: (p[0].open_entries,
                                   p[0].queue_depth
                                   + p[0].active_slots,
                                   p[1] % len(ready)))[0]
                by_affinity = False
                self.stats["load_routed"] += 1
            chosen.requests_routed += 1
            chosen.open_entries += 1
            return chosen, by_affinity

    # -- journal -------------------------------------------------------
    def _journal_entry(self, prompt: List[int],
                       params: Dict[str, Any]) -> _JournalEntry:
        with self._lock:
            rid = next(self._rids)
            entry = _JournalEntry(rid, prompt, params, self._now())
            entry.note(self._now(), "submitted")
            self._journal[rid] = entry
            # bounded journal: evict oldest DONE entries past the cap
            # (open entries are never evicted — they are the crash
            # ledger)
            if len(self._journal) > self.journal_cap:
                for old_rid in list(self._journal):
                    if len(self._journal) <= self.journal_cap:
                        break
                    old = self._journal[old_rid]
                    if old.done.is_set():
                        del self._journal[old_rid]
            self.stats["requests"] += 1
            self.tracer.incr("router_requests")
            return entry

    def journal_audit(self) -> Dict[str, Any]:
        """The chaos-soak ledger: per-entry delivery accounting. A
        LOST request is an entry that never reached a terminal; a
        DOUBLE DELIVERY would show as a high-water mark short of the
        token count (some token went out twice without advancing the
        mark — structurally impossible through ``_relay_tokens``, and
        audited anyway)."""
        with self._lock:
            open_rids = [e.rid for e in self._journal.values()
                         if not e.done.is_set()]
            replayed = [e.rid for e in self._journal.values()
                        if e.replays > 0]
            return {
                "entries": len(self._journal),
                "open": open_rids,
                "replayed": replayed,
                "lost": [e.rid for e in self._journal.values()
                         if e.done.is_set() and e.result is None],
            }

    # -- the proxy / replay core ---------------------------------------
    def _result_of(self, entry: _JournalEntry,
                   terminal: Dict[str, Any]) -> Dict[str, Any]:
        """Client-facing terminal: the replica's result re-keyed to
        the ROUTER's request id, tokens replaced by the journal's
        high-water view (identical for healthy terminals — asserted
        by the dedup walk — and the authoritative partial list for
        faults), plus the router's replay accounting."""
        out = dict(terminal)
        out.pop("done", None)
        out["id"] = entry.rid
        out["tokens"] = list(entry.tokens)
        out["replays"] = entry.replays
        return out

    def _fault_terminal(self, entry: _JournalEntry,
                        reason: str = "fault",
                        status: int = 500) -> Dict[str, Any]:
        return {"id": entry.rid, "tokens": list(entry.tokens),
                "finish_reason": reason, "status": status,
                "prompt_len": len(entry.prompt),
                "replays": entry.replays}

    def _finish(self, entry: _JournalEntry,
                result: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            entry.result = result
            entry.note(self._now(),
                       f"terminal:{result.get('finish_reason')}")
            entry.done.set()
            if result.get("finish_reason") == "fault":
                self.stats["request_faults"] += 1
                self.tracer.incr("router_request_faults")
        return result

    def _relay_tokens(self, entry: _JournalEntry, tokens: List[int],
                      seen: int) -> Tuple[int, List[int]]:
        """Advance one attempt's stream position through a delta.
        Tokens at positions the client already has are CHECKED against
        the journal (greedy replay must regenerate the exact streamed
        prefix) and dropped; tokens past the high-water mark extend
        the journal and are returned for delivery. This is the
        cross-process version of the engine's ``delta_sent`` dedup."""
        fresh: List[int] = []
        for t in tokens:
            t = int(t)
            seen += 1
            if seen <= len(entry.tokens):
                if t != entry.tokens[seen - 1]:
                    raise _ReplayDiverged(
                        f"request {entry.rid}: replay token {t} at "
                        f"position {seen - 1} != streamed "
                        f"{entry.tokens[seen - 1]}")
            else:
                entry.tokens.append(t)
                fresh.append(t)
        return seen, fresh

    def _ping_sleep(self, total_s: float, forward_ping) -> None:
        """Sleep ``total_s`` in ``keepalive_s`` slices, forwarding a
        keep-alive to the client before each slice — a replay wait
        must not look like a dead connection."""
        end = time.monotonic() + total_s
        while True:
            forward_ping()
            left = end - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(left, self.keepalive_s))

    def _attempt(self, entry: _JournalEntry, replica: _Replica,
                 client: GatewayClient, by_affinity: bool, emit,
                 forward_ping
                 ) -> Tuple[Optional[Dict[str, Any]], bool]:
        """One streaming attempt against one replica. Returns
        ``(terminal, diverged)``; ``terminal is None`` means the
        stream ended WITHOUT a terminal event (replica death or drain
        handback — the replay policy in ``_run_entry`` decides what
        that means). Raises :class:`_RouteAround` when the attempt
        never started streaming (submit rejected/unreachable — try a
        sibling, no replay charged) and :class:`_ClientGone` when the
        router's own client vanished mid-relay."""
        try:
            stream = client.stream(entry.prompt, **entry.params)
        except GatewayError as e:
            if e.status == 429:
                # backpressure, not failure: park the replica for the
                # hinted window and try a sibling NOW
                with self._lock:
                    replica.backoff_until = (time.monotonic()
                                             + (e.retry_after_s or 1))
                    self.stats["rerouted_429"] += 1
                    self.tracer.incr("router_rerouted_429")
                raise _RouteAround() from e
            if e.status == 503:
                # draining/closed: the health loop will catch up;
                # route around it meanwhile
                raise _RouteAround() from e
            # a deterministic rejection (400 bad params): replaying
            # elsewhere would just repeat it — relay to the client
            raise _RouteAround(deterministic={
                "id": entry.rid, "tokens": [],
                "finish_reason": "error", "status": e.status,
                "error": e.payload.get("error"),
                "replays": entry.replays}) from e
        except RETRYABLE_ERRORS as e:
            # could not even submit: breaker event, try a sibling
            self._note_failure(replica)
            raise _RouteAround() from e
        with self._lock:
            entry.replica_address = replica.address
            entry.replica_rid = stream.id
            entry.note(self._now(),
                       f"routed:{replica.replica_id}"
                       f"{':affinity' if by_affinity else ''}"
                       f":rid={stream.id}")
        terminal: Optional[Dict[str, Any]] = None
        diverged = False
        seen = 0
        try:
            if entry.cancelled and stream.id is not None:
                # cancel raced the submit: forward it now that the
                # replica-side id exists
                with contextlib.suppress(Exception):
                    client.cancel(stream.id)
            for kind, event in stream.raw_events():
                if kind == "ping":
                    forward_ping()
                    continue
                toks = event.get("tokens")
                if toks and not event.get("done"):
                    seen, fresh = self._relay_tokens(
                        entry, toks, seen)
                    if fresh:
                        emit(fresh)
                    continue
                if event.get("done"):
                    # the terminal may carry committed tokens the
                    # per-delta events did not (flushed tail) — run
                    # them through the same dedup before trusting it
                    if toks and len(toks) >= len(entry.tokens):
                        _, fresh = self._relay_tokens(
                            entry, toks, 0)
                        if fresh:
                            emit(fresh)
                    terminal = event
                    break
        except _ClientGone:
            raise  # _stream_response cancels; not a replica event
        except _ReplayDiverged:
            diverged = True
        except (*RETRYABLE_ERRORS, ValueError):
            # mid-stream death (or a torn frame from a dying peer):
            # the replay policy decides
            terminal = None
        finally:
            stream.close()
        return terminal, diverged

    def _run_entry(self, entry: _JournalEntry, emit,
                   forward_ping) -> Dict[str, Any]:
        """Drive one journaled request to its terminal: route, relay,
        and — on replica death or drain handback — replay onto a
        survivor with high-water dedup. ``emit(tokens)`` delivers
        fresh tokens to the client (SSE event or blocking
        accumulator); ``forward_ping()`` relays replica keep-alives.
        Returns the client-facing terminal dict (also journaled)."""
        exclude: Set[str] = set()
        attempts = 0
        while True:
            if entry.cancelled:
                return self._finish(
                    entry, self._fault_terminal(
                        entry, "cancelled", 499))
            attempts += 1
            if attempts > self.max_replays + 2 * len(self._replicas):
                # absolute bound on the route-submit loop: repeated
                # submit-time connection failures (distinct from
                # replays, which count mid-stream deaths)
                return self._finish(entry,
                                    self._fault_terminal(entry))
            try:
                replica, by_affinity = self._pick(entry.prompt,
                                                  exclude)
            except _AllBackedOff as e:
                if not entry.tokens:
                    wait = max(1, int(e.wait_s + 0.999))
                    return self._finish(entry, {
                        "id": entry.rid, "tokens": [],
                        "finish_reason": "shed", "status": 429,
                        "prompt_len": len(entry.prompt),
                        "retry_after_s": wait,
                        "replays": entry.replays})
                # mid-replay with streamed tokens: waiting is better
                # than faulting — the backoff hints are short. The
                # wait is pinged at keepalive_s cadence: the CLIENT
                # connection sees no replica traffic during this gap,
                # and a silent gap longer than its read timeout would
                # drop a request that was about to complete
                self._ping_sleep(min(max(e.wait_s, 0.05), 2.0),
                                 forward_ping)
                exclude.clear()
                continue
            except _NoReplica:
                if exclude:
                    # every healthy replica is excluded from THIS
                    # request (each failed it once): clear and let the
                    # state machine filter instead
                    exclude.clear()
                    continue
                return self._finish(entry, {
                    "id": entry.rid, "tokens": list(entry.tokens),
                    "finish_reason": ("fault" if entry.tokens
                                      else "shed"),
                    "status": (500 if entry.tokens else 503),
                    "prompt_len": len(entry.prompt),
                    "replays": entry.replays})
            entry.affinity = entry.affinity or by_affinity
            client = self._replica_client(replica)
            try:
                # _pick claimed one unit of the replica's in-flight
                # budget; the outer finally releases it however this
                # attempt ends (bounded-load affinity reads it live)
                terminal, diverged = self._attempt(
                    entry, replica, client, by_affinity, emit,
                    forward_ping)
            except _RouteAround as ra:
                exclude.add(replica.address)
                if ra.deterministic is not None:
                    return self._finish(entry, ra.deterministic)
                continue
            finally:
                with self._lock:
                    replica.open_entries -= 1
            if terminal is not None:
                return self._finish(entry,
                                    self._result_of(entry, terminal))
            if diverged:
                entry.note(self._now(), "replay_diverged")
                return self._finish(entry,
                                    self._fault_terminal(entry))
            # ---- the stream ended WITHOUT a terminal ---------------
            if entry.cancelled:
                return self._finish(
                    entry, self._fault_terminal(
                        entry, "cancelled", 499))
            draining = replica.state in ("draining", "dead")
            if not draining:
                # unannounced death: charge the breaker so routing
                # reacts before the next health tick
                self._note_failure(replica)
            if entry.temperature > 0 and entry.tokens:
                # the PR 3/5 contract, across processes: a redrawn
                # sampling stream cannot splice onto the streamed
                # prefix — terminate "fault" with the partial tokens
                entry.note(self._now(), "sampling_fault")
                return self._finish(entry,
                                    self._fault_terminal(entry))
            with self._lock:
                entry.replays += 1
                self.stats["replays"] += 1
                self.tracer.incr("router_replays")
                entry.note(self._now(),
                           f"replay:{entry.replays}:"
                           f"from={replica.replica_id}")
            if entry.replays > self.max_replays:
                return self._finish(entry,
                                    self._fault_terminal(entry))
            # keep the client connection warm across the failover
            # gap (route + resubmit + survivor prefill before its
            # first event)
            forward_ping()
            exclude.add(replica.address)

    # -- endpoint bodies -----------------------------------------------
    def _parse_generate(self, body: Dict[str, Any]
                        ) -> Tuple[List[int], Dict[str, Any]]:
        prompt = [int(t) for t in body.get("prompt", [])]
        params: Dict[str, Any] = {
            "max_new_tokens": int(body.get("max_new_tokens", 16))}
        for knob in ("temperature", "top_k", "eos_id", "deadline_s",
                     "queue_timeout_s"):
            if body.get(knob) is not None:
                params[knob] = body[knob]
        return prompt, params

    def _handle_generate(self, handler: _RouterHandler,
                         stream: bool) -> None:
        try:
            body = handler.read_json()
            if not isinstance(body, dict):
                raise ValueError(f"expected a JSON object, got "
                                 f"{type(body).__name__}")
            prompt, params = self._parse_generate(body)
            if not prompt:
                raise ValueError("empty prompt")
        except (ValueError, TypeError, UnicodeDecodeError) as e:
            handler.send_json({"error": f"bad JSON body: {e}"}, 400,
                              close=True)
            return
        entry = self._journal_entry(prompt, params)
        if stream:
            self._stream_response(handler, entry)
        else:
            self._blocking_response(handler, entry)

    def _blocking_response(self, handler, entry: _JournalEntry
                           ) -> None:
        acc: List[int] = []
        result = self._run_entry(entry, acc.extend, lambda: None)
        headers: Tuple = ()
        if result.get("retry_after_s"):
            headers = (("Retry-After", result["retry_after_s"]),)
        handler.send_json(result, int(result.get("status", 200)),
                          close=True, headers=headers)

    def _stream_response(self, handler, entry: _JournalEntry) -> None:
        with self._lock:
            self.stats["streams"] += 1
        try:
            handler.start_stream("text/event-stream")
            handler.send_event({"id": entry.rid})

            # client-facing writes raise _ClientGone so _run_entry
            # can tell "my client left" apart from "the replica died"
            def emit(tokens: List[int]) -> None:
                try:
                    handler.send_event({"id": entry.rid,
                                        "tokens": tokens})
                except OSError as e:
                    raise _ClientGone() from e

            def ping() -> None:
                try:
                    handler.send_ping()
                except OSError as e:
                    raise _ClientGone() from e

            result = self._run_entry(entry, emit, ping)
            out = dict(result)
            out["done"] = True
            handler.send_event(out)
            handler.end_stream()
        except (_ClientGone, BrokenPipeError, ConnectionResetError,
                OSError):
            # the ROUTER's client vanished: cancel on the replica and
            # close out the journal entry
            with self._lock:
                self.stats["disconnect_cancels"] += 1
                self.tracer.incr("router_disconnect_cancelled")
                entry.cancelled = True
                addr, rrid = entry.replica_address, entry.replica_rid
            if addr is not None and rrid is not None:
                with contextlib.suppress(Exception):
                    GatewayClient(
                        addr,
                        connect_timeout_s=self.replica_connect_timeout_s,
                        read_timeout_s=5.0).cancel(rrid)
            if not entry.done.is_set():
                self._finish(entry, self._fault_terminal(
                    entry, "cancelled", 499))

    def _handle_cancel(self, handler, path: str) -> None:
        tail = path.rsplit("/", 1)[-1]
        try:
            rid = int(tail)
        except ValueError:
            handler.send_json({"error": f"bad request id {tail!r}"},
                              400, close=True)
            return
        with self._lock:
            entry = self._journal.get(rid)
            if entry is not None:
                entry.cancelled = True
                addr, rrid = entry.replica_address, entry.replica_rid
                done = entry.done.is_set()
        if entry is None:
            handler.send_json({"id": rid, "cancelled": False,
                               "done": False}, 404, close=True)
            return
        if not done and addr is not None and rrid is not None:
            with contextlib.suppress(Exception):
                GatewayClient(
                    addr,
                    connect_timeout_s=self.replica_connect_timeout_s,
                    read_timeout_s=5.0).cancel(rrid)
        handler.send_json({"id": rid, "cancelled": not done,
                           "done": done}, 200, close=True)

    def _handle_poll(self, handler, path: str) -> None:
        tail = path.rsplit("/", 1)[-1]
        try:
            rid = int(tail)
        except ValueError:
            handler.send_json({"error": f"bad request id {tail!r}"},
                              400, close=True)
            return
        with self._lock:
            entry = self._journal.get(rid)
            result = entry.result if entry is not None else None
        if result is not None:
            # poll is ALWAYS 200 for a stored result, whatever its
            # mapped generate-time status — the gateway's contract
            handler.send_json(result, 200, close=True)
        elif entry is not None:
            handler.send_json({"id": rid, "running": True}, 202,
                              close=True)
        else:
            handler.send_json({"error": f"unknown request {rid}"},
                              404, close=True)

    # -- health / metrics / admin --------------------------------------
    def replica_status(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.status() for r in self._replicas]

    def _health(self) -> Dict[str, Any]:
        with self._lock:
            statuses = [r.status() for r in self._replicas]
            open_n = sum(1 for e in self._journal.values()
                         if not e.done.is_set())
        routable = any(s["state"] in ("live", "degraded")
                       for s in statuses)
        return {"ok": routable and not self._stopped,
                "state": "stopped" if self._stopped else (
                    "live" if routable else "dead"),
                "replicas": statuses,
                "journal_entries": len(self._journal),
                "journal_open": open_n}

    def _metrics_text(self) -> str:
        with self._lock:
            gauge = getattr(self.tracer, "gauge", self.tracer.counter)
            for key, value in self.stats.items():
                gauge(f"router_{key}", value)
            by_state = {s: 0 for s in REPLICA_STATES}
            for r in self._replicas:
                by_state[r.state] += 1
            for state, n in by_state.items():
                gauge(f"router_replicas_{state.replace('-', '_')}", n)
            gauge("router_journal_open",
                  sum(1 for e in self._journal.values()
                      if not e.done.is_set()))
            return self.tracer.prometheus_text()

    def drain_replica(self, replica_id: str,
                      timeout_s: Optional[float] = None
                      ) -> Dict[str, Any]:
        """Graceful scale-down of one replica: stop routing to it,
        ``/v1/drain`` it (in-flight work settles within the budget),
        and decommission it. Requests the drain could NOT settle end
        their relayed streams without a terminal — their relay loops
        fail over to survivors through the normal replay path, so
        from every client's point of view the requests simply
        continue. Returns the replica's drain summary plus the
        journal entries that were still open on it at drain time."""
        with self._lock:
            matches = [r for r in self._replicas
                       if replica_id in (r.replica_id, r.address)]
            if not matches:
                raise KeyError(f"unknown replica {replica_id!r}")
            replica = matches[0]
            replica.state = "draining"
            handed_off = [e.rid for e in self._journal.values()
                          if not e.done.is_set()
                          and e.replica_address == replica.address]
        try:
            summary = self._replica_client(replica).drain(timeout_s)
        except (GatewayError, *RETRYABLE_ERRORS) as e:
            # failed drain = unplanned death: the breaker path takes
            # over and the same replay machinery rescues the work
            self._note_failure(replica)
            summary = {"drained": False, "error": repr(e)}
        with self._lock:
            replica.state = "dead"
            replica.decommissioned = True
            self.stats["drained_replicas"] += 1
            self.tracer.incr("router_drained_replicas")
        return {"replica_id": replica.replica_id,
                "address": replica.address,
                "open_requests_handed_off": handed_off,
                "drain": summary}

    def _handle_drain_replica(self, handler) -> None:
        try:
            body = handler.read_json()
            replica_id = body["replica_id"]
            timeout = body.get("timeout_s")
            timeout = None if timeout is None else float(timeout)
        except (ValueError, KeyError, TypeError, AttributeError,
                UnicodeDecodeError) as e:
            handler.send_json({"error": f"bad drain body: {e}"}, 400,
                              close=True)
            return
        try:
            summary = self.drain_replica(replica_id, timeout)
        except KeyError as e:
            handler.send_json({"error": str(e)}, 404, close=True)
            return
        handler.send_json(summary, 200, close=True)
