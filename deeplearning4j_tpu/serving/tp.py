"""Tensor-parallel sharding context for the serving decode engine
(ISSUE 12 tentpole).

Training composes dp/tp/pp/sp/fsdp/ep over the device mesh, but until
this module the decode engine ran every executable on one chip. Here
the engine's jitted computations — prefill, chunked continuation,
decode, speculative verify, paged scatter, health, block movers —
become **fully-manual ``shard_map`` programs** over a ``tp`` mesh axis
(``parallel/mesh.py:make_mesh`` + ``util/jax_compat.py:shard_map``,
the same machinery the trainers ride), sharded Megatron-style over
attention heads:

- **params**: attention ``Wq``/``Wk``/``Wv`` column-sliced
  (``P(None, "tp")`` — each shard owns ``n_heads/TP`` whole heads),
  ``Wo`` row-sliced (``P("tp", None)``); everything else replicated.
  The layer body runs on local heads and all-reduces the output
  projection once (``nn/layers/attention.py:tp_head_shards``).
- **KV state**: every cache leaf shards on its HEAD axis — dense rows
  ``[B, H, W, dh]`` at ``P(None, "tp", None, None)``, paged pool
  blocks ``[n_blocks, block_tokens, H, dh]`` at
  ``P(None, None, "tp", None)`` — so per-shard KV bytes are exactly
  ``total / TP``, which is what lets a model whose KV working set
  exceeds one chip serve at all.
- **host bookkeeping is layout-invariant**: block ids, refcounts,
  CoW, quarantine, the radix trie, and the snapshot wire format never
  see the head axis, so ``BlockTable``/``PagedPrefixCache``/the PR 6
  pressure ladder work unchanged, and a snapshot taken at one TP
  width restores at any other (device state is rebuilt by re-prefill).

Everything the host reads back (sampled tokens, acceptance counts,
health verdicts) is REPLICATED across shards by construction: logits
are completed by the psum before sampling, and the health reduction
all-reduces its verdict, so the engine's control flow — and therefore
greedy ids — is bit-identical to the single-chip engine at the argmax
level (the PR 6 paged-parity convention; gated by
tests/test_serving_tp.py and the ``bench_decode_tp`` row).

In-spec/out-spec pytrees are derived from leaf KEY PATHS at trace
time (``pk``/``pv``/``k``/``v`` under an attention layer's key ride
the head sharding; everything else replicates), so the polymorphic
cache dicts — dense rows during a cold paged admission, paged dicts
with ring tables during decode — wrap without per-structure plumbing.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn.layers.attention import tp_head_shards
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.util.jax_compat import shard_map

#: attention param leaf -> (sharded axis index, spec) under head
#: sharding; params not listed (biases, LN, FFN, Wi) replicate
_ATTN_PARAM_SPECS = {
    "Wq": P(None, "tp"),
    "Wk": P(None, "tp"),
    "Wv": P(None, "tp"),
    "Wo": P("tp", None),
}


def _key_name(entry) -> Optional[str]:
    """The string key of one pytree path entry (DictKey across the
    jax versions this tree supports)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


class TPContext:
    """One engine's tensor-parallel execution context.

    ``attn_keys`` are the param/rnn-state pytree keys of the net's
    attention layers (layer index strings for a MultiLayerNetwork,
    vertex names for a ComputationGraph) — the ONLY subtrees whose
    leaves shard; a leaf named ``Wq`` anywhere else replicates.
    """

    def __init__(self, tp: int, attn_keys: Sequence[str],
                 axis: str = "tp", devices=None):
        if tp < 1:
            raise ValueError(f"tp {tp} < 1")
        n_dev = len(devices if devices is not None else jax.devices())
        if tp > n_dev:
            raise ValueError(
                f"tp {tp} exceeds the {n_dev} visible devices")
        self.size = int(tp)
        self.axis = axis
        self.attn_keys = frozenset(str(k) for k in attn_keys)
        self.mesh = make_mesh({axis: self.size}, devices)

    # -- spec derivation -----------------------------------------------
    def _norm(self, axes) -> P:
        """Drop trailing Nones: ``P(None, None, "tp", None)`` and
        ``P(None, None, "tp")`` mean the same sharding but hash as
        DIFFERENT jit cache keys — executables returning the
        normalized form would retrace against operands placed under
        the verbose one (one extra decode compile per engine, caught
        by the compile-count gate)."""
        axes = list(axes)
        while axes and axes[-1] is None:
            axes.pop()
        return P(*axes)

    def _leaf_spec(self, path, leaf) -> P:
        names = [_key_name(p) for p in path]
        last = names[-1] if names else None
        under_attn = any(n in self.attn_keys for n in names[:-1])
        if under_attn:
            if last in _ATTN_PARAM_SPECS and getattr(
                    leaf, "ndim", 0) == 2:
                spec = _ATTN_PARAM_SPECS[last]
                return self._norm(self.axis if a == "tp" else None
                                  for a in spec)
            if last in ("pk", "pv") and getattr(leaf, "ndim", 0) == 4:
                # paged pool blocks [n_blocks, block_tokens, H, dh]
                return self._norm((None, None, self.axis, None))
            if last in ("k", "v") and getattr(leaf, "ndim", 0) == 4:
                # dense cache rows [B, H, W, dh]
                return self._norm((None, self.axis, None, None))
        return P()

    def spec_tree(self, tree):
        """PartitionSpec pytree for any engine operand/output tree,
        derived from leaf key paths (see module docstring)."""
        return jax.tree_util.tree_map_with_path(self._leaf_spec, tree)

    def sharding_tree(self, tree):
        return jax.tree_util.tree_map_with_path(
            lambda p, leaf: NamedSharding(self.mesh,
                                          self._leaf_spec(p, leaf)),
            tree)

    # -- placement ------------------------------------------------------
    def place(self, tree):
        """Commit a host/device pytree onto the mesh under its derived
        sharding (params at init, fresh KV pools at first admission) —
        so the wrapped executables never pay a resharding transfer."""
        return jax.tree_util.tree_map_with_path(
            lambda p, leaf: jax.device_put(
                leaf, NamedSharding(self.mesh,
                                    self._leaf_spec(p, leaf))),
            tree)

    def replicate(self, host_array):
        """Commit one host array onto the mesh fully replicated. The
        engine's per-round table/base/floor/filled operands must enter
        every dispatch with the SAME (committed) sharding: a spec
        round chains the verify executable's OUTPUT pool (committed
        ``P()`` leaves) into the decode dispatch, while a plain round
        builds the operands fresh on the host — uncommitted vs
        committed hash as different jit keys, which cost the spec+tp
        engine a second decode lowering (caught by the compile-budget
        gate). Called per layer on the HOST array so every layer gets
        a distinct buffer (the donated dispatches reject one buffer
        aliased through two pytree leaves)."""
        return jax.device_put(host_array,
                              NamedSharding(self.mesh, P()))

    # -- shard_map wrapping --------------------------------------------
    def wrap(self, fn, donate_argnums=()):
        """The TP analogue of ``jax.jit(fn)``: the SAME engine step
        function becomes a fully-manual shard_map program over the tp
        axis, with in/out specs derived per leaf key path at trace
        time and the attention layers switched onto local heads + the
        output-projection all-reduce via ``tp_head_shards``. The
        jitted wrapper keeps the engine's compile-count discipline
        (``_cache_size`` reads through)."""
        axis, size, mesh = self.axis, self.size, self.mesh

        def sharded(*args):
            in_specs = tuple(self.spec_tree(a) for a in args)
            out_struct = jax.eval_shape(fn, *args)
            out_specs = self.spec_tree(out_struct)

            def body(*local):
                with tp_head_shards(axis, size):
                    return fn(*local)

            return shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             check_vma=False)(*args)

        return jax.jit(sharded, donate_argnums=donate_argnums)

    def all_ok(self, ok):
        """Combine a per-shard boolean verdict across shards (health
        sweeps must agree fleet-wide: a NaN lives on ONE shard's head
        slice but poisons the whole row/block)."""
        return jax.lax.psum(jnp.asarray(ok, jnp.int32),
                            self.axis) >= self.size

    # -- accounting -----------------------------------------------------
    def shard_bytes(self, tree) -> Dict[int, int]:
        """Per-shard addressable KV bytes of a (sharded) pytree — the
        ``total/TP`` acceptance arithmetic and the per-shard
        ``serving_tp_kv_bytes`` gauges read this."""
        per: Dict[int, int] = {i: 0 for i in range(self.size)}
        for leaf in jax.tree_util.tree_leaves(tree):
            shards = getattr(leaf, "addressable_shards", None)
            if shards is None:
                continue
            seen = set()
            for s in shards:
                dev = s.device.id
                idx = self._device_shard_index(dev)
                if idx is None or (idx, id(leaf)) in seen:
                    continue
                seen.add((idx, id(leaf)))
                per[idx] += int(np.prod(s.data.shape)
                                * s.data.dtype.itemsize)
        return per

    def _device_shard_index(self, device_id: int) -> Optional[int]:
        for i, dev in enumerate(self.mesh.devices.flat):
            if dev.id == device_id:
                return i
        return None
