"""KV transfer plane: cross-replica shipping of warmed KV blocks
(ISSUE 14 tentpole — ROADMAP item 2b, the DistServe-style half).

A prefix warmed on one replica is cold everywhere else, so affinity
misses, failover replay, and rolling-upgrade warmup all recompute the
full prompt on the receiver — correct (the PR 9 replay discipline),
but wrong for long-prompt traffic at fleet scale. The paged engine
already gives KV a serializable block-granular identity
(:class:`~deeplearning4j_tpu.serving.block_pool.BlockTable` + pool
block slices), so a warmed prefix can be a fleet-level resource:

- **Export** (:func:`export_prefix`): the donor looks the prompt up
  in its radix trie, slices the entry's referenced pool blocks out of
  device memory, and frames them as one binary payload
  (:func:`pack_prefix`). The wire format is LAYOUT-INVARIANT: a TP=N
  donor's head-sliced blocks reassemble to full logical
  ``[n, block_tokens, H, dh]`` arrays on the host (the PR 12
  host-bookkeeping contract — block ids and tables never saw the
  head axis), so any receiver width can import them.
- **Import** (:func:`import_prefix`): the receiver validates the
  frame against its own geometry (block size, layer set, head/dh
  shape, dtype, window), allocates fresh pool blocks (evicting LRU
  trie entries if needed — never preempting a live slot for a cache
  import), scatters the shipped slices in through ONE jitted
  executable per pow2 block-count bucket, and seeds its radix trie
  via the existing zero-copy ``insert_blocks`` path. From that moment
  the imported prefix is indistinguishable from a locally-computed
  one: the next admission splices it with the same CoW machinery,
  and greedy ids are bit-identical to a local prefill (gated by
  tests/test_kv_transfer.py across TP widths).

Correctness never depends on a transfer succeeding: every decline or
malformed frame surfaces as ``imported: False`` (or a
:class:`KVTransferError` the HTTP layer maps to 400) and the caller —
the router's warm-import hook, the controller's upgrade warmup —
falls back to full recompute.

Wire format (version 1)::

    b"DKV1" | u32 version | u32 header_len | header JSON | buffers

The header carries the covered prefix's token ids (the radix-trie
key), the block geometry, and per-layer dtype/shape; the buffers are
each layer's selected ``pk`` then ``pv`` blocks, C-contiguous, in
ascending logical-block order. Every size is validated against the
header before any buffer is touched, so a truncated payload (the
soak's injected fault) fails loudly instead of importing garbage.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"DKV1"
WIRE_VERSION = 1

#: default HTTP-facing payload cap (bytes): large enough for a long
#: prompt's blocks on a real model slice, small enough that a hostile
#: Content-Length cannot balloon the handler (the gateway's
#: ``kv_transfer_cap_bytes`` knob overrides per deployment)
DEFAULT_CAP_BYTES = 64 << 20


class KVTransferError(ValueError):
    """A payload failed structural validation (bad magic, truncated
    buffers, geometry mismatch): the HTTP layer answers 400 and the
    caller falls back to recompute."""


class KVTransferTooLarge(KVTransferError):
    """An export would exceed the transfer cap — detected from the
    block count and leaf shapes BEFORE any device gather runs, so an
    over-cap prompt costs arithmetic, not a wasted device-to-host
    copy under the engine lock. The HTTP layer answers 413."""


def pack_prefix(tokens: Sequence[int], blocks: Sequence[int],
                floor: int, block_tokens: int,
                layers: List[Tuple[str, np.ndarray, np.ndarray]]
                ) -> bytes:
    """Frame one warmed prefix: ``tokens`` is the covered prefix
    (the radix-trie key the receiver re-inserts under), ``blocks``
    the ascending logical block indices covering
    ``[floor, len(tokens))``, ``layers`` a list of
    ``(name, pk [n, bt, H, dh], pv [n, bt, H, dh])`` host arrays in a
    stable order."""
    header: Dict[str, Any] = {
        "block_tokens": int(block_tokens),
        "floor": int(floor),
        "length": len(tokens),
        "tokens": [int(t) for t in tokens],
        "blocks": [int(g) for g in blocks],
        "layers": [],
    }
    buffers: List[bytes] = []
    for name, pk, pv in layers:
        pk = np.ascontiguousarray(pk)
        pv = np.ascontiguousarray(pv)
        if pk.shape != pv.shape or pk.ndim != 4:
            raise KVTransferError(
                f"layer {name}: pk/pv shapes {pk.shape}/{pv.shape} "
                "are not matching [n, bt, H, dh] block stacks")
        header["layers"].append({
            "name": str(name),
            "dtype": str(pk.dtype),
            "heads": int(pk.shape[2]),
            "dh": int(pk.shape[3]),
            "nbytes": int(pk.nbytes),
        })
        buffers.append(pk.tobytes())
        buffers.append(pv.tobytes())
    head = json.dumps(header).encode()
    return b"".join([MAGIC, struct.pack("<II", WIRE_VERSION,
                                        len(head)), head] + buffers)


def unpack_prefix(payload: bytes) -> Dict[str, Any]:
    """Parse + validate one framed payload back to
    ``{"header": {...}, "layers": {name: (pk, pv)}}`` host arrays.
    Raises :class:`KVTransferError` on ANY structural problem —
    magic, version, header JSON, or buffer sizes that disagree with
    the header (the truncated-payload fault the soak injects)."""
    if len(payload) < len(MAGIC) + 8:
        raise KVTransferError(
            f"payload too short ({len(payload)} bytes)")
    if payload[:len(MAGIC)] != MAGIC:
        raise KVTransferError("bad magic (not a KV transfer frame)")
    version, head_len = struct.unpack_from("<II", payload, len(MAGIC))
    if version != WIRE_VERSION:
        raise KVTransferError(f"unsupported wire version {version}")
    off = len(MAGIC) + 8
    if off + head_len > len(payload):
        raise KVTransferError("truncated header")
    try:
        header = json.loads(payload[off:off + head_len])
    except ValueError as e:
        raise KVTransferError(f"bad header JSON: {e}") from None
    off += head_len
    for key in ("block_tokens", "floor", "length", "tokens",
                "blocks", "layers"):
        if key not in header:
            raise KVTransferError(f"header missing {key!r}")
    bt = int(header["block_tokens"])
    length = int(header["length"])
    floor = int(header["floor"])
    tokens = [int(t) for t in header["tokens"]]
    blocks = [int(g) for g in header["blocks"]]
    if bt < 1 or length < 1 or not tokens or len(tokens) != length:
        raise KVTransferError(
            f"inconsistent prefix: length {length}, "
            f"{len(tokens)} tokens")
    if not 0 <= floor < length:
        raise KVTransferError(f"floor {floor} outside [0, {length})")
    want = list(range(floor // bt, (length - 1) // bt + 1))
    if blocks != want:
        raise KVTransferError(
            f"blocks {blocks} do not contiguously cover "
            f"[{floor}, {length}) at block_tokens={bt}")
    layers: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    n = len(blocks)
    for spec in header["layers"]:
        name = str(spec["name"])
        try:
            dtype = np.dtype(str(spec["dtype"]))
        except TypeError as e:
            raise KVTransferError(
                f"layer {name}: unknown dtype "
                f"{spec.get('dtype')!r}: {e}") from None
        heads, dh = int(spec["heads"]), int(spec["dh"])
        if heads < 1 or dh < 1:
            # validated BEFORE the nbytes arithmetic: a negative pair
            # multiplies back to a "consistent" byte count and would
            # surface as a bare reshape ValueError instead of the
            # KVTransferError contract the HTTP 400 mapping rides
            raise KVTransferError(
                f"layer {name}: non-positive heads/dh "
                f"({heads}, {dh})")
        nbytes = int(spec["nbytes"])
        if nbytes != n * bt * heads * dh * dtype.itemsize:
            raise KVTransferError(
                f"layer {name}: declared {nbytes} bytes != "
                f"{n}x{bt}x{heads}x{dh} {dtype} blocks")
        if off + 2 * nbytes > len(payload):
            raise KVTransferError(
                f"truncated payload at layer {name}: need "
                f"{2 * nbytes} more bytes, "
                f"{len(payload) - off} remain")
        shape = (n, bt, heads, dh)
        pk = np.frombuffer(payload, dtype, n * bt * heads * dh,
                           off).reshape(shape)
        off += nbytes
        pv = np.frombuffer(payload, dtype, n * bt * heads * dh,
                           off).reshape(shape)
        off += nbytes
        layers[name] = (pk, pv)
    if off != len(payload):
        raise KVTransferError(
            f"{len(payload) - off} trailing bytes after the declared "
            "buffers")
    header["tokens"] = tokens
    header["blocks"] = blocks
    return {"header": header, "layers": layers}


# -- engine-side export / import --------------------------------------

def export_prefix(engine, prompt: Sequence[int],
                  cap_bytes: Optional[int] = None) -> Optional[bytes]:
    """Serialize the longest cached prefix of ``prompt`` from
    ``engine``'s paged radix trie (None when nothing reusable is
    cached, or the engine is not paged / has no pool yet). The lease
    taken by the lookup pins the entry while the device blocks are
    sliced to host; device arrays are immutable, so the snapshot is
    consistent even against concurrent rounds. Per-shard aware by
    construction: ``np.asarray`` on a TP-sharded pool leaf reassembles
    the full logical array (host bookkeeping never sees the head
    axis), so the payload is identical at any donor width.
    ``cap_bytes`` raises :class:`KVTransferTooLarge` from the block
    arithmetic alone — before any device work runs."""
    from deeplearning4j_tpu.serving.prefix_cache import PagedPrefixCache

    if (not engine.paged_kv or engine._pool is None
            or not isinstance(engine.prefix_cache, PagedPrefixCache)):
        return None
    hit = engine.prefix_cache.lookup(prompt)
    if hit is None:
        return None
    try:
        tab = engine.prefix_cache.payload(hit.row)
        matched = hit.matched
        if matched <= tab.floor:
            return None
        bt = engine.block_tokens
        want = list(range(tab.floor // bt, (matched - 1) // bt + 1))
        if any(g not in tab.blocks for g in want):
            return None  # entry no longer contiguous: nothing to ship
        bids = [tab.blocks[g] for g in want]
        if cap_bytes is not None:
            buffer_bytes = sum(
                2 * len(bids) * int(np.prod(st["pk"].shape[1:]))
                * st["pk"].dtype.itemsize
                for st in engine._pool.values())
            if buffer_bytes > cap_bytes:
                raise KVTransferTooLarge(
                    f"export of {len(bids)} blocks x "
                    f"{len(engine._pool)} layers needs "
                    f"{buffer_bytes} buffer bytes, over the "
                    f"{cap_bytes}-byte cap")
        # jitted bucketed gather: only the SELECTED blocks cross to
        # host (pow2-padded ids, pad lanes fill zero and are sliced
        # off — one executable per bucket, the import twin's compile
        # discipline), and ``np.asarray`` on the gathered leaves
        # reassembles TP head shards to full logical blocks
        import jax.numpy as jnp

        with engine._span("serving.kv_export", matched=matched,
                          blocks=len(bids)):
            width = _pow2_bucket(len(bids))
            ids = np.full(width, engine.kv_blocks, np.int32)
            ids[:len(bids)] = bids
            gathered = engine._kv_gather_jit(engine._pool,
                                             jnp.asarray(ids))
            layers: List[Tuple[str, np.ndarray, np.ndarray]] = []
            for name in sorted(gathered):
                st = gathered[name]
                pk = np.asarray(st["pk"])[:len(bids)]
                pv = np.asarray(st["pv"])[:len(bids)]
                layers.append((name, pk, pv))
            payload = pack_prefix([int(t) for t in prompt[:matched]],
                                  want, tab.floor, bt, layers)
        engine.stats["kv_exports"] = engine.stats.get(
            "kv_exports", 0) + 1
        engine.stats["kv_exported_tokens"] = engine.stats.get(
            "kv_exported_tokens", 0) + (matched - tab.floor)
        if engine.tracer is not None:
            engine.tracer.incr("serving_kv_exports")
            engine.tracer.incr("serving_kv_exported_tokens",
                               matched - tab.floor)
        return payload
    finally:
        engine.prefix_cache.release(hit)


def _pow2_bucket(n: int, lo: int = 1) -> int:
    b = max(lo, 1)
    while b < n:
        b <<= 1
    return b


def import_prefix(engine, payload: bytes) -> Dict[str, Any]:
    """Splice a shipped prefix into ``engine``'s pool + radix trie.
    Returns a summary dict; ``imported`` is False on any DECLINE
    (already warm, pool pressure, trie full) — soft outcomes the
    caller treats as "stay cold". Structural problems (bad frame,
    geometry mismatch with this engine) raise
    :class:`KVTransferError` instead: those are deployment bugs the
    HTTP layer maps to 400, and recompute still covers correctness."""
    from deeplearning4j_tpu.serving.prefix_cache import PagedPrefixCache

    if not engine.paged_kv or not isinstance(engine.prefix_cache,
                                             PagedPrefixCache):
        raise KVTransferError(
            "receiver is not a paged engine with a prefix trie "
            "(paged_kv=True + prefix_cache_rows required)")
    parsed = unpack_prefix(payload)
    header, shipped = parsed["header"], parsed["layers"]
    bt = int(header["block_tokens"])
    if bt != engine.block_tokens:
        raise KVTransferError(
            f"block_tokens mismatch: payload {bt} vs engine "
            f"{engine.block_tokens}")
    tokens = header["tokens"]
    bad = [t for t in tokens if not 0 <= t < engine.vocab]
    if bad:
        raise KVTransferError(
            f"prefix ids {bad[:4]} outside vocab [0, {engine.vocab})")
    length, floor = int(header["length"]), int(header["floor"])
    if length - floor > engine._wmax:
        raise KVTransferError(
            f"prefix spans {length - floor} tokens, wider than the "
            f"receiver's cache window ({engine._wmax})")
    if engine._pool is None:
        # a freshly booted receiver has no device pool yet (it
        # allocates lazily at first admission): establish it through
        # the regular prefill path — one tiny prefill at the minimum
        # bucket, the same executable the first cold admission pays
        rnn, _ = engine._prefill_sequence([0])
        engine._ensure_paged_pool(rnn)
    if set(shipped) != set(engine._pool):
        raise KVTransferError(
            f"layer set mismatch: payload {sorted(shipped)} vs "
            f"engine {sorted(engine._pool)}")
    for name, (pk, _pv) in shipped.items():
        leaf = engine._pool[name]["pk"]
        if pk.shape[1:] != tuple(leaf.shape[1:]):
            raise KVTransferError(
                f"layer {name}: shipped block shape "
                f"{pk.shape[1:]} != receiver {tuple(leaf.shape[1:])}")
        if str(pk.dtype) != str(leaf.dtype):
            raise KVTransferError(
                f"layer {name}: shipped dtype {pk.dtype} != "
                f"receiver {leaf.dtype}")
    n = len(header["blocks"])

    def result(imported: bool, reason: str) -> Dict[str, Any]:
        return {"imported": imported, "reason": reason,
                "prefix_len": length, "tokens": length - floor,
                "blocks": n}

    # already at least as warm: the trie holds this exact prefix (or
    # a longer one through it) — re-importing would duplicate blocks
    node, depth = engine.prefix_cache._walk(tuple(tokens))
    if depth == len(tokens) and (
            node.row is not None
            or engine.prefix_cache._shallowest_stored(node)
            is not None):
        engine.stats["kv_import_declined"] = engine.stats.get(
            "kv_import_declined", 0) + 1
        return result(False, "already_warm")
    # allocation may evict LRU trie entries but must NEVER preempt a
    # live slot: an import is a cache fill, not admitted work
    if not engine._paged_reserve(n, protect=set(range(engine.n_slots))):
        engine.stats["kv_import_declined"] = engine.stats.get(
            "kv_import_declined", 0) + 1
        return result(False, "no_blocks")
    from deeplearning4j_tpu.serving.block_pool import BlockTable

    import jax.numpy as jnp

    tab = BlockTable(bt, length=length, floor=floor)
    for g in header["blocks"]:
        bid = engine.block_pool.alloc()
        if bid is None:  # _paged_reserve just guaranteed n frees
            raise AssertionError("reserved kv-import alloc failed")
        tab.blocks[g] = bid
    # pad to the pow2 bucket so repeat imports share executables
    # (O(log max-blocks) compiles, the engine's standing discipline);
    # pad ids land out of range and drop inside the scatter
    width = _pow2_bucket(n)
    ids = np.full(width, engine.kv_blocks, np.int32)
    ids[:n] = [tab.blocks[g] for g in header["blocks"]]
    new = {}
    for name in engine._pool:
        pk, pv = shipped[name]
        if width != n:
            pad = ((0, width - n), (0, 0), (0, 0), (0, 0))
            pk = np.pad(pk, pad)
            pv = np.pad(pv, pad)
        new[name] = {"pk": pk, "pv": pv}
    t0 = engine._clock()
    with engine._span("serving.kv_import", prefix_len=length,
                      blocks=n, bytes=len(payload)):
        engine._pool = engine._kv_import_jit(
            engine._pool, new, jnp.asarray(ids))
    ok = engine.prefix_cache.insert_blocks(tokens, tab)
    engine._free_table(tab)
    if not ok:
        engine.stats["kv_import_declined"] = engine.stats.get(
            "kv_import_declined", 0) + 1
        return result(False, "trie_full")
    dt = engine._clock() - t0
    engine.stats["kv_imports"] = engine.stats.get("kv_imports", 0) + 1
    engine.stats["kv_imported_tokens"] = engine.stats.get(
        "kv_imported_tokens", 0) + (length - floor)
    engine.stats["kv_imported_blocks"] = engine.stats.get(
        "kv_imported_blocks", 0) + n
    engine._observe("serving_kv_import_s", dt)
    if engine.tracer is not None:
        engine.tracer.incr("serving_kv_imports")
        engine.tracer.incr("serving_kv_imported_tokens",
                           length - floor)
    return result(True, "imported")
