"""Paged KV memory: ONE device-resident block pool shared by decode
slots and the radix prefix trie (ISSUE 6 tentpole).

The dense serving layout gives every decode slot a whole window-sized
KV row and the prefix cache a SECOND whole-row pool, so concurrency is
bound by ``B x window`` contiguous rows and every prefix hit pays a
full-row ``prefix_fetch`` copy. This module replaces both with the
PagedAttention memory model (Kwon et al. 2023; RadixAttention sharing,
Zheng et al. 2024):

- **Blocks** — the pool is ``kv_blocks`` fixed-size token blocks per
  attention layer (``[n_blocks, block_tokens, H, dh]``); a block holds
  ``block_tokens`` consecutive tokens of exactly one logical sequence.
- **Block tables** — each slot (and each trie entry) owns a host-side
  :class:`BlockTable`: logical block index ``g`` (absolute positions
  ``[g*bt, (g+1)*bt)``) -> pool block id. The device sees a fixed-width
  ring projection of it (``g`` at ring slot ``g % S``), so the decode
  executable's shapes never depend on sequence length.
- **Refcounts** — blocks are shared, not copied: a prefix hit splices
  the trie entry's block ids into the slot's table with refcount bumps
  (zero device work), and the one jitted ``copy_block`` executable
  implements copy-on-write when a slot would append into a block still
  referenced by the trie or another slot (only ever the partial
  boundary block — full blocks are immutable once written).
- **Allocation on demand** — the engine reserves blocks only as
  ``filled`` crosses a block boundary, so short requests hold short
  tables and the same device bytes serve strictly more concurrent
  slots than the dense row layout (the ``decode_paged_max_slots``
  bench gate).

The pool itself holds only host bookkeeping; device arrays live in the
engine's rnn-state pytree (``{"pk","pv"}`` per attention layer) so the
existing jitted decode/verify/chunk executables thread them through
``AttentionImpl._paged_attend`` unchanged. The two jits owned here
(``copy_block`` for CoW, ``zero_block`` for quarantine scrubbing)
compile once each — the bounded-compile-count discipline of the dense
engine carries over.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class BlockTable:
    """Host-side view of one logical KV sequence: which pool block
    holds each logical block of the sequence, how many absolute tokens
    exist (``length``), and the earliest valid position (``floor`` —
    nonzero when the sequence's head slid out of the window, or when it
    was spliced from a trie entry that stored a slid window).

    Used for decode slots (mutated as the slot streams), for in-flight
    paged admissions, and as the payload of paged prefix-trie entries
    (frozen after insert)."""

    block_tokens: int
    blocks: Dict[int, int] = dataclasses.field(default_factory=dict)
    length: int = 0
    floor: int = 0

    def block_ids(self) -> List[int]:
        return list(self.blocks.values())

    def tail_block(self) -> Optional[Tuple[int, int]]:
        """(logical g, block id) of the partial tail block the next
        append writes into, or None when length is block-aligned (the
        next append starts a fresh block)."""
        if self.length % self.block_tokens == 0:
            return None
        g = self.length // self.block_tokens
        bid = self.blocks.get(g)
        return None if bid is None else (g, bid)

    def new_logical_blocks(self, n_tokens: int) -> List[int]:
        """Logical block indices an append of ``n_tokens`` tokens
        requires beyond what the table already maps."""
        if n_tokens <= 0:
            return []
        bt = self.block_tokens
        first = (self.length + bt - 1) // bt   # == length//bt aligned
        last = (self.length + n_tokens - 1) // bt
        return [g for g in range(first, last + 1)
                if g not in self.blocks]

    def arrays(self, ring_slots: int) -> Tuple[np.ndarray, np.ndarray]:
        """Device projection: ``(table[S], base[S])`` int32 with block
        ``g`` at ring slot ``g % S`` (-1 = unmapped). Two live logical
        blocks may never collide on a ring slot — the engine sizes S
        past the window plus one round's worst-case writes and frees
        slid-out blocks each round, so a collision is a bookkeeping
        bug, not load."""
        table = np.full(ring_slots, -1, np.int32)
        base = np.full(ring_slots, -1, np.int32)
        for g, bid in self.blocks.items():
            s = g % ring_slots
            if table[s] != -1:
                raise AssertionError(
                    f"ring collision at slot {s}: logical blocks "
                    f"{base[s] // self.block_tokens} and {g} both "
                    "live — expired blocks were not freed")
            table[s] = bid
            base[s] = g * self.block_tokens
        return table, base

    def coverage(self, g: int) -> int:
        """Valid tokens this sequence keeps in logical block ``g``
        (fragmentation accounting: ``block_tokens - coverage`` of a
        tail block is allocated-but-masked pad)."""
        bt = self.block_tokens
        lo = max(self.floor, g * bt)
        hi = min(self.length, (g + 1) * bt)
        return max(0, hi - lo)


class BlockPool:
    """Host-side allocator + refcounts for the shared KV block pool.

    Owns NO device arrays (those ride the engine's rnn pytree); owns
    the free list, per-block refcounts, the poisoned-block set the
    paranoid sweep feeds (a poisoned block is scrubbed by the engine
    the moment its last reference drops — never while an innocent
    sharer still reads it), and the two single-compile jitted helpers
    (``copy_block`` for CoW, ``zero_block`` for scrubbing)."""

    def __init__(self, n_blocks: int, block_tokens: int,
                 jit_wrap=None):
        if n_blocks < 1:
            raise ValueError(f"kv_blocks {n_blocks} < 1")
        if block_tokens < 1 or (block_tokens & (block_tokens - 1)):
            raise ValueError(
                f"block_tokens {block_tokens} must be a power of two")
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        # the engine's compilation entry point (ISSUE 12): a
        # tensor-parallel engine hands its shard_map wrapper in so the
        # pool's movers run per-shard on head-sliced blocks; None = the
        # single-chip plain jax.jit (the pool is engine-agnostic)
        self._jit_wrap = jit_wrap if jit_wrap is not None else jax.jit
        self._free: List[int] = list(range(self.n_blocks - 1, -1, -1))
        self._ref = np.zeros(self.n_blocks, np.int64)
        self.poisoned: set = set()
        self.stats: Dict[str, int] = {
            "allocs": 0, "frees": 0, "cow_copies": 0,
            "spliced": 0, "scrubbed": 0,
        }
        self._build_jits()

    def _build_jits(self):
        def copy_block(pool, src, dst):
            def cp(a):
                row = jax.lax.dynamic_slice_in_dim(a, src, 1, axis=0)
                return jax.lax.dynamic_update_slice_in_dim(
                    a, row, dst, axis=0)

            return jax.tree_util.tree_map(cp, pool)

        def zero_block(pool, blk):
            def z(a):
                row = jnp.zeros((1,) + a.shape[1:], a.dtype)
                return jax.lax.dynamic_update_slice_in_dim(
                    a, row, blk, axis=0)

            return jax.tree_util.tree_map(z, pool)

        # the pool is donated through every mover: one block changes,
        # the other n_blocks-1 alias in place instead of copying
        self._copy_jit = self._jit_wrap(copy_block, donate_argnums=(0,))
        self._zero_jit = self._jit_wrap(zero_block, donate_argnums=(0,))

    def compile_counts(self) -> Dict[str, int]:
        def n(f):
            return int(getattr(f, "_cache_size", lambda: -1)())

        return {"paged_copy": n(self._copy_jit),
                "paged_zero": n(self._zero_jit)}

    # -- allocation / sharing ------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.n_blocks - len(self._free)

    def alloc(self) -> Optional[int]:
        """One fresh block at refcount 1, or None when the pool is
        exhausted (the engine then evicts trie entries / preempts the
        youngest slot — allocation never blocks)."""
        if not self._free:
            return None
        bid = self._free.pop()
        self._ref[bid] = 1
        self.stats["allocs"] += 1
        return bid

    def ref(self, bid: int) -> None:
        if self._ref[bid] < 1:
            raise AssertionError(f"ref of free block {bid}")
        self._ref[bid] += 1

    def refcount(self, bid: int) -> int:
        return int(self._ref[bid])

    def deref(self, bid: int) -> bool:
        """Drop one reference; returns True when the block just became
        free (the caller scrubs it first if it was poisoned)."""
        if self._ref[bid] < 1:
            raise AssertionError(f"deref of free block {bid}")
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)
            self.stats["frees"] += 1
            return True
        return False

    # -- device helpers (pool pytree = {layer: {"pk","pv"}}) -----------
    def copy_block_device(self, pool_pytree, src: int, dst: int):
        """Jitted CoW copy of one block (the only per-hit device work a
        warm prefix admission can pay, and only when the match ends
        inside a block)."""
        self.stats["cow_copies"] += 1
        return self._copy_jit(pool_pytree,
                              jnp.asarray(src, jnp.int32),
                              jnp.asarray(dst, jnp.int32))

    def scrub_block_device(self, pool_pytree, bid: int):
        """Zero one (freed, poisoned) block so the paranoid finiteness
        sweep goes green again without touching live blocks."""
        self.stats["scrubbed"] += 1
        self.poisoned.discard(bid)
        return self._zero_jit(pool_pytree, jnp.asarray(bid, jnp.int32))

    # -- accounting -----------------------------------------------------
    def fragmentation_tokens(self, tables) -> int:
        """Allocated-but-masked tokens across the pool: for every USED
        block, ``block_tokens`` minus the widest valid coverage any
        referent keeps in it (tail pad of live sequences, heads slid
        out of windows). ``tables`` iterates every live
        :class:`BlockTable` (slots, pending admissions, trie entries);
        shared blocks count once."""
        best: Dict[int, int] = {}
        for tab in tables:
            if tab is None:
                continue
            for g, bid in tab.blocks.items():
                cov = tab.coverage(g)
                if cov > best.get(bid, -1):
                    best[bid] = cov
        frag = 0
        for bid in range(self.n_blocks):
            if self._ref[bid] > 0:
                frag += self.block_tokens - best.get(bid, 0)
        return frag
