"""Replica lifecycle handles: the process-management layer under the
elastic fleet controller (ISSUE 11).

``scripts/router_soak.py`` grew the first subprocess-replica manager —
spawn a child gateway, wait for its READY line on a reaper thread,
SIGKILL it for chaos, terminate it for cleanup. The fleet controller
(serving/controller.py) needs exactly that machinery to BREATHE the
fleet at runtime (spawn on SLO pressure, reap on idle, replace during
rolling upgrades), so it is hoisted here as a reusable pair:

- :class:`ReplicaProcess` — a real subprocess replica: any argv whose
  child prints a ready line (``READY <address>`` by convention; the
  pattern is a knob so ``dl4j-tpu serve`` children work too) once its
  gateway is listening. ``sigkill()`` is the chaos path (no drain, no
  goodbye), ``shutdown()`` the polite one (SIGTERM, then SIGKILL past
  the grace period).
- :class:`LocalReplica` — an in-process stand-in wrapping a
  :class:`~deeplearning4j_tpu.serving.ServingGateway`, whose
  ``hard_kill`` is network-indistinguishable from process death
  (connection refused, streams end without terminal). The tier-1
  soaks and controller tests scale a "fleet" in one process at a
  fraction of the subprocess wall cost.

Both expose the same handle protocol the controller scales over:
``address`` / ``replica_id`` / ``alive`` / ``sigkill()`` /
``shutdown()``. A *replica factory* is any callable
``factory(replica_id) -> handle`` returning a READY handle — the
controller never knows whether its fleet is processes or objects.
"""

from __future__ import annotations

import contextlib
import socket
import subprocess
import threading
from typing import Dict, List, Optional, Sequence


def free_port() -> int:
    """An ephemeral port that was free a moment ago (the child binds
    it after a tiny race window — fine for localhost test fleets)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ReplicaProcess:
    """One subprocess replica and the handles to manage its life.

    ``argv`` is the full child command; the child must print a line
    starting with ``ready_pattern`` (default ``"READY"``) to stdout
    once its gateway is accepting connections — that line is the
    boot handshake :meth:`wait_ready` blocks on. ``address`` is where
    the router reaches the replica (``host:port``)."""

    def __init__(self, argv: Sequence[str], replica_id: str,
                 port: int, host: str = "127.0.0.1",
                 ready_pattern: str = "READY",
                 env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None):
        self.replica_id = str(replica_id)
        self.port = int(port)
        self.host = host
        self.address = f"{host}:{port}"
        self.ready_pattern = ready_pattern
        self.proc = subprocess.Popen(
            list(argv), stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, env=env, cwd=cwd)

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def wait_ready(self, timeout_s: float = 120.0) -> None:
        """Block until the child printed its ready line. readline()
        blocks with no deadline of its own, so a wedged child (stuck
        in XLA init, never printing READY and never exiting) would
        hang the caller forever — read on a reaper thread and enforce
        the deadline with join()."""
        result: Dict[str, str] = {}
        pattern = self.ready_pattern

        def read():
            while True:
                line = self.proc.stdout.readline().decode()
                if not line or line.lstrip().startswith(pattern):
                    result["line"] = line
                    return

        t = threading.Thread(target=read, daemon=True,
                             name=f"replica-ready-{self.replica_id}")
        t.start()
        t.join(timeout=timeout_s)
        if result.get("line", "").lstrip().startswith(pattern):
            return
        raise RuntimeError(
            f"replica {self.replica_id} never became ready within "
            f"{timeout_s}s (last output {result.get('line')!r})")

    def sigkill(self) -> None:
        """Chaos path: SIGKILL — no drain, no cleanup, no goodbye."""
        self.proc.kill()
        self.proc.wait(timeout=30)

    def shutdown(self) -> None:
        """Polite teardown: SIGTERM, SIGKILL past the grace period,
        stdout pipe closed (the fd-leak gates count it)."""
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)
        self.proc.stdout.close()


class LocalReplica:
    """In-process replica handle: a gateway whose ``hard_kill`` is
    the SIGKILL stand-in. ``engine`` is a ready
    :class:`~deeplearning4j_tpu.serving.DecodeEngine` (the caller
    owns net/knob/throttle choices); everything else forwards to
    :class:`~deeplearning4j_tpu.serving.ServingGateway`."""

    def __init__(self, engine, replica_id: str, **gateway_kwargs):
        from deeplearning4j_tpu.serving.gateway import ServingGateway

        gateway_kwargs.setdefault("keepalive_s", 0.1)
        self.replica_id = str(replica_id)
        self.gw = ServingGateway(engine, replica_id=self.replica_id,
                                 **gateway_kwargs).start()
        self.address = (f"{self.gw._service.host}:"
                        f"{self.gw._service.port}")

    @property
    def alive(self) -> bool:
        return not self.gw._stopped

    def sigkill(self) -> None:
        self.gw.hard_kill()

    def shutdown(self) -> None:
        with contextlib.suppress(Exception):
            self.gw.close()


def shutdown_all(handles: List) -> None:
    """Best-effort teardown of a whole fleet of handles (soak/test
    cleanup; errors suppressed so one wreck cannot leak the rest)."""
    for h in handles:
        with contextlib.suppress(Exception):
            h.shutdown()
