"""Crash-safe write-ahead journal for the serving router (ISSUE 15
tentpole).

PRs 9-14 made every *replica* expendable — SIGKILL one and the
router's in-memory journal replays its streams bit-identically on a
survivor. The router itself was the last memory-only component: its
journal, warm-belief map, and per-tenant token buckets all evaporated
with the process. This module is the durable half of that bookkeeping:
an append-only on-disk log the router writes BEFORE acting, so a
SIGKILLed router restarted against the same file recovers every open
stream, every delivered-token high-water mark, every tenant's bucket
level, and every warm-KV belief.

**Wire format.** The file opens with an 8-byte header
(``b"DWJ1" + u32 version``); every record after it is framed
``u32 length | u32 crc32(payload) | payload`` with the payload a
compact-JSON object. A crash can only tear the TAIL of the file
(appends are sequential), so recovery reads records until the first
short frame or CRC mismatch and treats everything before it as truth —
``recover_state`` reports the torn bytes and the next append truncates
them away. A record is bounded (:data:`MAX_RECORD_BYTES`); a framed
length past the bound means the frame itself is garbage (not a torn
tail but a corrupt file) and recovery stops there just the same.

**Record types** (the ``"t"`` key):

- ``open``  — a request was journaled: rid, prompt, params, submit
  wall time. Written BEFORE the first routing attempt.
- ``route`` — an attempt was accepted by a replica: rid, replica
  ADDRESS (the field recovery restores; the id↔address binding has
  its own ``rep`` records).
- ``prog``  — tokens crossed the high-water mark: rid, the fresh
  token list, and ``at`` — the absolute token position the delta
  starts at. The fold of a rid's ``prog`` records IS its delivered
  high-water mark — replay after recovery dedups the regenerated
  prefix against it, so a restarted router neither loses nor
  double-delivers a token. Position-addressed writes make the
  record IDEMPOTENT: a delta folded twice (compaction carry-over
  below can duplicate) lands on the same positions.
- ``done``  — terminal: rid, finish_reason, status, total tokens.
- ``bucket`` — one tenant token-bucket level (ISSUE 15 satellite):
  tenant, tokens, capacity, rate, wall stamp. Folded newest-wins, so
  a restarted router refills a bucket only for the real wall-clock
  downtime — a flooder does not get a fresh burst out of a crash.
- ``warm``/``cold`` — warm-belief delta (ISSUE 15 satellite): the
  router believes replica R is (no longer) warm for affinity key K.
  Restored beliefs keep KV transfers flowing after a restart; a
  replica whose breaker opens during recovery drops its restored
  beliefs exactly like a live death would.
- ``rep`` — a replica's stable id→address binding, learned from its
  first health scrape. Recovery re-seats the ids before any scrape,
  so the rendezvous keyspace holds from the restarted router's first
  pick and a dead-at-recovery replica's breaker opens under the same
  id its restored beliefs are keyed by.
- ``snap``  — a compaction snapshot: the complete live state (open
  entries with their high-water tokens, recent terminals, bucket
  levels, warm beliefs, the next rid). Compaction rewrites the file
  as header + one ``snap`` + every record appended while the
  snapshot was being built (the CARRY-OVER buffer — see
  :meth:`WriteAheadJournal.begin_compaction`; nothing appended
  concurrently is ever lost), and keeps appending, so the WAL stays
  bounded like the in-memory ``journal_cap``. Carry-over can
  DUPLICATE a record that also made it into the snapshot, which is
  why every record type folds idempotently (``open`` never clobbers
  a known rid, ``prog`` writes absolute positions, the rest are
  last-wins).

**Fsync policy** (the ``fsync`` knob): ``per_record`` fsyncs every
append (strongest: survives power loss at per-record latency),
``batched`` (default) flushes to the OS on every append and fsyncs at
most once per ``batch_fsync_s`` (survives process SIGKILL exactly like
per_record — the OS has the bytes — and loses at most one batch window
to a kernel panic), ``off`` never fsyncs (still flushes, still
SIGKILL-safe; for tests and throwaway fleets). The acceptance bench
(``bench_router_wal_overhead``) prices ``batched`` at >= 0.97x WAL-off
throughput.

The journal is the ROUTER's: replicas have their own drain/restore
snapshots (PR 3/5) and the two layers compose — a router recovery
replays full prompts through whatever replicas answer healthz, exactly
like a replica-death replay would.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

JOURNAL_MAGIC = b"DWJ1"
JOURNAL_VERSION = 1
_HEADER = JOURNAL_MAGIC + struct.pack("<I", JOURNAL_VERSION)
_FRAME = struct.Struct("<II")  # length, crc32(payload)

#: every fsync policy the WAL speaks (the CLI's ``--fsync`` choices)
FSYNC_POLICIES = ("per_record", "batched", "off")

#: one framed record may not exceed this; a framed length past it is
#: corruption, not a big record (open records carry prompts, prog
#: records carry deltas — both orders of magnitude below this)
MAX_RECORD_BYTES = 8 << 20


class JournalError(RuntimeError):
    """The journal file is not a journal (bad magic/version) — a
    TORN TAIL is never an error (recovery truncates it), but a file
    that was never ours must not be silently overwritten."""


def _encode(record: Dict[str, Any]) -> bytes:
    payload = json.dumps(record, separators=(",", ":")).encode()
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def read_records(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Read every intact record: ``(records, torn_tail_bytes)``.
    Stops at the first short frame, CRC mismatch, oversized length,
    or undecodable payload — everything after that point is the torn
    tail a crash mid-append leaves behind (``torn_tail_bytes`` > 0
    reports it; the caller decides whether to truncate). Raises
    :class:`JournalError` for a file that is not a journal at all."""
    with open(path, "rb") as f:
        header = f.read(len(_HEADER))
        if len(header) < len(_HEADER) or header[:4] != JOURNAL_MAGIC:
            raise JournalError(
                f"{path} is not a router journal (bad magic "
                f"{header[:4]!r})")
        version = struct.unpack("<I", header[4:])[0]
        if version != JOURNAL_VERSION:
            raise JournalError(
                f"{path}: journal version {version} != "
                f"{JOURNAL_VERSION}")
        records: List[Dict[str, Any]] = []
        good_end = f.tell()
        size = os.fstat(f.fileno()).st_size
        while True:
            frame = f.read(_FRAME.size)
            if len(frame) < _FRAME.size:
                break
            length, crc = _FRAME.unpack(frame)
            if length > MAX_RECORD_BYTES:
                break
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                break
            try:
                rec = json.loads(payload)
            except ValueError:
                break
            records.append(rec)
            good_end = f.tell()
        return records, size - good_end


class WriteAheadJournal:
    """Append-only framed record log with bounded-size compaction.

    Thread-safe: appends from the router's relay threads serialize on
    an internal lock (per-rid ordering is free — one relay thread owns
    one stream). ``compact_bytes`` bounds the file: once the log grows
    past it the OWNER folds its live state into one ``snap`` record
    via :meth:`compact` (atomic: tmp file + ``os.replace``, fsync'd
    regardless of policy — a compaction that can vanish would lose
    everything it folded)."""

    def __init__(self, path: str, fsync: str = "batched",
                 compact_bytes: int = 1 << 20,
                 batch_fsync_s: float = 0.05):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync {fsync!r} not in {FSYNC_POLICIES}")
        self.path = str(path)
        self.fsync = fsync
        self.compact_bytes = int(compact_bytes)
        self.batch_fsync_s = float(batch_fsync_s)
        self._lock = threading.Lock()
        self._last_sync = 0.0
        self._closed = False
        #: armed by :meth:`begin_compaction`: encoded frames appended
        #: while the owner builds its snapshot, spliced into the
        #: compacted file so the rewrite cannot lose a concurrent
        #: append
        self._carry: Optional[List[bytes]] = None
        #: records recovered from an existing file at open (the
        #: router folds them through :func:`recover_state`); a torn
        #: tail is truncated HERE so appends extend intact state
        self.recovered: List[Dict[str, Any]] = []
        self.torn_tail_bytes = 0
        if os.path.exists(self.path) and os.path.getsize(self.path):
            self.recovered, self.torn_tail_bytes = read_records(
                self.path)
            if self.torn_tail_bytes:
                good = os.path.getsize(self.path) \
                    - self.torn_tail_bytes
                with open(self.path, "rb+") as f:
                    f.truncate(good)
            self._f = open(self.path, "ab")
        else:
            self._f = open(self.path, "wb")
            self._f.write(_HEADER)
            self._f.flush()
            self._sync(force=True)
            self._sync_dir()  # the file's CREATION must survive too

    # -- write path ----------------------------------------------------
    def _sync(self, force: bool = False) -> None:
        """Apply the fsync policy after a flushed write. The file is
        ALWAYS flushed to the OS first (process SIGKILL loses
        nothing); fsync buys kernel-crash durability per policy."""
        if self.fsync == "off" and not force:
            return
        now = time.monotonic()
        if (not force and self.fsync == "batched"
                and now - self._last_sync < self.batch_fsync_s):
            return
        os.fsync(self._f.fileno())
        self._last_sync = now

    def append(self, record: Dict[str, Any]) -> None:
        """Frame + write one record (no-op after close: the router's
        relay threads may race shutdown; a lost tail record after
        close() is indistinguishable from dying a moment earlier,
        which the recovery path already handles). A record past
        :data:`MAX_RECORD_BYTES` raises ``ValueError`` instead of
        being written: the reader treats an oversized frame as
        corruption and stops there, so writing one would silently
        poison every record journaled after it."""
        data = _encode(record)
        if len(data) - _FRAME.size > MAX_RECORD_BYTES:
            raise ValueError(
                f"record of {len(data) - _FRAME.size} bytes exceeds "
                f"the {MAX_RECORD_BYTES}-byte journal frame bound")
        with self._lock:
            if self._closed:
                return
            self._f.write(data)
            self._f.flush()
            if self._carry is not None:
                # a compaction snapshot is being built: this record
                # may or may not be reflected in it, so it is carried
                # into the rewritten file verbatim (idempotent folds
                # make the possible duplication harmless)
                self._carry.append(data)
            self._sync()

    @property
    def size_bytes(self) -> int:
        with self._lock:
            if self._closed:
                return 0
            return self._f.tell()

    def needs_compaction(self) -> bool:
        return self.size_bytes > self.compact_bytes

    def begin_compaction(self) -> None:
        """Arm the carry-over buffer BEFORE building the compaction
        snapshot: every record appended from this call until
        :meth:`compact` is also retained in memory and spliced after
        the snap record, so an append racing the snapshot build can
        never be lost to the rewrite (it may be duplicated when the
        snapshot already reflects it — the record types fold
        idempotently on purpose)."""
        with self._lock:
            if self._carry is None:
                self._carry = []

    def _sync_dir(self) -> None:
        """fsync the journal's DIRECTORY so a rename/creation is
        itself durable — without it, a power loss after ``os.replace``
        can resurrect the pre-compaction inode and silently drop
        every post-compaction record, defeating ``per_record``'s
        power-loss promise."""
        dirname = os.path.dirname(os.path.abspath(self.path)) or "."
        try:
            dirfd = os.open(dirname, os.O_RDONLY)
        except OSError:
            return  # platform without directory fds: best effort
        try:
            os.fsync(dirfd)
        except OSError:
            pass
        finally:
            os.close(dirfd)

    def compact(self, snapshot: Dict[str, Any]) -> None:
        """Rewrite the file as header + one ``snap`` record holding
        ``snapshot`` (the owner's complete live state) + any
        carried-over concurrent appends (see
        :meth:`begin_compaction`). Atomic (tmp + ``os.replace`` +
        directory fsync) and fsync'd regardless of policy: the
        rename must never land with the snap still in a volatile
        cache, or a crash could lose every folded record at once."""
        record = dict(snapshot)
        record["t"] = "snap"
        encoded = _encode(record)
        if len(encoded) - _FRAME.size > MAX_RECORD_BYTES:
            # an unreadable snap would poison the WHOLE file; better
            # to skip this compaction (the log keeps growing but
            # stays recoverable) and let the owner count the error.
            # The carry buffer MUST disarm on this path — every
            # carried record is already in the live file, and an
            # armed buffer with no compaction coming would grow with
            # each append for the rest of the process lifetime.
            with self._lock:
                self._carry = None
            raise ValueError(
                f"compaction snapshot of "
                f"{len(encoded) - _FRAME.size} bytes exceeds the "
                f"{MAX_RECORD_BYTES}-byte journal frame bound")
        tmp = self.path + ".compact"
        with self._lock:
            if self._closed:
                self._carry = None
                return
            carried = self._carry or []
            self._carry = None
            data = _HEADER + encoded + b"".join(carried)
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            try:
                os.replace(tmp, self.path)
            finally:
                # reopen WHATEVER the path now names — the new file,
                # or (replace failed) the old one, which already
                # holds every record the carry buffer duplicated
                self._f = open(self.path, "ab")
            self._last_sync = time.monotonic()
            self._sync_dir()

    def close(self) -> None:
        """Flush + fsync + close. Deliberately NO clean-shutdown
        marker: recovery must behave identically whether the previous
        router exited politely or was SIGKILLed — the one code path
        that matters is the one that always runs."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            self._f.close()


def recover_state(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a record sequence (as :func:`read_records` returns, or
    ``WriteAheadJournal.recovered``) into the router-shaped recovery
    state::

        {"entries": {rid: {"prompt", "params", "tokens", "replica",
                           "done", "finish_reason", "status",
                           "submit_wall"}},
         "buckets": {tenant: {"tokens", "capacity", "rate", "wall"}},
         "warm": {key_hex: {replica_id: wall_stamp}},
         "replica_ids": {address: stable_id},
         "next_rid": int,
         "snap_wall": float | None}

    A ``snap`` record REPLACES all folded state (compaction rewrote
    the file; a snap mid-stream means records before it were already
    folded into it). Unknown record types are skipped — an older
    router reading a newer journal recovers what it understands
    rather than refusing to boot."""
    entries: Dict[int, Dict[str, Any]] = {}
    buckets: Dict[str, Dict[str, float]] = {}
    warm: Dict[str, Dict[str, float]] = {}
    replica_ids: Dict[str, str] = {}
    next_rid = 0
    snap_wall: Optional[float] = None
    for rec in records:
        t = rec.get("t")
        if t == "snap":
            entries = {int(e["rid"]): {
                "prompt": [int(x) for x in e["prompt"]],
                "params": dict(e.get("params") or {}),
                "tokens": [int(x) for x in e.get("tokens") or []],
                "replica": e.get("replica"),
                "done": bool(e.get("done")),
                "finish_reason": e.get("finish_reason"),
                "status": e.get("status"),
                "submit_wall": e.get("submit_wall"),
            } for e in rec.get("entries") or []}
            buckets = {str(k): dict(v) for k, v
                       in (rec.get("buckets") or {}).items()}
            warm = {str(k): {str(r): float(s)
                             for r, s in v.items()}
                    for k, v in (rec.get("warm") or {}).items()}
            replica_ids = {str(a): str(r) for a, r
                           in (rec.get("replicas") or {}).items()}
            next_rid = int(rec.get("next_rid") or 0)
            snap_wall = rec.get("wall")
        elif t == "open":
            rid = int(rec["rid"])
            if rid not in entries:
                # rids are never reused, so an open for a known rid
                # can only be a compaction carry-over duplicate — it
                # must not clobber the snapshot's folded progress
                entries[rid] = {
                    "prompt": [int(x) for x in rec["prompt"]],
                    "params": dict(rec.get("params") or {}),
                    "tokens": [], "replica": None, "done": False,
                    "finish_reason": None, "status": None,
                    "submit_wall": rec.get("wall"),
                }
            next_rid = max(next_rid, rid + 1)
        elif t == "route":
            e = entries.get(int(rec["rid"]))
            if e is not None:
                e["replica"] = rec.get("replica")
        elif t == "prog":
            e = entries.get(int(rec["rid"]))
            if e is not None and not e["done"]:
                toks = [int(x) for x in rec["toks"]]
                tokens = e["tokens"]
                # position-addressed (idempotent under carry-over
                # duplication); a record without "at" is the legacy
                # append form. A record PAST a positional gap (a
                # mid-journal append failure swallowed upstream) is
                # DROPPED: the gap already bounds recovery fidelity
                # there, and splicing its tokens at wrong absolute
                # positions would serve wrong tokens to a resuming
                # client — replay regenerates the real ones instead.
                at = int(rec.get("at", len(tokens)))
                if 0 <= at <= len(tokens):
                    tokens[at:at + len(toks)] = toks
        elif t == "done":
            e = entries.get(int(rec["rid"]))
            if e is not None:
                e["done"] = True
                e["finish_reason"] = rec.get("reason")
                e["status"] = rec.get("status")
                n = rec.get("n")
                if n is not None and len(e["tokens"]) != int(n):
                    # the done record is authoritative about the
                    # delivered count: a prog append racing the crash
                    # may have landed after the terminal was sealed
                    e["tokens"] = e["tokens"][:int(n)]
        elif t == "bucket":
            buckets[str(rec["tenant"])] = {
                "tokens": float(rec["tokens"]),
                "capacity": float(rec["capacity"]),
                "rate": float(rec["rate"]),
                "wall": float(rec.get("wall") or 0.0),
            }
        elif t == "warm":
            warm.setdefault(str(rec["k"]), {})[str(rec["r"])] = \
                float(rec.get("wall") or 0.0)
        elif t == "rep":
            replica_ids[str(rec["addr"])] = str(rec["r"])
        elif t == "cold":
            k = rec.get("k")
            if k is None:
                # replica-wide cold (breaker opened): drop the
                # replica from every key's belief set
                for beliefs in warm.values():
                    beliefs.pop(str(rec["r"]), None)
            else:
                beliefs = warm.get(str(k))
                if beliefs is not None:
                    beliefs.pop(str(rec["r"]), None)
    return {"entries": entries, "buckets": buckets,
            "warm": {k: v for k, v in warm.items() if v},
            "replica_ids": replica_ids,
            "next_rid": next_rid, "snap_wall": snap_wall}
