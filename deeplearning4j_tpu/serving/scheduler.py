"""Request queue + admission policy for the continuous-batching engine.

The scheduler owns everything host-side about a request's lifecycle
BEFORE it holds a slot: validation against the cache window, FIFO
ordering, the pow2 prompt-length bucketing that bounds prefill
compilations (one XLA executable per bucket, O(log window) buckets
total, instead of one per distinct prompt length), and — with chunked
prefill enabled — the per-round token budget that decides how much
prefill work may run between two decode rounds (the Sarathi-Serve
stall-vs-TTFT tradeoff, Agrawal et al. 2024)."""

from __future__ import annotations

import dataclasses
import itertools
import math
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence

from deeplearning4j_tpu.nn.streaming import scan_length_bucket


@dataclasses.dataclass
class Request:
    """One decode request. ``temperature == 0`` means greedy (the
    default — bit-identical to ``MultiLayerNetwork.generate``);
    ``top_k=None`` means unfiltered. ``eos_id`` optionally ends the
    request early (the eos token is included in the output).

    ``deadline_s`` is an END-TO-END budget: measured from submit, a
    request past it is terminated wherever it is (queued, mid-
    admission, or mid-decode) with ``finish_reason="deadline"`` and
    whatever tokens it produced. ``queue_timeout_s`` bounds QUEUE WAIT
    only: a request that has not started admission within it is shed
    (``finish_reason="shed"``) — the backpressure contract that a
    request which waited too long is cheaper to drop than to start."""

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    eos_id: Optional[int] = None
    id: Optional[int] = None
    deadline_s: Optional[float] = None
    queue_timeout_s: Optional[float] = None
    #: fleet-level trace context (ISSUE 10): an opaque
    #: ``<trace_id>/<span_id>`` string minted by an upstream tier
    #: (the router's journaled request id + per-attempt span id) and
    #: carried through the engine so every span, flight-recorder
    #: record, and ``serving.request_done`` instant this request
    #: produces is stitchable into one cross-process trace. Pure
    #: host metadata — never touches device work, RNG, or ids.
    trace: Optional[str] = None
    #: multi-tenant QoS identity (ISSUE 13): which tenant's quotas,
    #: priority class, and fair share this request bills against.
    #: ``"default"`` = the unlabeled-caller class — engines without a
    #: TenantRegistry ignore the field entirely, so existing callers
    #: are unchanged. Rides the snapshot wire format and the router
    #: journal, so failover replay and drain/restore preserve it.
    tenant: str = "default"
    #: optional per-request priority override (ISSUE 13): CLAMPED to
    #: the tenant's class — a request can de-prioritize itself (batch
    #: traffic under an interactive tenant) but never self-boost.
    #: None = the tenant spec's priority.
    priority: Optional[int] = None

    def __post_init__(self):
        if len(self.prompt) == 0:
            raise ValueError("empty prompt")
        # tenant names ride Prometheus labels and accounting keys
        # verbatim — validate here so EVERY submit surface (engine,
        # gateway, router) rejects a malformed one identically
        from deeplearning4j_tpu.serving.tenancy import validate_tenant

        self.tenant = validate_tenant(self.tenant)
        if self.priority is not None:
            self.priority = int(self.priority)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens {self.max_new_tokens} < 1")
        if self.temperature < 0:
            raise ValueError(f"temperature {self.temperature} < 0")
        if self.top_k is not None and self.top_k < 1:
            # top_k=0 would otherwise fall through `top_k or vocab`
            # as unfiltered sampling — the opposite of the caller's
            # plausible intent
            raise ValueError(
                f"top_k {self.top_k} < 1 (use None for unfiltered)")
        for name in ("deadline_s", "queue_timeout_s"):
            val = getattr(self, name)
            if val is not None and val <= 0:
                raise ValueError(
                    f"{name} {val} <= 0 (use None for no limit)")


#: every terminal state a request can reach. 'length'/'eos' are the
#: healthy outcomes; the rest are the failure-handling layer's:
#: 'deadline' (end-to-end budget blown, partial tokens returned),
#: 'cancelled' (engine.cancel, partial tokens returned), 'shed'
#: (admission-queue backpressure or queue timeout, no tokens), 'fault'
#: (an injected/detected fault exhausted the retry cap).
FINISH_REASONS = ("length", "eos", "deadline", "cancelled", "shed",
                  "fault")


@dataclasses.dataclass
class GenerationResult:
    """A finished request: generated ids (prompt excluded) and why it
    stopped (one of :data:`FINISH_REASONS`). ``prefix_tokens_reused``
    counts prompt tokens served from the radix prefix cache instead of
    prefilled; ``ttft_s`` is submit-to-first-token wall time (None when
    the engine predates the request's submit, e.g. hand-built results,
    or the request never produced a token); ``retries`` counts fault
    re-admissions the request survived before this terminal state."""

    id: int
    tokens: List[int]
    finish_reason: str
    prompt_len: int
    prefix_tokens_reused: int = 0
    ttft_s: Optional[float] = None
    retries: int = 0
    #: speculative-decoding counters (``spec_draft_len > 0`` engines):
    #: tokens the n-gram table proposed for this request, and how many
    #: of them verification accepted — acceptance rate per request is
    #: ``spec_accepted / spec_drafted`` (0/0 when the request never
    #: drafted, e.g. spec-off engines; sampling requests draft too —
    #: stochastic acceptance, ISSUE 16)
    spec_drafted: int = 0
    spec_accepted: int = 0
    #: per-request phase breakdown from the engine's phase clock
    #: (ISSUE 7; ``record_timing=True`` engines): a plain JSON-able
    #: dict — ``queue_wait_s``, ``admission_s`` (+ its cold / chunked /
    #: splice split), ``decode_s``, ``verify_s``, ``stall_s``,
    #: ``ttft_s`` (identical to the top-level field), ``e2e_s``,
    #: ``attempts``, ``rounds``, ``tokens``. The disjoint-interval
    #: attribution guarantees the phase sums never exceed ``e2e_s``.
    #: None when timing was off or the engine predates the request.
    timing: Optional[Dict[str, Any]] = None
    #: the fleet trace context the request carried in (ISSUE 10) —
    #: echoed on the terminal so an upstream tier can correlate the
    #: result with the stitched cross-process trace. None for
    #: requests submitted without one.
    trace: Optional[str] = None
    #: the tenant the request billed against (ISSUE 13) — echoed on
    #: the terminal ONLY by tenancy-enabled engines (None otherwise,
    #: so non-tenant deployments' wire format is unchanged); the
    #: gateway's per-tenant Retry-After and the router's per-tenant
    #: parking read it back.
    tenant: Optional[str] = None


class Scheduler:
    """FIFO admission queue with pow2 prompt-length bucketing.

    ``max_prompt_len`` is the engine's cache window: a prompt longer
    than the window cannot prefill losslessly (its oldest tokens would
    slide out before decoding starts), so it is rejected at submit
    time rather than silently truncated."""

    #: valid chunked-prefill scheduling policies (see ``plan_chunks``)
    POLICIES = ("ttft", "decode")

    #: speculative K-adaptation policy (see ``record_acceptance``):
    #: acceptance is averaged over this many verify rounds before K
    #: moves, so one unlucky round cannot whipsaw the draft length
    SPEC_ADAPT_ROUNDS = 8
    #: mean acceptance below this halves K (floor 1 — at K=1 a round
    #: with no n-gram match at all already IS plain decode)
    SPEC_ACCEPT_LOW = 0.4
    #: mean acceptance above this doubles K back toward the ceiling
    SPEC_ACCEPT_HIGH = 0.8

    def __init__(self, max_prompt_len: int, min_bucket: int = 8,
                 prefill_chunk: int = 0,
                 prefill_budget: Optional[int] = None,
                 policy: str = "ttft",
                 max_queue: Optional[int] = None,
                 pressure_high: Optional[int] = None,
                 pressure_low: Optional[int] = None,
                 spec_draft_len: int = 0):
        self.max_prompt_len = int(max_prompt_len)
        self.min_bucket = int(min_bucket)
        if policy not in self.POLICIES:
            raise ValueError(
                f"admission policy {policy!r}: expected one of "
                f"{self.POLICIES}")
        if prefill_chunk < 0:
            raise ValueError(f"prefill_chunk {prefill_chunk} < 0")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue {max_queue} < 1")
        self.policy = policy
        self.prefill_chunk = int(prefill_chunk)
        if prefill_budget is None:
            # decode-priority: ONE chunk between decode rounds — the
            # minimum that still makes admission progress, so a running
            # slot never stalls longer than one chunk. ttft-priority:
            # 4 chunks' worth, front-loaded on the oldest admission.
            prefill_budget = (self.prefill_chunk if policy == "decode"
                              else 4 * self.prefill_chunk)
        self.prefill_budget = int(prefill_budget)
        # adaptive-degradation bounds (see adapt_budget): the budget
        # never adapts above its configured value or below one chunk
        self._budget_ceiling = self.prefill_budget
        self.pressure_high = (int(pressure_high)
                              if pressure_high is not None
                              else 4 * max(self._budget_ceiling, 1))
        self.pressure_low = (int(pressure_low)
                             if pressure_low is not None
                             else max(self._budget_ceiling, 1))
        self.max_queue = None if max_queue is None else int(max_queue)
        if spec_draft_len < 0:
            raise ValueError(f"spec_draft_len {spec_draft_len} < 0")
        #: speculative drafting: ``spec_ceiling`` is the configured K;
        #: ``draft_len`` is the CURRENT K the engine drafts with, which
        #: ``record_acceptance`` adapts inside [1, spec_ceiling]
        self.spec_ceiling = int(spec_draft_len)
        self.draft_len = self.spec_ceiling
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_rounds = 0
        self._queue: Deque[Request] = deque()
        self._ids = itertools.count()
        self._issued = set()

    def bucket_of(self, prompt_len: int) -> int:
        """Compiled-prefill bucket for a prompt length: next pow2,
        clamped to the window (the pad past the prompt is masked, so a
        clamped bucket still fits any admissible prompt)."""
        return min(scan_length_bucket(prompt_len, self.min_bucket),
                   self.max_prompt_len)

    def validate(self, request: Request) -> None:
        """Reject prompts the engine could never serve losslessly."""
        if len(request.prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt of {len(request.prompt)} tokens exceeds the "
                f"cache window ({self.max_prompt_len}): raise "
                "stream_max_t or shorten the prompt")

    def assign_id(self, request: Request) -> int:
        """Issue (or verify) the request's id WITHOUT enqueueing — the
        engine uses this for requests it must answer at submit time
        (e.g. shed under the reject-new policy), so even a rejected
        request has a stable id its result can be keyed by."""
        if request.id is None:
            request.id = next(self._ids)
        elif request.id in self._issued:
            # results are keyed by id: a duplicate (e.g. the same
            # Request object submitted twice) would silently overwrite
            # the earlier request's output
            raise ValueError(
                f"request id {request.id} already submitted; construct "
                "a new Request (or leave id=None)")
        self._issued.add(request.id)
        return request.id

    def submit(self, request: Request) -> int:
        self.validate(request)
        rid = self.assign_id(request)
        self._queue.append(request)
        return rid

    def requeue(self, request: Request) -> None:
        """Put an already-issued request back in line (fault retry,
        snapshot restore): no re-validation, no duplicate check — the
        id stays issued across its whole retry lifetime."""
        self._issued.add(request.id)
        self._queue.append(request)

    def pop(self) -> Request:
        return self._queue.popleft()

    # -- tenancy hooks (ISSUE 13): the base scheduler is tenant-blind;
    # -- these defaults keep the engine/gateway call sites unconditional
    # -- while WeightedFairScheduler (serving/tenancy.py) overrides them
    def pop_admissible(self) -> Optional[Request]:
        """Next request the admission loop may start, or None when
        every queued request is quota-blocked. FIFO base: the front
        of the queue, always (no quotas exist to block it)."""
        return self.pop() if self._queue else None

    def shed_victim(self) -> Request:
        """Overflow victim under the shed-oldest policy. FIFO base:
        the oldest queued request (the pre-tenancy behavior);
        weighted-fair picks the flooder's oldest instead."""
        return self.pop()

    def tenant_full(self, tenant: str) -> bool:
        """Per-tenant queue-bound check — never full without tenancy
        (only the global ``max_queue`` sheds)."""
        return False

    def tenant_retry_after_s(self, tenant: str, n_slots: int,
                             round_time_s: float) -> int:
        """Per-tenant Retry-After hint — the global hint without
        tenancy, so the gateway's 429 path is tenancy-agnostic."""
        return self.retry_after_s(n_slots, round_time_s)

    def remove(self, request_id: int) -> Optional[Request]:
        """Pull a specific queued request out of line (cancellation,
        deadline expiry). Returns it, or None if not queued."""
        for req in self._queue:
            if req.id == request_id:
                self._queue.remove(req)
                return req
        return None

    def queued_requests(self) -> List[Request]:
        """Snapshot of the queue, oldest first (deadline sweeps and
        engine snapshots; mutating the list does not touch the
        queue)."""
        return list(self._queue)

    def reserve_ids_through(self, max_id: int) -> None:
        """Advance the id counter past ``max_id`` (snapshot restore:
        replayed requests keep their original ids, and future submits
        must not collide with them)."""
        self._ids = itertools.count(int(max_id) + 1)

    def release(self, request_id: int) -> None:
        """Forget a finished request's id: ``_issued`` then tracks only
        queued/in-flight requests (bounded memory over a long-lived
        engine) while still rejecting concurrent duplicate ids."""
        self._issued.discard(request_id)

    def plan_chunks(self, remaining: Sequence[int],
                    verify_tokens: int = 0) -> List[int]:
        """Grant prefill chunks for one scheduling round.

        ``remaining`` is the suffix-tokens-left count per in-flight
        admission, oldest first. Returns indices into ``remaining``,
        one entry per granted chunk, in execution order. Grants go to
        the oldest admission until its suffix is done, then the next
        (finishing one TTFT beats starting many), each grant costing a
        full ``prefill_chunk`` of budget (a padded partial chunk costs
        chunk-shaped compute — budget tracks the stall, not the
        tokens). The budget floors at one chunk so a round always makes
        admission progress:

        - ``decode`` priority: budget == one chunk — between two decode
          rounds at most ONE prefill chunk runs, so the decode stall of
          any admission is bounded by one chunk (the engine's
          non-blocking-admission guarantee).
        - ``ttft`` priority: budget defaults to 4 chunks — admissions
          reach their first token up to 4x sooner per round at the cost
          of a longer decode gap.

        ``verify_tokens`` is the round's speculative-verify width (the
        draft length + the current token, when the engine will run a
        verify pass this round): the verify pass grows the round's
        device work just like an extra prefill chunk would, so it
        bills against the SAME budget — a speculative engine under
        ttft priority grants fewer chunks per round rather than
        silently stretching the round past what the policy promised.
        The one-chunk floor survives the charge, so admissions always
        progress and the decode-priority stall bound (<= 1 chunk/round)
        is unchanged."""
        if not remaining or self.prefill_chunk < 1:
            return []
        budget = max(self.prefill_budget - max(int(verify_tokens), 0),
                     self.prefill_chunk)
        grants: List[int] = []
        for i, left in enumerate(remaining):
            while left > 0 and budget >= self.prefill_chunk:
                grants.append(i)
                left -= min(self.prefill_chunk, left)
                budget -= self.prefill_chunk
            if budget < self.prefill_chunk:
                break
        return grants

    @property
    def pending(self) -> int:
        return len(self._queue)

    def decision_pending(self) -> bool:
        """True when the NEXT scheduling round needs a per-round
        decision from this scheduler — queued arrivals to admit (and,
        in the weighted-fair subclass, the preemption planning that
        only ever fires for queued arrivals). The fused multi-round
        decode path (ISSUE 16) asks this before dispatching a K-round
        scan: while it is False, K rounds of pure decode can run as
        one device program without the scheduler's input; the moment
        it turns True the engine falls back to per-round stepping so
        admission/QoS keep their per-round cadence. Tombstone-aware
        in the subclass via the ``pending`` property."""
        return bool(self.pending)

    @property
    def full(self) -> bool:
        """Bounded-admission check: True when the queue has reached
        ``max_queue`` and the next submit must shed (engine policy
        decides whom). ``max_queue=None`` never sheds."""
        return (self.max_queue is not None
                and len(self._queue) >= self.max_queue)

    def pressure(self) -> int:
        """Backpressure signal: total estimated suffix-prefill tokens
        queued (= queue depth x mean prompt tokens; the prompt length
        is an upper bound per request — prefix-cache hits only lower
        it). This is the prefill work the engine owes before the queue
        drains."""
        return sum(len(r.prompt) for r in self._queue)

    def retry_after_s(self, n_slots: int, round_time_s: float) -> int:
        """Whole-seconds backpressure hint for a shedding front door's
        ``Retry-After`` header (ISSUE 5): with ``depth`` requests
        queued ahead of a would-be arrival and ``n_slots`` of them
        admitted per drain wave, capacity is roughly
        ``ceil(depth / n_slots)`` scheduling rounds away; scaled by the
        measured per-round wall time and floored at 1 s (the header's
        useful minimum — a sub-second hint just invites an immediate
        re-shed). The estimate is deliberately coarse: its job is to
        spread retries out, not to promise a slot."""
        waves = math.ceil(max(len(self._queue), 1) / max(n_slots, 1))
        return max(1, math.ceil(waves * max(round_time_s, 0.0)))

    def record_acceptance(self, drafted: int, accepted: int) -> int:
        """Feed one speculative verify round's outcome into the
        K-adaptation policy and return the draft length the engine
        should use next (the adaptive scheduler of ISSUE 4: K steps
        DOWN when acceptance is poor — wasted verify lanes are wasted
        decode-gap budget — and recovers when acceptance improves).

        Acceptance is averaged over ``SPEC_ADAPT_ROUNDS`` verify rounds
        (rounds that drafted nothing don't count — they already ran as
        plain decode); mean rate below ``SPEC_ACCEPT_LOW`` halves
        ``draft_len`` (floor 1 = one drafted token, the minimum that is
        still speculative; no-match rounds below that are plain
        decode), above ``SPEC_ACCEPT_HIGH`` doubles it back toward the
        configured ``spec_ceiling``."""
        if self.spec_ceiling < 1 or drafted < 1:
            return self.draft_len
        self._spec_drafted += int(drafted)
        self._spec_accepted += int(accepted)
        self._spec_rounds += 1
        if self._spec_rounds >= self.SPEC_ADAPT_ROUNDS:
            rate = self._spec_accepted / self._spec_drafted
            if rate < self.SPEC_ACCEPT_LOW:
                self.draft_len = max(1, self.draft_len // 2)
            elif rate > self.SPEC_ACCEPT_HIGH:
                self.draft_len = min(self.spec_ceiling,
                                     2 * self.draft_len)
            self._spec_drafted = 0
            self._spec_accepted = 0
            self._spec_rounds = 0
        return self.draft_len

    def adapt_budget(self) -> int:
        """Graceful-degradation step (engine calls once per round when
        ``adaptive_prefill`` is on): pressure above ``pressure_high``
        steps the per-round prefill budget DOWN one chunk (decode
        latency stays smooth while admissions slow), pressure below
        ``pressure_low`` steps it back UP toward the configured
        ceiling. The budget never leaves [one chunk, ceiling], so
        admission always progresses and recovery is automatic."""
        if self.prefill_chunk < 1:
            return self.prefill_budget
        p = self.pressure()
        if p > self.pressure_high:
            self.prefill_budget = max(
                self.prefill_chunk,
                self.prefill_budget - self.prefill_chunk)
        elif p < self.pressure_low:
            self.prefill_budget = min(
                self._budget_ceiling,
                self.prefill_budget + self.prefill_chunk)
        return self.prefill_budget
