"""Deterministic fault injection for the serving runtime (ISSUE 3).

Production serving treats failure as an input, not an exception path
(Clockwork, Gujarati et al. OSDI'20): a NaN'd sampler, a failed
allocation, a stalled dispatch, or a corrupted cache row must cost ONE
request (bounded by its retry cap), never the batch. The only way to
keep that property true over time is to rehearse it — so faults here
are *data*: a seeded :class:`FaultPlan` names exactly which round gets
which fault, the engine injects it on schedule, and tests assert the
blast radius (victims reach a terminal state, healthy slots are
bit-unaffected, compile counts stay bounded).

Fault kinds (each exercises a different subsystem):

- ``"nan"`` — poison a live slot's KV rows with NaN (a sampler/matmul
  NaN in the wild). Detected by the engine's ``paranoid`` per-round
  finiteness sweep; the slot is quarantined (rows zeroed) and the
  victim re-queued.
- ``"admit_fail"`` — the next admission this round fails before any
  device work (an allocation failure / transient RESOURCE_EXHAUSTED).
  The victim re-queues with backoff; no slot is touched.
- ``"stall"`` — the round stalls ``seconds`` (a slow dispatch /
  preempted host). Surfaces as a ``slow_steps`` event when the round
  exceeds ``stall_threshold_s``; deadlines keep firing through it.
- ``"cache_corrupt"`` — poison a stored prefix-cache row with NaN (bit
  rot / a buggy writer). The corruption rides a later prefix hit into
  a slot, the paranoid sweep catches it, and the engine invalidates
  the poisoned entries before retrying the victim cold.

Injection happens OUTSIDE the engine's jitted computations (host-side
``.at[].set`` scatters), so a plan never changes compile counts; the
one new executable in a fault-tolerant engine is the ``paranoid``
finiteness check itself (see ``DecodeEngine``).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

#: every fault kind a plan may schedule
FAULT_KINDS = ("nan", "admit_fail", "stall", "cache_corrupt")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: at engine round ``round``, inject ``kind``.

    ``slot`` targets a specific slot ("nan"; None = first active),
    ``row`` a specific prefix-cache row ("cache_corrupt"; None = the
    lowest stored row), ``seconds`` the stall length ("stall")."""

    round: int
    kind: str
    slot: Optional[int] = None
    row: Optional[int] = None
    seconds: float = 0.05

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"fault kind {self.kind!r}: expected one of "
                f"{FAULT_KINDS}")
        if self.round < 0:
            raise ValueError(f"fault round {self.round} < 0")
        if self.seconds < 0:
            raise ValueError(f"stall seconds {self.seconds} < 0")


class FaultPlan:
    """A deterministic schedule of :class:`FaultEvent`s.

    Build explicitly (``FaultPlan([FaultEvent(3, "nan"), ...])``) or
    seeded (:meth:`random`) — either way the plan is pure data, so the
    same plan replays the same failure sequence on every run (the
    chaos-parity gate depends on this). ``injected`` records what the
    engine actually applied, for assertions and soak reports."""

    def __init__(self, events: Sequence[FaultEvent] = ()):
        self.events: List[FaultEvent] = sorted(
            events, key=lambda e: (e.round, e.kind))
        self.injected: List[FaultEvent] = []

    @classmethod
    def random(cls, seed: int, rounds: int,
               kinds: Sequence[str] = FAULT_KINDS,
               rate: float = 0.1) -> "FaultPlan":
        """Seeded plan: each round draws each kind independently with
        probability ``rate`` (aggressive soaks use ``rate >= 0.1``)."""
        for k in kinds:
            if k not in FAULT_KINDS:
                raise ValueError(f"fault kind {k!r}")
        rng = np.random.default_rng(seed)
        events = [FaultEvent(r, k)
                  for r in range(rounds) for k in kinds
                  if rng.random() < rate]
        return cls(events)

    def events_at(self, round_: int) -> List[FaultEvent]:
        return [e for e in self.events if e.round == round_]

    def record(self, event: FaultEvent) -> None:
        self.injected.append(event)

    def __len__(self) -> int:
        return len(self.events)


class ManualClock:
    """Injectable deterministic clock for deadline/stall tests: the
    engine's ``clock=`` knob accepts any zero-arg float callable; this
    one only moves when told to (``advance``), so deadline expiry and
    stall detection become exact assertions instead of sleeps. A
    ``"stall"`` fault advances it instead of sleeping."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def __call__(self) -> float:
        return self._t

    def advance(self, seconds: float) -> float:
        self._t += float(seconds)
        return self._t


def poison_rows(pytree, rows: Sequence[int]):
    """Overwrite the given batch rows of every floating leaf with NaN
    (integer leaves — e.g. the attention ``filled`` counters — are left
    intact so the corruption models bad *values*, not bad bookkeeping).
    Host-side op-by-op dispatch: never enters a jitted program, so
    injection cannot change an engine's compile counts."""
    idx = jnp.asarray(sorted({int(r) for r in rows}), jnp.int32)

    def poison(a):
        if jnp.issubdtype(a.dtype, jnp.floating):
            return a.at[idx].set(jnp.asarray(float("nan"), a.dtype))
        return a

    return jax.tree_util.tree_map(poison, pytree)
