"""Convolution + subsampling (pooling) layers.

Reference: nn/layers/convolution/ConvolutionLayer.java (conv2d as im2col +
GEMM, :135 forward, :109 backward col2im) and SubsamplingLayer.java (max/avg
pooling). TPU-native inversion (SURVEY.md §2.9): convolution is
``lax.conv_general_dilated``, which XLA tiles directly onto the MXU — no
explicit im2col materialization; pooling is ``lax.reduce_window``.

Layouts: activations [N, C, H, W]; kernels [O, I, kH, kW] (OIHW).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.layers import PoolingType
from deeplearning4j_tpu.nn.layers.base import LayerImplBase
from deeplearning4j_tpu.nn.weights import init_weights

_DIMSPEC = ("NCHW", "OIHW", "NCHW")


class ConvolutionImpl(LayerImplBase):
    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        kh, kw = lc.kernel_size
        w = init_weights(
            key,
            (lc.n_out, lc.n_in, kh, kw),
            conf.resolved("weight_init"),
            conf.resolved("dist"),
            dtype,
        )
        b = jnp.full((lc.n_out,), conf.resolved("bias_init"), dtype)
        return {"W": w, "b": b}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        lc = conf.layer
        x = cls.maybe_dropout(conf, x, train, rng)
        ph, pw = lc.padding
        z = lax.conv_general_dilated(
            x,
            params["W"],
            window_strides=tuple(lc.stride),
            padding=((ph, ph), (pw, pw)),
            dimension_numbers=_DIMSPEC,
        )
        z = z + params["b"][None, :, None, None]
        return cls.activation_of(conf)(z), state


class SubsamplingImpl(LayerImplBase):
    """Parameter-free spatial pooling (reference SubsamplingLayer.java)."""

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        lc = conf.layer
        kh, kw = lc.kernel_size
        sh, sw = lc.stride
        ph, pw = lc.padding
        window = (1, 1, kh, kw)
        strides = (1, 1, sh, sw)
        padding = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        if lc.pooling_type == PoolingType.MAX:
            out = lax.reduce_window(
                x, -jnp.inf, lax.max, window, strides, padding
            )
        elif lc.pooling_type == PoolingType.SUM:
            out = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        elif lc.pooling_type == PoolingType.AVG:
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
            out = s / float(kh * kw)
        else:
            raise ValueError(f"Unknown pooling type {lc.pooling_type}")
        return out, state
