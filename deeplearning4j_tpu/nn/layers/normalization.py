"""Normalization layers: batch norm + local response normalization.

Reference: nn/layers/normalization/BatchNormalization.java (402 LoC) and
LocalResponseNormalization.java. Batch-norm running statistics are carried
in the functional state pytree (no mutation), the TPU-idiomatic equivalent
of the reference's in-place moving averages.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers.base import LayerImplBase


class BatchNormImpl(LayerImplBase):
    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        n = lc.n_out or lc.n_in
        return {
            "gamma": jnp.full((n,), lc.gamma, dtype),
            "beta": jnp.full((n,), lc.beta, dtype),
        }

    @classmethod
    def init_state(cls, conf, dtype=jnp.float32):
        lc = conf.layer
        n = lc.n_out or lc.n_in
        return {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        lc = conf.layer
        # Normalize over all axes except the channel axis (axis 1 for 4-d
        # CNN activations, axis 1 for [N, C]).
        axes = (0,) if x.ndim == 2 else (0, 2, 3)
        shape = (1, -1) if x.ndim == 2 else (1, -1, 1, 1)
        if train:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            decay = lc.decay
            new_state = {
                "mean": decay * state["mean"] + (1 - decay) * mean,
                "var": decay * state["var"] + (1 - decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        xhat = (x - mean.reshape(shape)) * lax.rsqrt(
            var.reshape(shape) + lc.eps
        )
        if lc.lock_gamma_beta:
            out = xhat
        else:
            out = params["gamma"].reshape(shape) * xhat + params["beta"].reshape(
                shape
            )
        return out, new_state


def layer_norm(x, g, b, axis: int = -1, eps: float = 1e-5):
    """LayerNorm over ``axis``; moments at >= f32 so the bf16 compute
    path keeps a stable normalizer (promote, don't hard-cast — the f64
    gradient-check path must stay f64). Shared by LayerNormImpl (axis 1
    on [N, C, T]) and TransformerBlockImpl (trailing axis on [N, T, C]).
    """
    ct = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(ct)
    mu = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.var(xf, axis=axis, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + jnp.asarray(eps, ct))
    shape = [1] * x.ndim
    shape[axis] = -1
    return (y * g.astype(ct).reshape(shape)
            + b.astype(ct).reshape(shape)).astype(x.dtype)


class LayerNormImpl(LayerImplBase):
    """Per-example LayerNorm over the channel axis (conf bean
    LayerNormalization); works on [N, C] and [N, C, T]."""

    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        n = lc.n_out or lc.n_in
        return {"g": jnp.ones((n,), dtype), "b": jnp.zeros((n,), dtype)}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None,
              mask=None):
        lc = conf.layer
        y = layer_norm(x, params["g"], params["b"], axis=1, eps=lc.eps)
        return y, None


class LRNImpl(LayerImplBase):
    """Across-channel local response normalization (reference
    LocalResponseNormalization.java):
    y = x / (k + alpha * sum_{j in window} x_j^2)^beta.
    """

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        lc = conf.layer
        half = int(lc.n) // 2
        sq = x * x
        # Sliding window sum over the channel axis via reduce_window.
        s = lax.reduce_window(
            sq,
            0.0,
            lax.add,
            window_dimensions=(1, 2 * half + 1, 1, 1),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (half, half), (0, 0), (0, 0)),
        )
        return x / jnp.power(lc.k + lc.alpha * s, lc.beta), state
