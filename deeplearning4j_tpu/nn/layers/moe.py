"""Mixture-of-experts dense layer (conf bean + impl).

NEW capability relative to the reference (SURVEY.md §2.7 expert-
parallelism mandate): a capacity-factored top-k MoE FFN block that slots
into a MultiLayerNetwork/ComputationGraph stack next to attention layers
(models/zoo.py ``moe_transformer_lm``). Dispatch math lives in
parallel/expert_parallel.py; this layer adapts it to the framework's
layer contract:

- accepts [N, C] feed-forward or [N, C, T] recurrent activations
  (tokens = N·T);
- the load-balancing auxiliary loss is returned through the layer-state
  channel (``{"aux_loss": ...}``) and added to the training score by
  MultiLayerNetwork._loss_fn weighted by ``aux_weight`` — the same
  functional-state route BatchNormalization uses for running stats;
- ``ep_axis`` names a mesh axis for explicit all-to-all expert
  parallelism when the surrounding train step runs under shard_map
  (same convention as MultiHeadSelfAttention.ring_axis), with
  ``W_up/W_down`` holding the local expert slice.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import FeedForwardLayer
from deeplearning4j_tpu.nn.conf.serde import register_bean
from deeplearning4j_tpu.nn.layers.base import LayerImplBase
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.parallel.expert_parallel import moe_apply


@register_bean("MoeDense")
@dataclasses.dataclass
class MoeDense(FeedForwardLayer):
    """Conf bean: n_in must equal n_out (the block is residual-shaped:
    route -> expert FFN (n_in -> n_hidden -> n_out) -> combine [+ x])."""

    n_experts: int = 8
    n_hidden: int = 0           # 0 => 4 * n_in
    capacity_factor: float = 1.25
    top_k: int = 1
    aux_weight: float = 0.01    # weight of the load-balancing loss
    residual: bool = True
    ep_axis: Optional[str] = None  # expert-parallel mesh axis


class MoeDenseImpl(LayerImplBase):
    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        if lc.n_out and lc.n_out != lc.n_in:
            raise ValueError(
                f"MoeDense needs n_in == n_out, got {lc.n_in}/{lc.n_out}")
        d, e = lc.n_in, lc.n_experts
        h = lc.n_hidden or 4 * d
        kr, ku, kd = jax.random.split(key, 3)
        scheme = conf.resolved("weight_init")
        dist = conf.resolved("dist")
        return {
            "router": init_weights(kr, (d, e), scheme, dist, dtype),
            "W_up": init_weights(ku, (e, d, h), scheme, dist, dtype),
            "W_down": init_weights(kd, (e, h, d), scheme, dist, dtype),
        }

    @classmethod
    def init_state(cls, conf, dtype=jnp.float32):
        # Registers the layer in the state pytree so _forward_fn threads
        # the per-batch aux loss out to _loss_fn.
        return {"aux_loss": jnp.zeros((), dtype)}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None,
              mask=None):
        lc = conf.layer
        x = cls.maybe_dropout(conf, x, train, rng)
        recurrent = x.ndim == 3  # [N, C, T]
        if recurrent:
            n, c, t = x.shape
            tokens = jnp.transpose(x, (0, 2, 1)).reshape(n * t, c)
        else:
            tokens = x
        y, aux = moe_apply(
            params, tokens,
            capacity_factor=lc.capacity_factor,
            top_k=lc.top_k,
            ep_axis=lc.ep_axis,
        )
        if lc.residual:
            y = y + tokens
        y = cls.activation_of(conf)(y)
        if recurrent:
            y = jnp.transpose(y.reshape(n, t, c), (0, 2, 1))
            if mask is not None:
                y = y * mask[:, None, :]
        return y, {"aux_loss": aux}
