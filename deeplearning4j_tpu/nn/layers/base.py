"""Shared layer-impl machinery: dropout, dropconnect, activation resolution.

Reference counterparts: nn/layers/BaseLayer.java (preOutput :327, activate
:337-352, dropout hook :424-428) and util/Dropout.java (applyDropout :32 —
inverted dropout with a Bernoulli mask; applyDropConnect :20).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.activations import activation as act_fn

Array = jax.Array


def apply_dropout(x: Array, rate: float, rng: Optional[Array]) -> Array:
    """Inverted dropout on input activations (reference Dropout.applyDropout
    :32). ``rate`` is the DROP probability, matching the reference's
    ``dropOut`` semantics. No-op when rng is None (inference)."""
    if rate <= 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def apply_dropconnect(w: Array, rate: float, rng: Optional[Array]) -> Array:
    """DropConnect on a weight matrix (reference Dropout.applyDropConnect)."""
    if rate <= 0.0 or rng is None:
        return w
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, w.shape)
    return jnp.where(mask, w / keep, 0.0).astype(w.dtype)


class LayerImplBase:
    """Default no-param, identity-state implementation skeleton."""

    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        return {}

    @classmethod
    def init_state(cls, conf, dtype=jnp.float32):
        return None

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        raise NotImplementedError

    # -- helpers -------------------------------------------------------
    @staticmethod
    def activation_of(conf):
        return act_fn(conf.resolved("activation"))

    @staticmethod
    def dropout_of(conf) -> float:
        return float(conf.resolved("dropout") or 0.0)

    @staticmethod
    def maybe_dropout(conf, x, train, rng):
        if train and rng is not None:
            return apply_dropout(x, LayerImplBase.dropout_of(conf), rng)
        return x
