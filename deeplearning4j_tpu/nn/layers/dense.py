"""Dense + Output layer implementations.

Reference: nn/layers/feedforward/dense/DenseLayer.java over BaseLayer
(preOutput = input.mmul(W).addiRowVector(b), BaseLayer.java:327) and
nn/layers/OutputLayer.java / BaseOutputLayer.java (439 LoC: loss function +
labels). The matmul is the MXU hot path; XLA fuses the bias add and
activation into the GEMM epilogue.
"""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import LayerImplBase, apply_dropconnect
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.losses import loss_fn
import jax


class DenseImpl(LayerImplBase):
    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        wkey, _ = jax.random.split(key)
        w = init_weights(
            wkey,
            (lc.n_in, lc.n_out),
            conf.resolved("weight_init"),
            conf.resolved("dist"),
            dtype,
        )
        b = jnp.full((lc.n_out,), conf.resolved("bias_init"), dtype)
        return {"W": w, "b": b}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        x = cls.maybe_dropout(conf, x, train, rng)
        w = params["W"]
        if train and rng is not None and conf.use_drop_connect:
            w = apply_dropconnect(w, cls.dropout_of(conf), rng)
        z = x @ w + params["b"]
        return cls.activation_of(conf)(z), state


class OutputImpl(DenseImpl):
    """Dense layer whose conf carries the loss function; scoring happens in
    the network-level loss (reference BaseOutputLayer.computeScore)."""

    @classmethod
    def loss(cls, conf, activations, labels, mask=None):
        return loss_fn(conf.layer.loss_function)(activations, labels, mask)
