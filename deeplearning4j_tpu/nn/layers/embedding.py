"""Embedding layer.

Reference: nn/layers/feedforward/embedding/EmbeddingLayer.java — input is a
column of integer indices [N, 1]; output is W[idx] + b. On TPU the lookup is
``jnp.take`` which XLA lowers to a gather; backprop produces a scatter-add.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import LayerImplBase
from deeplearning4j_tpu.nn.weights import init_weights


class EmbeddingImpl(LayerImplBase):
    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        w = init_weights(
            key,
            (lc.n_in, lc.n_out),
            conf.resolved("weight_init"),
            conf.resolved("dist"),
            dtype,
        )
        b = jnp.full((lc.n_out,), conf.resolved("bias_init"), dtype)
        return {"W": w, "b": b}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2:
            idx = idx[:, 0]
        z = jnp.take(params["W"], idx, axis=0) + params["b"]
        return cls.activation_of(conf)(z), state
