"""Pretrainable layers: RBM (contrastive divergence) + (denoising) AutoEncoder.

Reference: nn/layers/feedforward/rbm/RBM.java (contrastiveDivergence :101,
computeGradientAndScore CD-k :110-178, sampleHiddenGivenVisible :225,
gibbhVh :267; BINARY/GAUSSIAN/RECTIFIED/SOFTMAX unit kinds) and
nn/layers/feedforward/autoencoder/AutoEncoder.java. The reference's stateful
device RNG (RBM.java:236,:251) becomes explicit ``jax.random`` keys threaded
through the Gibbs chain; the whole CD-k update is one jitted computation.

CD-k is not the gradient of a tractable loss, so ``RBMImpl`` provides
``pretrain_value_and_grad`` directly instead of a loss for autodiff.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import HiddenUnit, VisibleUnit
from deeplearning4j_tpu.nn.layers.base import LayerImplBase
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.losses import loss_fn

Array = jax.Array


class RBMImpl(LayerImplBase):
    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        w = init_weights(
            key,
            (lc.n_in, lc.n_out),
            conf.resolved("weight_init"),
            conf.resolved("dist"),
            dtype,
        )
        b = jnp.full((lc.n_out,), conf.resolved("bias_init"), dtype)
        vb = jnp.full((lc.n_in,), lc.visible_bias_init, dtype)
        return {"W": w, "b": b, "vb": vb}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        x = cls.maybe_dropout(conf, x, train, rng)
        z = x @ params["W"] + params["b"]
        return cls.activation_of(conf)(z), state

    # ------------------------------------------------------------------
    # CD-k machinery
    # ------------------------------------------------------------------
    @classmethod
    def _hidden_mean(cls, conf, params, v):
        z = v @ params["W"] + params["b"]
        hu = conf.layer.hidden_unit
        if hu == HiddenUnit.BINARY:
            return jax.nn.sigmoid(z)
        if hu == HiddenUnit.GAUSSIAN:
            return z
        if hu == HiddenUnit.RECTIFIED:
            return jax.nn.relu(z)
        if hu == HiddenUnit.SOFTMAX:
            return jax.nn.softmax(z, axis=-1)
        raise ValueError(hu)

    @classmethod
    def _sample_hidden(cls, conf, params, v, key):
        mean = cls._hidden_mean(conf, params, v)
        hu = conf.layer.hidden_unit
        if hu == HiddenUnit.BINARY:
            return mean, jax.random.bernoulli(key, mean).astype(v.dtype)
        if hu == HiddenUnit.GAUSSIAN:
            return mean, mean + jax.random.normal(key, mean.shape, v.dtype)
        if hu == HiddenUnit.RECTIFIED:
            # NReLU sampling: max(0, mean + N(0, sigmoid(mean))).
            noise = jax.random.normal(key, mean.shape, v.dtype)
            return mean, jax.nn.relu(
                mean + noise * jnp.sqrt(jax.nn.sigmoid(mean) + 1e-8)
            )
        if hu == HiddenUnit.SOFTMAX:
            return mean, mean
        raise ValueError(hu)

    @classmethod
    def _visible_mean(cls, conf, params, h):
        z = h @ params["W"].T + params["vb"]
        vu = conf.layer.visible_unit
        if vu == VisibleUnit.BINARY:
            return jax.nn.sigmoid(z)
        if vu in (VisibleUnit.GAUSSIAN, VisibleUnit.LINEAR):
            return z
        if vu == VisibleUnit.SOFTMAX:
            return jax.nn.softmax(z, axis=-1)
        raise ValueError(vu)

    @classmethod
    def _sample_visible(cls, conf, params, h, key):
        mean = cls._visible_mean(conf, params, h)
        vu = conf.layer.visible_unit
        if vu == VisibleUnit.BINARY:
            return mean, jax.random.bernoulli(key, mean).astype(h.dtype)
        if vu == VisibleUnit.GAUSSIAN:
            return mean, mean + jax.random.normal(key, mean.shape, h.dtype)
        return mean, mean

    @classmethod
    def pretrain_value_and_grad(cls, conf, params, x, rng):
        """One CD-k estimate: (score, grads) with grads oriented for
        gradient DESCENT (params -= lr * grad), matching the reference's
        sign handling in RBM.computeGradientAndScore :140-178."""
        lc = conf.layer
        k = max(1, lc.k)
        n = x.shape[0]

        key0, key_chain = jax.random.split(rng)
        h0_mean, h0_sample = cls._sample_hidden(conf, params, x, key0)

        def gibbs_step(carry, key):
            h_sample = carry
            kv, kh = jax.random.split(key)
            v_mean, v_sample = cls._sample_visible(conf, params, h_sample, kv)
            h_mean, h_new = cls._sample_hidden(conf, params, v_sample, kh)
            return h_new, (v_mean, v_sample, h_mean)

        keys = jax.random.split(key_chain, k)
        _, (v_means, v_samples, h_means) = jax.lax.scan(
            gibbs_step, h0_sample, keys
        )
        vk_mean, vk = v_means[-1], v_samples[-1]
        hk_mean = h_means[-1]

        w_grad = -(x.T @ h0_mean - vk.T @ hk_mean) / n
        hb_grad = -jnp.mean(h0_mean - hk_mean, axis=0)
        vb_grad = -jnp.mean(x - vk, axis=0)
        score = loss_fn(lc.loss_function)(vk_mean, x)
        return score, {"W": w_grad, "b": hb_grad, "vb": vb_grad}


class AutoEncoderImpl(LayerImplBase):
    """Denoising autoencoder with tied decode weights (reference
    AutoEncoder.java; corruption via ``corruption_level`` Bernoulli mask)."""

    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        w = init_weights(
            key,
            (lc.n_in, lc.n_out),
            conf.resolved("weight_init"),
            conf.resolved("dist"),
            dtype,
        )
        b = jnp.full((lc.n_out,), conf.resolved("bias_init"), dtype)
        vb = jnp.full((lc.n_in,), lc.visible_bias_init, dtype)
        return {"W": w, "b": b, "vb": vb}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        x = cls.maybe_dropout(conf, x, train, rng)
        z = x @ params["W"] + params["b"]
        return cls.activation_of(conf)(z), state

    @classmethod
    def pretrain_loss(cls, conf, params, x, rng):
        lc = conf.layer
        act = cls.activation_of(conf)
        corrupted = x
        if lc.corruption_level > 0.0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - lc.corruption_level, x.shape)
            corrupted = x * keep.astype(x.dtype)
        h = act(corrupted @ params["W"] + params["b"])
        recon = act(h @ params["W"].T + params["vb"])
        score = loss_fn(lc.loss_function)(recon, x)
        if getattr(lc, "sparsity", 0.0):
            rho, rho_hat = lc.sparsity, jnp.mean(h, axis=0)
            eps = 1e-7
            kl = rho * jnp.log(rho / (rho_hat + eps)) + (1 - rho) * jnp.log(
                (1 - rho) / (1 - rho_hat + eps)
            )
            score = score + jnp.sum(kl)
        return score

    @classmethod
    def pretrain_value_and_grad(cls, conf, params, x, rng):
        return jax.value_and_grad(
            lambda p: cls.pretrain_loss(conf, p, x, rng)
        )(params)
