"""Pretrainable layers: RBM (contrastive divergence) + (denoising) AutoEncoder.

Reference: nn/layers/feedforward/rbm/RBM.java (contrastiveDivergence :101,
computeGradientAndScore CD-k :110-178, sampleHiddenGivenVisible :225,
gibbhVh :267; BINARY/GAUSSIAN/RECTIFIED/SOFTMAX unit kinds) and
nn/layers/feedforward/autoencoder/AutoEncoder.java. The reference's stateful
device RNG (RBM.java:236,:251) becomes explicit ``jax.random`` keys threaded
through the Gibbs chain; the whole CD-k update is one jitted computation.

CD-k is not the gradient of a tractable loss, so ``RBMImpl`` provides
``pretrain_value_and_grad`` directly instead of a loss for autodiff.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import HiddenUnit, VisibleUnit
from deeplearning4j_tpu.nn.layers.base import LayerImplBase
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.losses import loss_fn

Array = jax.Array


class RBMImpl(LayerImplBase):
    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        w = init_weights(
            key,
            (lc.n_in, lc.n_out),
            conf.resolved("weight_init"),
            conf.resolved("dist"),
            dtype,
        )
        b = jnp.full((lc.n_out,), conf.resolved("bias_init"), dtype)
        vb = jnp.full((lc.n_in,), lc.visible_bias_init, dtype)
        return {"W": w, "b": b, "vb": vb}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        x = cls.maybe_dropout(conf, x, train, rng)
        z = x @ params["W"] + params["b"]
        return cls.activation_of(conf)(z), state

    # ------------------------------------------------------------------
    # CD-k machinery
    # ------------------------------------------------------------------
    @classmethod
    def _hidden_mean(cls, conf, params, v):
        z = v @ params["W"] + params["b"]
        hu = conf.layer.hidden_unit
        if hu == HiddenUnit.BINARY:
            return jax.nn.sigmoid(z)
        if hu == HiddenUnit.GAUSSIAN:
            return z
        if hu == HiddenUnit.RECTIFIED:
            return jax.nn.relu(z)
        if hu == HiddenUnit.SOFTMAX:
            return jax.nn.softmax(z, axis=-1)
        raise ValueError(hu)

    @classmethod
    def _sample_hidden(cls, conf, params, v, key):
        mean = cls._hidden_mean(conf, params, v)
        hu = conf.layer.hidden_unit
        if hu == HiddenUnit.BINARY:
            return mean, jax.random.bernoulli(key, mean).astype(v.dtype)
        if hu == HiddenUnit.GAUSSIAN:
            return mean, mean + jax.random.normal(key, mean.shape, v.dtype)
        if hu == HiddenUnit.RECTIFIED:
            # NReLU sampling: max(0, mean + N(0, sigmoid(mean))).
            noise = jax.random.normal(key, mean.shape, v.dtype)
            return mean, jax.nn.relu(
                mean + noise * jnp.sqrt(jax.nn.sigmoid(mean) + 1e-8)
            )
        if hu == HiddenUnit.SOFTMAX:
            return mean, mean
        raise ValueError(hu)

    @classmethod
    def _visible_mean(cls, conf, params, h):
        z = h @ params["W"].T + params["vb"]
        vu = conf.layer.visible_unit
        if vu == VisibleUnit.BINARY:
            return jax.nn.sigmoid(z)
        if vu in (VisibleUnit.GAUSSIAN, VisibleUnit.LINEAR):
            return z
        if vu == VisibleUnit.SOFTMAX:
            return jax.nn.softmax(z, axis=-1)
        raise ValueError(vu)

    @classmethod
    def _sample_visible(cls, conf, params, h, key):
        mean = cls._visible_mean(conf, params, h)
        vu = conf.layer.visible_unit
        if vu == VisibleUnit.BINARY:
            return mean, jax.random.bernoulli(key, mean).astype(h.dtype)
        if vu == VisibleUnit.GAUSSIAN:
            return mean, mean + jax.random.normal(key, mean.shape, h.dtype)
        return mean, mean

    @classmethod
    def pretrain_value_and_grad(cls, conf, params, x, rng):
        """One CD-k estimate: (score, grads) with grads oriented for
        gradient DESCENT (params -= lr * grad), matching the reference's
        sign handling in RBM.computeGradientAndScore :140-178."""
        lc = conf.layer
        k = max(1, lc.k)
        n = x.shape[0]

        key0, key_chain = jax.random.split(rng)
        h0_mean, h0_sample = cls._sample_hidden(conf, params, x, key0)

        def gibbs_step(carry, key):
            h_sample = carry
            kv, kh = jax.random.split(key)
            v_mean, v_sample = cls._sample_visible(conf, params, h_sample, kv)
            h_mean, h_new = cls._sample_hidden(conf, params, v_sample, kh)
            return h_new, (v_mean, v_sample, h_mean)

        keys = jax.random.split(key_chain, k)
        _, (v_means, v_samples, h_means) = jax.lax.scan(
            gibbs_step, h0_sample, keys
        )
        vk_mean, vk = v_means[-1], v_samples[-1]
        hk_mean = h_means[-1]

        w_grad = -(x.T @ h0_mean - vk.T @ hk_mean) / n
        hb_grad = -jnp.mean(h0_mean - hk_mean, axis=0)
        vb_grad = -jnp.mean(x - vk, axis=0)
        score = loss_fn(lc.loss_function)(vk_mean, x)
        return score, {"W": w_grad, "b": hb_grad, "vb": vb_grad}


class AutoEncoderImpl(LayerImplBase):
    """Denoising autoencoder with tied decode weights (reference
    AutoEncoder.java; corruption via ``corruption_level`` Bernoulli mask)."""

    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        w = init_weights(
            key,
            (lc.n_in, lc.n_out),
            conf.resolved("weight_init"),
            conf.resolved("dist"),
            dtype,
        )
        b = jnp.full((lc.n_out,), conf.resolved("bias_init"), dtype)
        vb = jnp.full((lc.n_in,), lc.visible_bias_init, dtype)
        return {"W": w, "b": b, "vb": vb}

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None, mask=None):
        x = cls.maybe_dropout(conf, x, train, rng)
        z = x @ params["W"] + params["b"]
        return cls.activation_of(conf)(z), state

    @classmethod
    def pretrain_loss(cls, conf, params, x, rng):
        lc = conf.layer
        act = cls.activation_of(conf)
        corrupted = x
        if lc.corruption_level > 0.0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - lc.corruption_level, x.shape)
            corrupted = x * keep.astype(x.dtype)
        h = act(corrupted @ params["W"] + params["b"])
        recon = act(h @ params["W"].T + params["vb"])
        score = loss_fn(lc.loss_function)(recon, x)
        if getattr(lc, "sparsity", 0.0):
            rho, rho_hat = lc.sparsity, jnp.mean(h, axis=0)
            eps = 1e-7
            kl = rho * jnp.log(rho / (rho_hat + eps)) + (1 - rho) * jnp.log(
                (1 - rho) / (1 - rho_hat + eps)
            )
            score = score + jnp.sum(kl)
        return score

    @classmethod
    def pretrain_value_and_grad(cls, conf, params, x, rng):
        return jax.value_and_grad(
            lambda p: cls.pretrain_loss(conf, p, x, rng)
        )(params)


class RecursiveAutoEncoderImpl(LayerImplBase):
    """Recursive autoencoder — backprop through structure (reference
    nn/layers/feedforward/autoencoder/recursive/RecursiveAutoEncoder.java
    + RecursiveParamInitializer.java: UNTIED encoder W [nIn, nOut] /
    decoder U [nOut, nIn], hidden bias b, visible bias vb).

    The reference's computeGradientAndScore (:102-160) greedily folds the
    input rows: starting from the base pair [x0; x1], each step prepends
    the next row to the running stack, encodes/decodes every row, and
    adds 0.5 * mean((z - stack)^2) to the score (:155). Because encode/
    decode act row-wise, row j's reconstruction error err_j appears in
    every step whose stack contains it — steps have sizes m = 2..R, and
    the step of size m contributes (1/m) * sum_{j<m} err_j. The score is
    therefore computed here in closed form as sum_j w_j * err_j with
    tail-harmonic weights w_j = sum_{m=max(j+1,2)}^{R} 1/m — one encoder
    and one decoder matmul over all rows instead of the reference's
    O(R^2) recomputation loop (the TPU-native restructuring).

    Gradients are the exact autodiff of this score; the reference's
    hand-written accumulation (:126-151) is explicitly marked "TODO
    review code below to confirm computation" (:100) and mixes up its
    own operand shapes, so the score — not that loop — is the parity
    contract.
    """

    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        kw, ku = jax.random.split(key)
        scheme = conf.resolved("weight_init")
        dist = conf.resolved("dist")
        w = init_weights(kw, (lc.n_in, lc.n_out), scheme, dist, dtype)
        u = init_weights(ku, (lc.n_out, lc.n_in), scheme, dist, dtype)
        b = jnp.full((lc.n_out,), conf.resolved("bias_init"), dtype)
        vb = jnp.full((lc.n_in,), lc.visible_bias_init, dtype)
        return {"W": w, "U": u, "b": b, "vb": vb}

    @classmethod
    def encode(cls, conf, params, x):
        return cls.activation_of(conf)(x @ params["W"] + params["b"])

    @classmethod
    def decode(cls, conf, params, y):
        return cls.activation_of(conf)(y @ params["U"] + params["vb"])

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None,
              mask=None):
        # Reference activate(input) :81-84 — forward through a stack is
        # the encoding.
        x = cls.maybe_dropout(conf, x, train, rng)
        return cls.encode(conf, params, x), state

    @classmethod
    def pretrain_loss(cls, conf, params, x, rng):
        rows = x.shape[0]
        if rows < 2:
            raise ValueError(
                "RecursiveAutoEncoder needs >= 2 rows to fold")
        z = cls.decode(conf, params, cls.encode(conf, params, x))
        err = 0.5 * jnp.mean((z - x) ** 2, axis=-1)  # [R] per-row
        # w_j = sum_{m=max(j+1,2)}^{R} 1/m  (tail harmonic numbers)
        m = jnp.arange(rows + 1, dtype=x.dtype)
        inv = jnp.where(m >= 2, 1.0 / jnp.maximum(m, 1), 0.0)
        # tail[k] = sum_{m=k}^{R} 1/m for k in 0..R
        tail = jnp.cumsum(inv[::-1])[::-1]
        lo = jnp.maximum(jnp.arange(rows) + 1, 2)
        weights = tail[lo]
        return jnp.sum(weights * err)

    @classmethod
    def pretrain_value_and_grad(cls, conf, params, x, rng):
        return jax.value_and_grad(
            lambda p: cls.pretrain_loss(conf, p, x, rng)
        )(params)
