"""Runtime layer implementations (pure functions over pytrees).

Replaces reference nn/layers/** (BaseLayer.java:327 preOutput, per-type
subclasses) and the LayerFactories indirection (nn/layers/factory/*.java,
used from MultiLayerNetwork.init :351): here the "factory" is a plain
registry from conf-bean class to a stateless impl class.

Impl contract (all classmethods, all pure):
- ``init(key, conf, dtype) -> params`` — parameter pytree for one layer.
- ``init_state(conf, dtype) -> state | None`` — mutable-state pytree
  (e.g. batch-norm running stats), threaded functionally.
- ``apply(conf, params, x, state, train, rng, mask) -> (out, state)``.
- pretrainable impls add ``pretrain_value_and_grad(conf, params, x, rng)``.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers import (
    attention,
    convolution,
    dense,
    embedding,
    moe,
    normalization,
    pretrain,
    recurrent,
)

_IMPLS = {
    L.DenseLayer: dense.DenseImpl,
    L.OutputLayer: dense.OutputImpl,
    L.EmbeddingLayer: embedding.EmbeddingImpl,
    L.ConvolutionLayer: convolution.ConvolutionImpl,
    L.SubsamplingLayer: convolution.SubsamplingImpl,
    L.LocalResponseNormalization: normalization.LRNImpl,
    L.LayerNormalization: normalization.LayerNormImpl,
    L.BatchNormalization: normalization.BatchNormImpl,
    L.GravesLSTM: recurrent.LSTMImpl,
    L.ImageLSTM: recurrent.ImageLSTMImpl,
    L.GravesBidirectionalLSTM: recurrent.BiLSTMImpl,
    L.GRU: recurrent.GRUImpl,
    L.RnnOutputLayer: recurrent.RnnOutputImpl,
    L.RBM: pretrain.RBMImpl,
    L.AutoEncoder: pretrain.AutoEncoderImpl,
    L.RecursiveAutoEncoder: pretrain.RecursiveAutoEncoderImpl,
    attention.MultiHeadSelfAttention: attention.AttentionImpl,
    attention.TransformerBlock: attention.TransformerBlockImpl,
    moe.MoeDense: moe.MoeDenseImpl,
}


def get_impl(layer_bean: L.Layer):
    """conf bean -> runtime impl (reference LayerFactories.getFactory)."""
    try:
        return _IMPLS[type(layer_bean)]
    except KeyError:
        raise ValueError(
            f"No runtime implementation for layer bean {type(layer_bean).__name__}"
        ) from None


def register_impl(bean_cls, impl_cls) -> None:
    _IMPLS[bean_cls] = impl_cls
