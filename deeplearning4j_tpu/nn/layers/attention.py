"""Multi-head self-attention layer.

NEW capability relative to the reference (2015 — predates attention;
SURVEY.md §5.7 mandates long-context support as first-class in this
framework). Follows the framework's [N, C, T] recurrent layout so it
composes with GravesLSTM/RnnOutputLayer in a MultiLayerNetwork stack.

When ``ring_axis`` names a mesh axis present at trace time (sequence
parallelism), the core attention runs as ring attention over that axis
(parallel/sequence_parallel.py); otherwise it is a fused dense
flash-style attention that XLA maps onto the MXU.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import BaseRecurrentLayer
from deeplearning4j_tpu.nn.conf.serde import register_bean
from deeplearning4j_tpu.nn.layers.base import LayerImplBase
from deeplearning4j_tpu.nn.weights import init_weights

# -- tensor-parallel head sharding (serving TP, ISSUE 12) --------------
#
# Trace-time marker stack: when the enclosing program is the body of a
# fully-manual ``shard_map`` over a TP mesh axis with attention weights
# head-sharded (Wq/Wk/Wv column-sliced so each shard owns n_heads/TP
# whole heads, Wo row-sliced), the attention layers must (a) reshape
# onto the LOCAL head count and (b) all-reduce the partial output
# projection — the Megatron self-attention block. The serving decode
# engine (serving/tp.py) enters this context inside its shard_map
# bodies; training TP needs none of it (the trainers shard via GSPMD
# param specs, parallel/data_parallel.py:tp_param_specs, and XLA
# derives the same collective). Thread-local: engines in one process
# may trace concurrently (the in-process replica pattern), and a tp>1
# scope must not leak into a sibling engine's plain-jit trace.
_TP_SCOPES = threading.local()


def _tp_stack() -> List[Tuple[str, int]]:
    stack = getattr(_TP_SCOPES, "stack", None)
    if stack is None:
        stack = _TP_SCOPES.stack = []
    return stack


@contextlib.contextmanager
def tp_head_shards(axis_name: str, size: int):
    """Declare that attention params (and KV caches) within this trace
    are head-sharded ``size``-ways over mesh axis ``axis_name``."""
    stack = _tp_stack()
    stack.append((str(axis_name), int(size)))
    try:
        yield
    finally:
        stack.pop()


def _tp_scope() -> Optional[Tuple[str, int]]:
    stack = _tp_stack()
    return stack[-1] if stack else None


def _tp_local_heads(n_heads: int, tp: Tuple[str, int]) -> int:
    axis, size = tp
    if n_heads % size:
        raise ValueError(
            f"tensor parallelism over {axis!r} needs tp ({size}) to "
            f"divide n_heads ({n_heads}): head sharding slices whole "
            "heads")
    return n_heads // size


@register_bean("MultiHeadSelfAttention")
@dataclasses.dataclass
class MultiHeadSelfAttention(BaseRecurrentLayer):
    """Conf bean: n_in = model width C, n_out = model width out; heads
    must divide n_out."""

    n_heads: int = 4
    causal: bool = True
    ring_axis: Optional[str] = None  # sequence-parallel mesh axis
    # sub-chunk the visiting K/V block inside the ring (blockwise online
    # softmax): bounds the per-device score buffer at
    # [B, H, T_local, ring_block_size] instead of [.., T_local, T_local]
    # — the memory lever for LONG local shards; None = whole block
    ring_block_size: Optional[int] = None
    # which SP schedule runs over ring_axis: "ring" (K/V ppermute hops,
    # O(T_local) score memory) or "ulysses" (two all-to-alls swap
    # heads<->time, full-T attention on H/P heads per device — fewer,
    # larger collectives; needs n_heads % sp == 0)
    sp_mode: str = "ring"
    # pallas flash-attention path: True forces it (TPU, no mask, T
    # multiple of 128 and >= 256), False forces dense, None = auto —
    # engages at T >= 2048 when T % 512 == 0 (healthy kernel blocks),
    # and at T >= 8192 unconditionally (dense OOMs long before 32k)
    use_flash: Optional[bool] = None
    # pallas PAGED-attention decode kernel (serving paged_kv engines;
    # ISSUE 12): True forces it (TPU), False forces the XLA
    # gather-by-block-table program, "interpret" runs the kernel in
    # pallas interpret mode (the CPU parity-testing hook), None = auto
    # — kernel on TPU, XLA gather everywhere else (see
    # _should_use_flash_paged)
    use_flash_paged: Optional[object] = None
    # KV-cache length for rnn_time_step streaming (reference
    # rnnTimeStep contract, BaseRecurrentLayer stateMap): a FIXED-size
    # right-aligned sliding cache so the decode step compiles once
    # (static shapes — no per-step recompilation as context grows);
    # tokens older than this many steps fall out of the window
    stream_max_t: int = 512


class AttentionImpl(LayerImplBase):
    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        kq, kk, kv, ko = jax.random.split(key, 4)
        scheme = conf.resolved("weight_init")
        dist = conf.resolved("dist")
        d_in, d = lc.n_in, lc.n_out
        return {
            "Wq": init_weights(kq, (d_in, d), scheme, dist, dtype),
            "Wk": init_weights(kk, (d_in, d), scheme, dist, dtype),
            "Wv": init_weights(kv, (d_in, d), scheme, dist, dtype),
            "Wo": init_weights(ko, (d, d), scheme, dist, dtype),
            "b": jnp.zeros((d,), dtype),
        }

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None,
              mask=None):
        lc = conf.layer
        h = lc.n_heads
        d = lc.n_out
        if d % h:
            raise ValueError(f"n_out {d} not divisible by n_heads {h}")
        dh = d // h
        tp = _tp_scope()
        if tp is not None:
            h = _tp_local_heads(h, tp)
        x = cls.maybe_dropout(conf, x, train, rng)
        xt = jnp.transpose(x, (0, 2, 1))  # [N, T, C]

        def split_heads(m):
            y = xt @ m  # [N, T, D] (local D/TP under tp head sharding)
            return jnp.transpose(
                y.reshape(y.shape[0], y.shape[1], h, dh), (0, 2, 1, 3)
            )  # [N, H, T, dh]

        q = split_heads(params["Wq"])
        k = split_heads(params["Wk"])
        v = split_heads(params["Wv"])
        o, state = cls._attend_core(lc, q, k, v, state, train, mask)

        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(
            o.shape[0], o.shape[2], h * dh
        )  # [N, T, D] (local heads under tp)
        if tp is not None:
            # row-parallel output projection: each shard's o covers
            # its own heads, the matmul yields a partial [N, T, D]
            # sum — ONE all-reduce completes it (bias added once,
            # after). Partials accumulate AND all-reduce in f32,
            # rounding to the compute dtype once: bf16 partials
            # rounded per shard then summed double-round, and the
            # extra noise flips argmaxes vs the single-chip engine
            # (the bench id-match gate caught it at tp=2/bf16)
            out = jax.lax.dot_general(
                o, params["Wo"], (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            out = jax.lax.psum(out, tp[0]).astype(o.dtype)
        else:
            out = o @ params["Wo"]
        out = out + params["b"]
        out = cls.activation_of(conf)(out)
        out = jnp.transpose(out, (0, 2, 1))  # [N, D, T]
        if mask is not None:
            out = out * mask[:, None, :]
        return out, state

    @classmethod
    def _attend_core(cls, lc, q, k, v, state, train, mask):
        """Attention-core dispatch on [N, H, T, dh] q/k/v, shared with
        TransformerBlockImpl: streaming continuation, ring/Ulysses
        sequence parallelism, pallas flash, or dense — plus the serving
        KV-cache prefill."""
        if state is not None:
            # Streaming continuation (rnn_time_step): attend over the
            # carried KV cache + this chunk — the attention analogue of
            # the LSTM carried (h, c) (reference BaseRecurrentLayer
            # stateMap). Always causal (the future is unwritten when
            # decoding). An optional right-padded chunk mask lets a
            # bucket-padded suffix chunk resume a partially-filled
            # cache (serving chunked prefill); unmasked streaming (the
            # reference contract, and the decode hot path) is the
            # mask=None fast path.
            return cls._stream_attend(lc, q, k, v, state, mask)
        if lc.ring_axis:
            from deeplearning4j_tpu.parallel.sequence_parallel import (
                ring_attention,
                ulysses_attention,
            )

            if lc.sp_mode == "ulysses":
                if lc.ring_block_size:
                    raise ValueError(
                        "ring_block_size bounds the RING schedule's "
                        "score memory; ulysses materializes the "
                        "full [T, T] scores of its local heads — "
                        "unset ring_block_size or use "
                        "sp_mode='ring'")
                o = ulysses_attention(
                    q, k, v, lc.ring_axis, causal=lc.causal,
                    key_mask=mask,
                )
            elif lc.sp_mode == "ring":
                o = ring_attention(
                    q, k, v, lc.ring_axis, causal=lc.causal,
                    key_mask=mask, block_size=lc.ring_block_size,
                )
            else:
                raise ValueError(
                    f"sp_mode {lc.sp_mode!r}: expected 'ring' or "
                    "'ulysses'")
            return o, None
        if _should_use_flash(lc.use_flash, q, mask):
            o = _flash_attention(q, k, v, lc.causal)
        else:
            o = _dense_attention(q, k, v, lc.causal, mask)
        new_state = None
        if not train:
            # Prefill: expose the (right-aligned, fixed-size) KV
            # cache so a later rnn_time_step call continues this
            # context. Under output()/evaluate the returned rnn
            # state is discarded, so XLA dead-code-eliminates the
            # cache build; training (train=True) never creates it —
            # tBPTT windows stay independent, as without a cache.
            # (Built for non-causal layers too so that a SECOND
            # streaming call reaches _stream_attend's explicit
            # cannot-stream error instead of silently attending
            # chunk-locally.)
            new_state = cls._prefill_cache(lc, k, v, mask)
        return o, new_state

    # -- rnn_time_step streaming (fixed-size sliding KV cache) ---------
    @staticmethod
    def _right_align(shift, *arrays):
        """Right-rotate each batch row of ``[N, H, T, dh]`` arrays by
        its per-row ``shift`` along the time axis — the
        pad-out-of-view trick shared by bucket-padded prefill and
        masked chunk continuation: after rotation a ``[:, :, -tm:, :]``
        window slice keeps real tokens contiguous at the right edge,
        and the wrapped pad lands in the left region the per-row
        ``filled`` mask invalidates (it must never receive attention
        weight — both call sites rely on exactly this invariant)."""
        roll = jax.vmap(lambda a, s: jnp.roll(a, s, axis=1))
        return tuple(roll(a, shift) for a in arrays)

    @classmethod
    def _prefill_cache(cls, lc, k, v, mask=None):
        """Right-align the last ``stream_max_t`` K/V positions into the
        fixed-size cache (zeros pad the left when underfilled).

        ``filled`` is a PER-ROW int32 vector [N]: each batch row is an
        independent streaming slot with its own valid-length, so ragged
        requests can share one batched cache (serving/engine.py slots).
        With ``mask`` (right-padded prompts, [N, T] 1/0 over the valid
        prefix) each row's real K/V are rotated to the right edge of
        the window and ``filled`` counts only real tokens — the padded
        tail wraps into the left region that the per-row window mask
        already invalidates, so a bucket-padded prefill streams
        identically to an unpadded prefill of the same prompt (the
        masked left region may hold wrapped pad instead of zeros; it
        never receives attention weight). Works for any T, including
        T > stream_max_t (ordinary masked inference on long padded
        batches): the window then keeps each row's last
        ``min(length, stream_max_t)`` valid positions."""
        tm = lc.stream_max_t
        n, h, t, dh = k.shape
        if mask is None:
            filled = jnp.full((n,), min(t, tm), jnp.int32)
        else:
            # rotate each row's pad out of view BEFORE windowing:
            # valid K/V land contiguous at the right edge for any T
            # (window-sized or longer) — see _right_align
            lengths = jnp.sum(mask.astype(jnp.int32), axis=1)  # [N]
            k, v = cls._right_align(t - lengths, k, v)
            filled = jnp.minimum(lengths, tm)
        zk = jnp.zeros((n, h, tm, dh), k.dtype)
        ck = jnp.concatenate([zk, k], axis=2)[:, :, -tm:, :]
        cv = jnp.concatenate([zk, v], axis=2)[:, :, -tm:, :]
        return {"k": ck, "v": cv, "filled": filled}

    @classmethod
    def _paged_attend(cls, lc, q, k, v, cache, mask=None):
        """Gather-by-block-table attention over the shared KV block
        pool (the serving engine's ``paged_kv=True`` layout — vLLM's
        PagedAttention memory model on the XLA level: the pallas
        double-buffered kernel in boom_attention_tricks.md is the TPU
        hot-path successor, this program is its semantics).

        The cache dict is NOT a per-slot row but a view into one pool
        shared by every slot and the radix prefix trie:

        - ``pk``/``pv`` [n_blocks, block_tokens, H, dh] — the device
          pool; a block holds ``block_tokens`` consecutive tokens of
          exactly one logical sequence (possibly shared by several
          slots/trie entries via host-side refcounts).
        - ``table`` [B, S] int32 — each row's ring-addressed block
          table: logical block ``g`` (covering absolute token
          positions ``[g*bt, (g+1)*bt)``) lives at ring slot
          ``g % S``; -1 = unmapped.
        - ``base`` [B, S] int32 — ``g*bt`` for the block each ring
          slot currently holds (validates ring-slot occupancy: a slot
          whose base disagrees with the probed logical block is stale
          and masked).
        - ``floor`` [B] int32 — minimum valid absolute position (a
          prefix-trie splice of a window-slid entry exposes only the
          positions the entry actually stored).
        - ``filled`` [B] int32 — absolute length = the next write
          position (NOT capped at the window, unlike the dense cache).

        Per call: the chunk's K/V scatter into the pool at their
        absolute positions THROUGH the table (one flat scatter; pad
        positions and unmapped rows drop), then every query gathers
        the ``<= window + t`` tokens its sliding window can reach and
        attends under exactly the dense path's validity rule — causal,
        last-``stream_max_t`` window, per-row floor. Writes precede
        the gather inside one program, so position ``p``'s content is
        committed before any query with ``qpos >= p`` reads it; stale
        garbage past ``filled`` is causally masked and overwritten by
        the next append (the rewind contract of
        ``nn.streaming.drop_newest_tokens``). The host guarantees
        every block written here has refcount 1 (copy-on-write happens
        before dispatch), so shared prefix blocks are never mutated."""
        tm = lc.stream_max_t
        b, h, t, dh = q.shape
        if not lc.causal:
            raise ValueError(
                "non-causal (bidirectional) attention cannot stream: "
                "rnn_time_step continuation would need future tokens; "
                "use causal=True or run output() on full sequences")
        pk, pv = cache["pk"], cache["pv"]
        table, base = cache["table"], cache["base"]
        floor, filled = cache["floor"], cache["filled"]
        nb, bt = pk.shape[0], pk.shape[1]
        n_tok = nb * bt
        s_ring = table.shape[1]
        pkf = pk.reshape(n_tok, h, dh)
        pvf = pv.reshape(n_tok, h, dh)
        if mask is None:
            lengths = jnp.full((b,), t, jnp.int32)
        else:
            lengths = jnp.sum(mask.astype(jnp.int32), axis=1)
        # -- scatter the chunk's K/V to their absolute positions ------
        pos = filled[:, None] + jnp.arange(t)[None, :]        # [B, t]
        blk = jnp.take_along_axis(table, (pos // bt) % s_ring, axis=1)
        writable = (jnp.arange(t)[None, :] < lengths[:, None]) & (
            blk >= 0)
        widx = jnp.where(writable, blk * bt + pos % bt, n_tok)
        kt = jnp.swapaxes(k, 1, 2).reshape(b * t, h, dh)
        vt = jnp.swapaxes(v, 1, 2).reshape(b * t, h, dh)
        pkf = pkf.at[widx.reshape(-1)].set(kt.astype(pkf.dtype),
                                           mode="drop")
        pvf = pvf.at[widx.reshape(-1)].set(vt.astype(pvf.dtype),
                                           mode="drop")
        # -- gather each row's reachable window -----------------------
        # consecutive logical blocks from the earliest any query needs
        # (bounded per-executable: ~window + chunk tokens, NOT the
        # whole ring — the decode step reads ~window keys like dense)
        ntab = min(s_ring, (tm + t - 2) // bt + 2)
        lo = jnp.maximum(floor, jnp.maximum(filled - tm + 1, 0))
        lo_blk = lo // bt
        g = lo_blk[:, None] + jnp.arange(ntab)[None, :]    # [B, ntab]
        tb = jnp.take_along_axis(table, g % s_ring, axis=1)
        bb = jnp.take_along_axis(base, g % s_ring, axis=1)
        bval = (tb >= 0) & (bb == g * bt)          # ring slot holds g
        toggle = getattr(lc, "use_flash_paged", None)
        if _should_use_flash_paged(toggle, bt, dh):
            # fused pallas kernel (ISSUE 12): each (row, head) walks
            # its block list INSIDE the kernel — no [B, ntab*bt, ...]
            # gather ever materializes in HBM. Same validity rule,
            # same value-level NaN masking, online softmax; parity vs
            # the gather program is argmax-level (different float
            # reduction shape — the PR 6 paged-parity convention).
            o = _paged_flash_attention(
                q, pkf.reshape(nb, bt, h, dh),
                pvf.reshape(nb, bt, h, dh),
                jnp.where(bval, tb, 0).astype(jnp.int32),
                bval.astype(jnp.int32), lo_blk.astype(jnp.int32),
                floor.astype(jnp.int32), filled.astype(jnp.int32),
                lengths.astype(jnp.int32), tm=tm,
                interpret=(toggle == "interpret"))
            return o, {"pk": pkf.reshape(nb, bt, h, dh),
                       "pv": pvf.reshape(nb, bt, h, dh),
                       "table": table, "base": base, "floor": floor,
                       "filled": filled + lengths}
        off = jnp.arange(bt)
        gidx = (jnp.where(bval, tb, 0)[:, :, None] * bt
                + off[None, None, :]).reshape(b, ntab * bt)
        kpos = (g[:, :, None] * bt
                + off[None, None, :]).reshape(b, ntab * bt)
        kval = jnp.repeat(bval, bt, axis=1)        # [B, ntab*bt]
        ek = jnp.swapaxes(pkf[gidx], 1, 2)         # [B, H, K, dh]
        ev = jnp.swapaxes(pvf[gidx], 1, 2)
        # gather lanes outside each row's WRITTEN span carry foreign
        # data: invalid-block lanes read a placeholder block, and a
        # freshly (re)allocated tail block holds whatever its previous
        # owner left there — possibly NaN under fault injection, since
        # eviction releases blocks by reference without scrubbing. A
        # NaN value survives a zero softmax weight (0 * NaN = NaN), so
        # values must be zeroed at the VALUE level over the full
        # validity rule — block mapped AND position inside
        # [floor, filled + written) — or a recycled dirty block
        # silently corrupts its next owner through masked lanes
        # (caught by the chaos gate and the paranoid-off regression).
        # The pallas kernel above enforces the SAME rule on its DMA'd
        # V blocks (`vlive` in _paged_flash_attention) — the two paths
        # share the contract, and the kernel parity tests poison a
        # freed block to prove it holds there too
        vlive = (kval
                 & (kpos < (filled + lengths)[:, None])
                 & (kpos >= floor[:, None]))
        ev = jnp.where(vlive[:, None, :, None], ev, 0)
        qpos = filled[:, None] + jnp.arange(t)[None, :]
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, ek) / jnp.sqrt(
            jnp.asarray(dh, q.dtype))
        ok = (kval[:, None, :]
              & (kpos[:, None, :] <= qpos[:, :, None])      # causal
              & (kpos[:, None, :] > qpos[:, :, None] - tm)  # window
              & (kpos[:, None, :] >= floor[:, None, None]))
        neg = jnp.asarray(-1e30, q.dtype)
        scores = jnp.where(ok[:, None], scores, neg)
        w = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", w, ev)
        return o, {"pk": pkf.reshape(nb, bt, h, dh),
                   "pv": pvf.reshape(nb, bt, h, dh),
                   "table": table, "base": base, "floor": floor,
                   "filled": filled + lengths}

    @classmethod
    def _stream_attend(cls, lc, q, k, v, cache, mask=None):
        """Dense attention of the current chunk's queries over
        cache + chunk. The cache stays ``stream_max_t`` long (static
        shapes — one compiled decode step regardless of how much
        context has streamed); the oldest tokens slide out when the
        window is exceeded.

        ``mask`` (``[N, T]`` 1/0, right-padded) marks the chunk's valid
        prefix per row: this is the resume-from-a-partially-filled-cache
        path, shared by TWO serving callers — chunked prefill (a
        pow2/fixed-width padded suffix chunk continues a prefix-cache
        hit) and the speculative verify attend (every slot's
        [current token | draft] chunk scores in one batched pass, each
        row masked to its own draft length — B rows at B different
        lengths AND different ``filled`` levels share one executable).
        Pad keys never receive weight, pad positions never enter the
        cache (the same roll-the-pad-out-of-view trick as
        ``_prefill_cache``), and ``filled`` advances by each row's true
        chunk length — so a padded chunked continuation streams
        identically to an unpadded one-shot prefill of the same
        tokens, and output position ``i`` of a verify chunk holds
        exactly the logits sequential decode would have produced after
        its first ``i`` chunk tokens (the property speculative
        acceptance rests on — serving/engine.py rewinds rejected
        tails afterwards via ``nn.streaming.drop_newest_tokens``).
        ``mask=None`` (the decode hot path) keeps the original,
        roll-free program."""
        if isinstance(cache, dict) and "pk" in cache:
            # paged block-pool layout (serving paged_kv engines): same
            # streaming contract, storage indirected through per-row
            # block tables — the dense row path below stays untouched
            # for paged=False
            return cls._paged_attend(lc, q, k, v, cache, mask)
        tm = lc.stream_max_t
        t = q.shape[2]
        if not lc.causal:
            raise ValueError(
                "non-causal (bidirectional) attention cannot stream: "
                "rnn_time_step continuation would need future tokens; "
                "use causal=True or run output() on full sequences")
        if t > tm:
            raise ValueError(
                f"rnn_time_step continuation chunk of {t} steps exceeds "
                f"stream_max_t={tm}: raise stream_max_t or stream "
                "smaller chunks")
        # Attend over the FULL [cache | chunk] extension (length tm+t)
        # and slice only the returned cache: slicing BEFORE attending
        # would drop cached keys still inside the sliding window of the
        # chunk's EARLY queries (chunked streaming would diverge from
        # one-token-at-a-time streaming once the window saturates).
        ek = jnp.concatenate([cache["k"], k], axis=2)   # [N,H,tm+t,dh]
        ev = jnp.concatenate([cache["v"], v], axis=2)
        prev = cache["filled"]                    # [N] per-slot lengths
        if mask is None:
            lengths = jnp.full(q.shape[:1], t, jnp.int32)
        else:
            lengths = jnp.sum(mask.astype(jnp.int32), axis=1)  # [N]
        filled = jnp.minimum(prev + lengths, tm)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, ek) / jnp.sqrt(
            jnp.asarray(q.shape[-1], q.dtype)
        )
        j = jnp.arange(tm + t)                    # extension positions
        i = jnp.arange(t)                         # query i at ext tm+i
        ok = (
            (j[None, :] <= tm + i[:, None])       # causal
            & (j[None, :] >= i[:, None] + 1)      # its last-tm window
        )                                         # [t, tm+t]
        # per-slot validity: cache zeros (or an idle/evicted slot's
        # stale rows — filled == 0 invalidates the whole window) never
        # receive weight, so slots at different fill levels share one
        # batched step without contaminating each other
        ok = ok[None] & (j[None, None, :] >= tm - prev[:, None, None])
        if mask is not None:
            # chunk pad (positions past each row's true chunk length)
            # is invalid too — a padded chunk attends exactly like its
            # unpadded counterpart
            ok = ok & ((j[None, None, :] < tm)
                       | (j[None, None, :] - tm
                          < lengths[:, None, None]))
        neg = jnp.asarray(-1e30, q.dtype)
        scores = jnp.where(ok[:, None], scores, neg)
        w = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", w, ev)
        if mask is None:
            ck, cv = ek[:, :, -tm:, :], ev[:, :, -tm:, :]
        else:
            # rotate each row's chunk pad out of view before windowing
            # (see _right_align — shared with _prefill_cache)
            ek, ev = cls._right_align(t - lengths, ek, ev)
            ck, cv = ek[:, :, -tm:, :], ev[:, :, -tm:, :]
        return o, {"k": ck, "v": cv, "filled": filled}


@register_bean("TransformerBlock")
@dataclasses.dataclass
class TransformerBlock(BaseRecurrentLayer):
    """Conf bean: a full pre-LN transformer block — LayerNorm →
    multi-head self-attention → residual, then LayerNorm → FFN
    (``ffn_mult``× inner width, gelu) → residual.

    This is the convergence-grade building unit the bare
    ``MultiHeadSelfAttention`` stack lacks: without the residual path
    and pre-LN, width ≥ 1024 stacks diverge at any useful lr (measured,
    BENCHMARKS.md flagship section), which is the standard
    transformer-training result. NEW capability vs the 2015 reference
    (predates attention; SURVEY.md §5.7 mandates first-class
    long-context), layered on the framework's [N, C, T] recurrent
    layout so it composes with RnnOutputLayer and the sp/pp/tp
    parallel trainers.

    When ``n_in != n_out`` the block first applies a learned input
    projection (no residual across it — the standard embed step);
    homogeneous interior blocks (n_in == n_out) are pure residual and
    therefore stackable under the pipeline trainer's homogeneous-stage
    mode."""

    n_heads: int = 4
    causal: bool = True
    ffn_mult: int = 4
    ffn_activation: str = "gelu"
    ring_axis: Optional[str] = None
    ring_block_size: Optional[int] = None
    sp_mode: str = "ring"
    use_flash: Optional[bool] = None
    use_flash_paged: Optional[object] = None
    stream_max_t: int = 512


def _layer_norm(x, g, b, eps=1e-5):
    from deeplearning4j_tpu.nn.layers.normalization import layer_norm

    return layer_norm(x, g, b, axis=-1, eps=eps)


class TransformerBlockImpl(LayerImplBase):
    @classmethod
    def init(cls, key, conf, dtype=jnp.float32) -> dict:
        lc = conf.layer
        d_in, d = lc.n_in, lc.n_out
        dff = lc.ffn_mult * d
        kq, kk, kv, ko, k1, k2, ki = jax.random.split(key, 7)
        scheme = conf.resolved("weight_init")
        dist = conf.resolved("dist")
        p = {
            "ln1_g": jnp.ones((d,), dtype),
            "ln1_b": jnp.zeros((d,), dtype),
            "Wq": init_weights(kq, (d, d), scheme, dist, dtype),
            "Wk": init_weights(kk, (d, d), scheme, dist, dtype),
            "Wv": init_weights(kv, (d, d), scheme, dist, dtype),
            "Wo": init_weights(ko, (d, d), scheme, dist, dtype),
            "bo": jnp.zeros((d,), dtype),
            "ln2_g": jnp.ones((d,), dtype),
            "ln2_b": jnp.zeros((d,), dtype),
            "W1": init_weights(k1, (d, dff), scheme, dist, dtype),
            "b1": jnp.zeros((dff,), dtype),
            "W2": init_weights(k2, (dff, d), scheme, dist, dtype),
            "b2": jnp.zeros((d,), dtype),
        }
        if d_in != d:
            p["Wi"] = init_weights(ki, (d_in, d), scheme, dist, dtype)
        return p

    @classmethod
    def apply(cls, conf, params, x, state=None, train=False, rng=None,
              mask=None):
        from deeplearning4j_tpu.ops.activations import activation

        lc = conf.layer
        h, d = lc.n_heads, lc.n_out
        if d % h:
            raise ValueError(f"n_out {d} not divisible by n_heads {h}")
        dh = d // h
        tp = _tp_scope()
        if tp is not None:
            h = _tp_local_heads(h, tp)
        x = cls.maybe_dropout(conf, x, train, rng)
        xt = jnp.transpose(x, (0, 2, 1))  # [N, T, C]
        if "Wi" in params:
            xt = xt @ params["Wi"]

        hn = _layer_norm(xt, params["ln1_g"], params["ln1_b"])

        def split_heads(m):
            y = hn @ m  # [N, T, D] (local D/TP under tp head sharding)
            return jnp.transpose(
                y.reshape(y.shape[0], y.shape[1], h, dh), (0, 2, 1, 3)
            )  # [N, H, T, dh]

        q = split_heads(params["Wq"])
        k = split_heads(params["Wk"])
        v = split_heads(params["Wv"])
        o, state = AttentionImpl._attend_core(
            lc, q, k, v, state, train, mask)
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(
            o.shape[0], o.shape[2], h * dh)  # [N, T, D] (local heads)
        if tp is not None:
            # row-parallel Wo: one all-reduce per block completes the
            # partial sum; LN params, biases, and the (replicated) FFN
            # see the full-width activation — the Megatron block with
            # only the attention heads sharded (the KV cache is the
            # memory that matters in serving; serving/tp.py). f32
            # accumulate + f32 psum + one rounding, as in
            # AttentionImpl.apply — per-shard bf16 rounding before the
            # sum flips argmaxes vs single-chip
            attn = jax.lax.dot_general(
                o, params["Wo"], (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            attn = jax.lax.psum(attn, tp[0]).astype(o.dtype)
        else:
            attn = o @ params["Wo"]
        xt = xt + (attn + params["bo"])

        h2 = _layer_norm(xt, params["ln2_g"], params["ln2_b"])
        ffn = activation(lc.ffn_activation)(
            h2 @ params["W1"] + params["b1"])
        xt = xt + (ffn @ params["W2"] + params["b2"])

        out = jnp.transpose(xt, (0, 2, 1))  # [N, D, T]
        if mask is not None:
            out = out * mask[:, None, :]
        return out, state


# Beans carrying the shared attention-core options (n_heads, causal,
# ring_axis/sp_mode, use_flash, stream_max_t). Parallel trainers
# dispatch on this tuple, not the concrete classes, so both stay
# covered by tp head-sharding, sp ring validation, etc.
ATTENTION_BEANS = (MultiHeadSelfAttention, TransformerBlock)


def guard_streamable(named_layer_beans) -> None:
    """Raise if any layer bean carries ring_axis: rnn_time_step streams
    on a single device, and sequence-parallel attention cannot (shared
    by MultiLayerNetwork.rnn_time_step and
    ComputationGraph.rnn_time_step)."""
    for name, lc in named_layer_beans:
        if getattr(lc, "ring_axis", None):
            raise ValueError(
                f"rnn_time_step streams on a single device; layer "
                f"{name} is configured with ring_axis="
                f"{lc.ring_axis!r} (sequence parallelism) and cannot "
                "stream — rebuild the conf with ring_axis=None for "
                "serving")


def _should_use_flash(use_flash, q, mask) -> bool:
    """Training/prefill flash dispatch. The PAGED decode analogue is
    :func:`_should_use_flash_paged` below — same toggle philosophy
    (None = auto, False = XLA always, True = force the kernel), but
    auto mode gates on BACKEND + tile health rather than sequence
    length: a decode chunk is a handful of queries over ~window keys,
    so the kernel's win is skipping the [B, ntab*bt, H, dh] gather
    materialization (HBM bandwidth), not O(T²) score memory."""
    if use_flash is False:
        return False
    t, dh = q.shape[2], q.shape[3]
    kernel_ok = (jax.default_backend() == "tpu" and mask is None
                 and t >= 256 and t % 128 == 0
                 and (dh <= 128 or dh % 128 == 0))
    if use_flash and not kernel_ok:
        raise ValueError(
            "use_flash=True requires the TPU backend, no mask, a "
            "sequence length >= 256 divisible by 128, and head dim "
            "<= 128 or divisible by 128")
    if use_flash is None:
        # Auto mode: flash is the LONG-context enabler — it removes the
        # O(T²) score materialization that stops dense attention at
        # ~16k+ tokens. With the tuned 1024-element block sizes (the
        # kernel defaults were pathological — see _flash_attention) it
        # reaches speed parity by T~512-1024 and wins ~2x at T=4096;
        # keep a conservative 2048 threshold where the win is clear
        # beyond dispatch noise and the memory savings start to matter.
        # The t % 512 == 0 condition guarantees a healthy block size:
        # a T like 2176 (=128*17) would degrade the kernel to
        # 128-blocks — the pathological regime — where dense is faster.
        # Above 8192 that tradeoff inverts: even degraded-block flash
        # beats dense's O(T²) score materialization (4.3 GB at 8k,
        # OOM by 32k), so memory safety overrides block health there.
        return kernel_ok and t >= 2048 and (t % 512 == 0 or t >= 8192)
    return bool(use_flash)


def _flash_attention(q, k, v, causal):
    """Pallas TPU flash-attention kernel: O(T) memory instead of the
    dense O(T²) score matrix (pallas_guide.md; long-context fast path —
    SURVEY.md §5.7).

    Block sizes are pinned to the largest of (1024, 512, 256, 128)
    dividing T: the kernel's defaults measured PATHOLOGICAL at long
    context on v5e — T=16384 forward 584 ms default vs 47 ms at
    1024-blocks (12x), fwd+bwd 177 ms vs 48 ms (3.7x); 2048-blocks
    fails to compile (VMEM). Auto mode engages only where T yields
    >= 512 blocks BELOW 8192; at T >= 8192 it engages unconditionally
    (degraded 128/256-blocks included — dense's O(T²) scores OOM there,
    so a slow flash beats no flash). A forced use_flash=True accepts
    whatever divisor T offers. Measured in BENCHMARKS.md."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    t = q.shape[2]
    # largest block <= 1024 that divides T (T % 128 == 0 guaranteed by
    # _should_use_flash, so 128 always divides)
    n = next(b for b in (1024, 512, 256, 128) if t % b == 0)
    bs = BlockSizes(
        block_q=n, block_k_major=n, block_k=n, block_b=1,
        block_q_major_dkv=n, block_k_major_dkv=n,
        block_k_dkv=n, block_q_dkv=n,
        block_k_major_dq=n, block_k_dq=n, block_q_dq=n,
    )
    return flash_attention(
        q, k, v, causal=causal, sm_scale=q.shape[-1] ** -0.5,
        block_sizes=bs)


def _should_use_flash_paged(toggle, block_tokens: int,
                            head_dim: int) -> bool:
    """Dispatch rule for the pallas paged-attention decode kernel
    (:func:`_paged_flash_attention`) vs the XLA gather-by-block-table
    program in :meth:`AttentionImpl._paged_attend`:

    - ``None`` (auto): the kernel on the TPU backend when the block
      shape tiles healthily — ``block_tokens`` a multiple of 8
      (sublane) and ``head_dim`` a multiple of 128 (lane); toy/test
      geometries below the native tile stay on the XLA gather, which
      fuses fine at those sizes. Off-TPU always falls back to the
      gather program (the kernel's DMA scheduling is TPU-specific;
      interpret mode exists for parity testing, not serving).
    - ``True``: force the kernel — raises off-TPU or on unhealthy
      tiles instead of silently degrading.
    - ``False``: the XLA gather program always.
    - ``"interpret"``: the kernel through the pallas interpreter on
      any backend — the CPU bit-parity testing hook (tier-1 gates the
      kernel's semantics against the gather program with it).

    Both paths enforce the SAME value-level masking rule: gathered /
    DMA'd V lanes outside ``[floor, filled + written)`` are zeroed at
    the VALUE level, not just score-masked, because a recycled dirty
    block's NaN survives a zero softmax weight (0 x NaN = NaN — the
    PR 6 poisoned-neighbour fix; the kernel parity tests poison a
    freed block to prove the kernel preserves it)."""
    if toggle is False or (toggle is None
                           and jax.default_backend() != "tpu"):
        return False
    if toggle == "interpret":
        return True
    tiles_ok = (block_tokens % 8 == 0 and head_dim % 128 == 0)
    if toggle is None:
        return tiles_ok
    if jax.default_backend() != "tpu" or not tiles_ok:
        raise ValueError(
            "use_flash_paged=True requires the TPU backend, "
            "block_tokens % 8 == 0 and head dim % 128 == 0 "
            f"(got block_tokens={block_tokens}, head_dim={head_dim} "
            f"on {jax.default_backend()!r}); use 'interpret' for "
            "off-TPU parity testing or None for auto fallback")
    return True


def _paged_flash_attention(q, pk, pv, bid, bval, lo_blk, floor,
                           filled, lengths, *, tm: int,
                           interpret: bool = False):
    """Fused pallas paged-attention kernel (ISSUE 12; pallas_guide.md,
    boom_attention_tricks.md §8-12 — the in-repo flash kernel's decode
    successor). One grid step = one (row, head, logical-block) visit:

    - the BLOCK TABLE rides as scalar-prefetch operands, and the K/V
      BlockSpec ``index_map`` reads it to map grid step ``(b, h, j)``
      to pool block ``bid[b, j]`` — pallas's pipeline then DMAs each
      (non-contiguous) block HBM→VMEM ahead of compute, exactly the
      double-buffered page walk of the reference paged kernel, with
      NO ``[B, ntab*bt, H, dh]`` gather ever materialized.
    - online softmax over the block walk (running max / sum / output
      accumulator in VMEM scratch, rescaled per block) under the SAME
      validity rule as the XLA gather program: block mapped, causal,
      last-``tm`` window, per-row floor.
    - value-level masking: V lanes outside ``[floor, filled + len)``
      are zeroed BEFORE the weighted sum — a zero softmax weight does
      not kill a NaN (0 x NaN = NaN), so a recycled dirty block would
      otherwise poison its next owner through masked lanes (the PR 6
      fix, preserved here; `_should_use_flash_paged` documents the
      shared contract). Fully-masked blocks contribute exactly zero
      mass (``p`` is zeroed where invalid, so ``l`` never counts
      them — rows with NO valid key anywhere, idle slots, emit 0 like
      the gather path's uniform-softmax-over-zeroed-values).

    Shapes: q [B, H, t, dh]; pk/pv [nb, bt, H, dh] (post-scatter);
    bid/bval [B, ntab] int32 (pool block per logical block, validity);
    lo_blk/floor/filled/lengths [B] int32. Returns o [B, H, t, dh].
    Parity vs the gather program is argmax-level (one float reduction
    runs blockwise, the other over the flat gather — the PR 6
    paged-parity convention), gated per tier-1 workload in
    tests/test_serving_tp.py via interpret mode."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b_sz, h_sz, t, dh = q.shape
    nb, bt = pk.shape[0], pk.shape[1]
    ntab = bid.shape[1]
    scale = dh ** -0.5

    def kernel(bid_ref, bval_ref, lo_ref, floor_ref, filled_ref,
               len_ref, q_ref, pk_ref, pv_ref, o_ref, m_ref, l_ref,
               acc_ref):
        b = pl.program_id(0)
        j = pl.program_id(2)
        nj = pl.num_programs(2)

        @pl.when(j == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, -1e30)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        qb = q_ref[0, 0].astype(jnp.float32)          # [t, dh]
        kb = pk_ref[0, :, 0, :].astype(jnp.float32)   # [bt, dh]
        vb = pv_ref[0, :, 0, :].astype(jnp.float32)
        kpos = ((lo_ref[b] + j) * bt
                + jax.lax.broadcasted_iota(jnp.int32, (t, bt), 1))
        qpos = (filled_ref[b]
                + jax.lax.broadcasted_iota(jnp.int32, (t, bt), 0))
        live = bval_ref[b, j] > 0
        ok = (live & (kpos <= qpos) & (kpos > qpos - tm)
              & (kpos >= floor_ref[b]))
        # value-level masking (see docstring): one [1, bt] row — the
        # written-span rule is q-position-independent
        vlive = (live
                 & (kpos[:1] < filled_ref[b] + len_ref[b])
                 & (kpos[:1] >= floor_ref[b]))
        vb = jnp.where(vlive.reshape(bt, 1), vb, 0.0)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = jnp.where(ok, s, -1e30)
        m_prev = jnp.max(m_ref[...], axis=1)          # [t]
        l_prev = jnp.max(l_ref[...], axis=1)
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.where(ok, jnp.exp(s - m_next[:, None]), 0.0)
        l_next = alpha * l_prev + jnp.sum(p, axis=1)
        acc_ref[...] = (alpha[:, None] * acc_ref[...]
                        + jax.lax.dot_general(
                            p, vb, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_ref[...] = jnp.broadcast_to(m_next[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_next[:, None], l_ref.shape)

        @pl.when(j == nj - 1)
        def _finalize():
            l = jnp.max(l_ref[...], axis=1)
            o_ref[0, 0] = (
                acc_ref[...] / jnp.where(l == 0, 1.0, l)[:, None]
            ).astype(o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(b_sz, h_sz, ntab),
        in_specs=[
            pl.BlockSpec((1, 1, t, dh),
                         lambda b, h, j, *refs: (b, h, 0, 0)),
            # the page walk: scalar-prefetched table drives the DMA
            pl.BlockSpec((1, bt, 1, dh),
                         lambda b, h, j, bid, *refs:
                         (bid[b, j], 0, h, 0)),
            pl.BlockSpec((1, bt, 1, dh),
                         lambda b, h, j, bid, *refs:
                         (bid[b, j], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, t, dh),
                               lambda b, h, j, *refs: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((t, 128), jnp.float32),   # running max
            pltpu.VMEM((t, 128), jnp.float32),   # running sum
            pltpu.VMEM((t, dh), jnp.float32),    # output accumulator
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b_sz, h_sz, t, dh), q.dtype),
        interpret=interpret,
    )(bid, bval, lo_blk, floor, filled, lengths, q, pk, pv)


def _dense_attention(q, k, v, causal, mask):
    t = q.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
        jnp.asarray(q.shape[-1], q.dtype)
    )
    neg = jnp.asarray(-1e30, q.dtype)
    if causal:
        cm = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(cm, scores, neg)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :] > 0, scores, neg)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)
