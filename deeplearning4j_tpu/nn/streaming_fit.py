"""Shared window-driving loop for the host-fed ``fit_stream`` paths
(MultiLayerNetwork + ComputationGraph — one copy so transport tweaks
cannot silently diverge between them).

The loop accumulates batches from a DataSetIterator into windows of
``scan_steps``; a full uniform window flushes fused (one fit_scan
dispatch), while a ragged tail — iterator exhaustion mid-window or a
batch whose shape differs from the window's first — flushes per-batch.
"""

from __future__ import annotations

import time
from typing import Callable


def drive_stream_windows(iterator, scan_steps: int,
                         flush: Callable, batch_shape: Callable,
                         telemetry=None) -> None:
    """``flush(window, fused)`` trains a list of batches;
    ``batch_shape(ds)`` returns a comparable shape signature (host-side
    only — no device transfers). ``telemetry`` (a TrainTelemetry)
    accumulates the host wait on ``iterator.next()`` as the data-wait
    phase — with an async prefetcher keeping up, this reads near zero;
    a disk-bound run shows exactly where its step time went."""
    window, first_shape = [], None
    while True:
        t0 = time.perf_counter()
        ds = iterator.next()
        if telemetry is not None:
            telemetry.add_data_wait(time.perf_counter() - t0)
        if ds is None:
            if window:  # exhausted mid-window: always ragged here
                flush(window, False)
            break
        shape = batch_shape(ds)
        if window and shape != first_shape:
            # smaller tail batch can't stack with the window
            flush(window, False)
            window = []
        if not window:
            first_shape = shape
        window.append(ds)
        if len(window) == scan_steps:
            flush(window, True)
            window = []
