"""Slot-aware streaming-state utilities.

Shared by the network-level rnn-state APIs
(``MultiLayerNetwork.rnn_clear_previous_state`` /
``ComputationGraph.rnn_clear_previous_state``) and the serving decode
engine (``serving/engine.py``).

CONTRACT — streaming state is batch-major: every leaf of an rnn-state
pytree (attention ``k``/``v``/``filled``, GravesLSTM/GRU carried
``(h, c)``) has the batch dimension on axis 0, one row per batch
element. The serving engine treats those rows as KV-cache *slots*;
``clear_state_rows`` relies on the contract to reset individual slots
without touching their neighbours. A zeroed attention row is exactly
the empty-cache state (``filled == 0`` masks every cached position in
``AttentionImpl._stream_attend``), and zeroed LSTM/GRU rows equal the
initial carry, so a cleared slot streams as if freshly created.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp


def scan_length_bucket(n: int, minimum: int = 8) -> int:
    """Next power of two >= max(n, minimum) — the jit-cache key for
    length-dependent decode scans and prefills.

    Keying compiled executables on the raw length grows the jit cache
    unboundedly under varied request lengths (every distinct
    ``n_tokens`` used to cost a full XLA compile of the generate scan);
    bucketing bounds compilations at O(log max_len) while wasting at
    most 2x scan steps for ``n >= minimum`` (below it, up to
    ``minimum`` steps run — the floor trades those cheap frozen-carry
    steps for not compiling a separate tiny-scan executable per
    sub-``minimum`` length), and the actual length rides alongside as
    a traced operand so masking stays exact."""
    n = max(int(n), int(minimum))
    return 1 << (n - 1).bit_length()


def make_bucketed_generate(step: Callable, vocab: int, dtype,
                           bucket: int):
    """Build the jitted freeze-carry greedy decode scan shared by
    ``MultiLayerNetwork.generate`` and ``ComputationGraph.generate``.

    ``step(params, state, x, rnn) -> (out [B, V, T], new_rnn)`` is the
    network's streaming forward for one one-hot token. The returned
    jitted callable ``(params, state, rnn_state, tok0, n_rem) ->
    (toks [B, bucket], rnn)`` scans ``bucket`` steps with the true
    remaining length traced: steps at ``i >= n_rem`` freeze the carry,
    so one executable serves every ``n_tokens`` in the bucket and the
    rnn state still lands exactly at the post-generation position."""
    def gen_fn(params, state, rnn_state, tok0, n_rem):
        def body(carry, i):
            rnn, tok = carry
            x = jax.nn.one_hot(tok, vocab, dtype=dtype)[:, :, None]
            out, new_rnn = step(params, state, x, rnn)
            nxt = jnp.argmax(out[:, :, -1], axis=1).astype(jnp.int32)
            live = i < n_rem  # bucket-pad steps freeze the carry
            keep = functools.partial(jnp.where, live)
            return (jax.tree_util.tree_map(keep, new_rnn, rnn),
                    jnp.where(live, nxt, tok)), nxt

        (rnn, _), toks = jax.lax.scan(body, (rnn_state, tok0),
                                      jnp.arange(bucket))
        return jnp.swapaxes(toks, 0, 1), rnn

    return jax.jit(gen_fn)


def reset_streaming_state(rnn_state: Any, slots) -> Any:
    """Shared body of ``rnn_clear_previous_state`` for both
    ``MultiLayerNetwork`` and ``ComputationGraph``: ``slots=None``
    wipes everything (fresh empty container), ``slots=[...]`` zeroes
    only those batch rows via ``clear_state_rows``. Returns the new
    state container."""
    if slots is None:
        return {}
    if not rnn_state:
        raise ValueError(
            "no streaming state to clear slots from — run "
            "rnn_time_step first (or call without slots)")
    return clear_state_rows(rnn_state, slots)


def drop_newest_tokens(rnn_state: Any, drop) -> Any:
    """Rewind every attention KV-cache in a streaming-state pytree by
    ``drop`` tokens (0 or more, static or traced), returning the state
    as it was before the newest ``drop`` tokens streamed in. ``drop``
    may be a scalar (every batch row rewinds equally — the prefix-cache
    fetch path) or a per-row ``[N]`` vector (each row rewinds its own
    count — the speculative-verify path, where every slot keeps its
    accepted prefix and sheds its own rejected tail).

    Valid because K/V at a position are per-token projections of that
    token alone: removing the newest entries and re-right-aligning
    reproduces the shorter prefix's cache exactly. The roll wraps the
    dropped K/V into the left region that the decremented ``filled``
    already invalidates (the same mask argument as
    ``AttentionImpl._prefill_cache``), so they never receive attention
    weight. Used by the serving prefix cache (an exact-match prompt
    rewinds the cached state one token so the final prompt token can be
    re-streamed to produce first-token logits) and by the speculative
    verify step (rejected draft tails roll back before the bonus token
    commits). The caller guarantees ``drop <= filled`` per row AND that
    none of the dropped tokens pushed an older token out of the sliding
    window (a slid-out token cannot be recovered by rewind; the serving
    engine caps draft lengths at ``window - filled - 1`` for exactly
    this reason). Raises on non-attention state (an LSTM carry has no
    per-token axis to rewind)."""
    drop = jnp.asarray(drop)
    if drop.ndim > 1:
        raise ValueError(
            f"drop must be a scalar or per-row vector; got shape "
            f"{drop.shape}")
    if drop.ndim == 1:
        roll = jax.vmap(lambda a, s: jnp.roll(a, s, axis=1))
    else:
        def roll(a, s):
            return jnp.roll(a, s, axis=2)
    out = {}
    for name, st in (rnn_state or {}).items():
        if not (isinstance(st, dict) and "filled" in st):
            raise ValueError(
                f"streaming state for layer {name!r} carries no "
                "KV-cache 'filled' vector — only attention caches can "
                "be rewound by token")
        if "pk" in st:
            # paged block-pool cache (serving/block_pool.py): tokens
            # live at fixed absolute positions in pool blocks, so a
            # rewind is "pop blocks + mask tail" — the length counter
            # moves back and the stale tail is masked by the causal
            # position check in AttentionImpl._paged_attend (the next
            # append overwrites it in place). Block bookkeeping (the
            # pop) is host-side, in the engine's BlockTable.
            out[name] = dict(st, filled=st["filled"] - drop)
            continue
        out[name] = {
            "k": roll(st["k"], drop),
            "v": roll(st["v"], drop),
            "filled": st["filled"] - drop,
        }
    return out


def clear_state_rows(rnn_state: Any, slots: Iterable[int]) -> Any:
    """Zero the given batch rows of every leaf in a streaming-state
    pytree, leaving all other rows untouched.

    This is the per-slot counterpart of the whole-batch state wipe: the
    serving engine evicts a finished request by clearing its slot while
    the other slots keep decoding mid-flight. Slot indices are
    validated against the state's batch size; a scalar leaf violates
    the batch-major contract and raises."""
    idx = sorted({int(s) for s in slots})
    if not idx:
        return rnn_state
    leaves = jax.tree_util.tree_leaves(rnn_state)
    if not leaves:
        return rnn_state
    for leaf in leaves:
        if getattr(leaf, "ndim", 0) < 1:
            raise ValueError(
                "streaming-state leaf is scalar — per-slot clearing "
                "requires batch-major state (axis 0 = slot); re-run "
                "the prefill with this version's per-row cache")
    n = min(leaf.shape[0] for leaf in leaves)
    bad = [s for s in idx if s < 0 or s >= n]
    if bad:
        raise ValueError(
            f"slots {bad} out of range for streaming batch size {n}")
    iarr = jnp.asarray(idx, jnp.int32)

    def zero_rows(a):
        return a.at[iarr].set(jnp.zeros((), a.dtype))

    return jax.tree_util.tree_map(zero_rows, rnn_state)
