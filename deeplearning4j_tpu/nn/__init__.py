"""NN core: configuration, layers, parameters, updaters, networks.

Mirror of the reference's ``org.deeplearning4j.nn`` package
(reference deeplearning4j-core/src/main/java/org/deeplearning4j/nn,
SURVEY.md §2.2) redesigned around pure functions and pytrees.
"""
