"""Updaters: per-parameter learning rules with aggregatable state.

Mirror of reference nn/updater/*.java (BaseUpdater + Sgd, Adam, AdaDelta,
AdaGrad, Nesterovs, RmsProp, NoOp; MultiLayerUpdater composition; state
aggregation SPI nn/updater/aggregate/UpdaterAggregator.java used for
parameter averaging). Redesigned as pure gradient transforms over pytrees:
``init(params) -> state``; ``update(grads, state, lr, it) -> (updates,
state)`` where the caller applies ``params -= updates``. All jit-safe.
"""

from deeplearning4j_tpu.nn.updater.updaters import (
    LayerUpdater,
    aggregate_updater_states,
    make_layer_updater,
    normalize_gradients,
)
