"""Updater implementations + gradient normalization.

Semantics follow the reference updaters (nn/updater/{SgdUpdater,AdamUpdater,
AdaDeltaUpdater,AdaGradUpdater,NesterovsUpdater,RmsPropUpdater,NoOpUpdater}
.java) and gradient normalization modes (nn/conf/GradientNormalization.java,
applied in BaseUpdater before the rule). Unit tests pin closed-form
expected updates per rule like the reference's TestUpdaters.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.enums import GradientNormalization, Updater

Array = jax.Array
Pytree = dict


def _tree_zeros(params: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, params)


class LayerUpdater:
    """One layer's updater: rule + hyperparams resolved from its conf."""

    def __init__(self, rule: Updater, hp: dict):
        self.rule = rule
        self.hp = hp

    def init(self, params: Pytree) -> Pytree:
        if self.rule in (Updater.SGD, Updater.NONE):
            return {}
        if self.rule == Updater.NESTEROVS:
            return {"v": _tree_zeros(params)}
        if self.rule == Updater.ADAGRAD:
            return {"g2": _tree_zeros(params)}
        if self.rule == Updater.RMSPROP:
            return {"g2": _tree_zeros(params)}
        if self.rule == Updater.ADADELTA:
            return {"g2": _tree_zeros(params), "dx2": _tree_zeros(params)}
        if self.rule == Updater.ADAM:
            return {"m": _tree_zeros(params), "v": _tree_zeros(params)}
        raise ValueError(f"Unsupported updater {self.rule}")

    def update(self, grads: Pytree, state: Pytree, lr, iteration):
        """-> (updates, new_state); caller applies ``params -= updates``."""
        hp = self.hp
        if self.rule == Updater.SGD:
            return jax.tree.map(lambda g: lr * g, grads), state
        if self.rule == Updater.NONE:
            return grads, state
        if self.rule == Updater.NESTEROVS:
            mu = _resolve_schedule(
                hp["momentum"], hp.get("momentum_schedule"), iteration
            )
            v_prev = state["v"]
            v_new = jax.tree.map(lambda v, g: mu * v - lr * g, v_prev, grads)
            # params += -mu*v_prev + (1+mu)*v_new  (Sutskever NAG, as in the
            # reference NesterovsUpdater) => update = mu*v_prev - (1+mu)*v_new
            updates = jax.tree.map(
                lambda vp, vn: mu * vp - (1.0 + mu) * vn, v_prev, v_new
            )
            return updates, {"v": v_new}
        if self.rule == Updater.ADAGRAD:
            eps = hp["epsilon"]
            g2 = jax.tree.map(lambda a, g: a + g * g, state["g2"], grads)
            updates = jax.tree.map(
                lambda g, a: lr * g / (jnp.sqrt(a) + eps), grads, g2
            )
            return updates, {"g2": g2}
        if self.rule == Updater.RMSPROP:
            d, eps = hp["rms_decay"], hp["epsilon"]
            g2 = jax.tree.map(
                lambda a, g: d * a + (1 - d) * g * g, state["g2"], grads
            )
            updates = jax.tree.map(
                lambda g, a: lr * g / jnp.sqrt(a + eps), grads, g2
            )
            return updates, {"g2": g2}
        if self.rule == Updater.ADADELTA:
            rho, eps = hp["rho"], hp["epsilon"]
            g2 = jax.tree.map(
                lambda a, g: rho * a + (1 - rho) * g * g, state["g2"], grads
            )
            dx = jax.tree.map(
                lambda g, a, d2: g
                * jnp.sqrt(d2 + eps)
                / jnp.sqrt(a + eps),
                grads,
                g2,
                state["dx2"],
            )
            dx2 = jax.tree.map(
                lambda d2, d: rho * d2 + (1 - rho) * d * d, state["dx2"], dx
            )
            return dx, {"g2": g2, "dx2": dx2}
        if self.rule == Updater.ADAM:
            b1, b2, eps = hp["adam_mean_decay"], hp["adam_var_decay"], hp["epsilon"]
            t = iteration + 1
            m = jax.tree.map(
                lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads
            )
            v = jax.tree.map(
                lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], grads
            )
            bias = jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
            updates = jax.tree.map(
                lambda m_, v_: lr * bias * m_ / (jnp.sqrt(v_) + eps), m, v
            )
            return updates, {"m": m, "v": v}
        raise ValueError(f"Unsupported updater {self.rule}")


def _resolve_schedule(base: float, sched, iteration):
    """Piecewise-constant schedule lookup, jit-safe (reference
    ``momentumAfter``/``learningRateAfter`` map semantics)."""
    if not sched:
        return base
    items = sorted((int(k), float(v)) for k, v in sched.items())
    val = jnp.asarray(base, jnp.float32)
    for it_key, v in items:
        val = jnp.where(iteration >= it_key, v, val)
    return val


def make_layer_updater(conf) -> LayerUpdater:
    """Build a LayerUpdater from a NeuralNetConfiguration, honoring
    layer-over-global hyperparameter overrides."""
    rule = conf.resolved("updater")
    hp = {
        "momentum": float(conf.resolved("momentum")),
        "momentum_schedule": conf.momentum_schedule,
        "rho": float(conf.resolved("rho")),
        "rms_decay": float(conf.resolved("rms_decay")),
        "adam_mean_decay": float(conf.resolved("adam_mean_decay")),
        "adam_var_decay": float(conf.resolved("adam_var_decay")),
        "epsilon": float(conf.epsilon),
    }
    return LayerUpdater(Updater(rule), hp)


def resolve_lr(conf, iteration):
    """Learning rate with optional integer-keyed schedule (reference
    ``learningRateAfter`` map semantics) or smooth lr_policy. jit-safe:
    the schedule dict/policy constants are static; the lookup compiles
    to selects / a closed-form cosine on the iteration counter."""
    base = float(conf.resolved("learning_rate"))
    policy = getattr(conf, "lr_policy", None)
    if policy:
        if conf.learning_rate_schedule:
            raise ValueError(
                "lr_policy and learning_rate_schedule are mutually "
                "exclusive")
        if policy != "warmup_cosine":
            raise ValueError(
                f"unknown lr_policy {policy!r} (known: 'warmup_cosine')")
        warm = int(conf.lr_warmup_steps)
        total = int(conf.lr_total_steps)
        if total <= warm:
            raise ValueError(
                f"lr_policy='warmup_cosine' needs lr_total_steps "
                f"({total}) > lr_warmup_steps ({warm}) — an unset "
                "horizon would silently train at the min-fraction floor")
        frac = float(conf.lr_min_fraction)
        it = jnp.asarray(iteration, jnp.float32)
        ramp = jnp.minimum(it / warm, 1.0) if warm > 0 else 1.0
        prog = jnp.clip((it - warm) / (total - warm), 0.0, 1.0)
        cos = frac + (1.0 - frac) * 0.5 * (1.0 + jnp.cos(jnp.pi * prog))
        return base * ramp * cos
    return _resolve_schedule(base, conf.learning_rate_schedule, iteration)


def normalize_gradients(
    mode: GradientNormalization, grads: Pytree, threshold: float
) -> Pytree:
    """Per-layer gradient normalization (reference GradientNormalization)."""
    if mode == GradientNormalization.NONE:
        return grads
    if mode == GradientNormalization.CLIP_ELEMENT_WISE_ABSOLUTE_VALUE:
        return jax.tree.map(
            lambda g: jnp.clip(g, -threshold, threshold), grads
        )
    if mode == GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE:
        return jax.tree.map(
            lambda g: g / (jnp.linalg.norm(g.ravel()) + 1e-8), grads
        )
    if mode == GradientNormalization.CLIP_L2_PER_PARAM_TYPE:

        def clip(g):
            n = jnp.linalg.norm(g.ravel())
            return jnp.where(n > threshold, g * (threshold / (n + 1e-8)), g)

        return jax.tree.map(clip, grads)
    # Whole-layer modes: norm over every parameter in the layer.
    leaves = jax.tree.leaves(grads)
    total = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
    if mode == GradientNormalization.RENORMALIZE_L2_PER_LAYER:
        return jax.tree.map(lambda g: g / (total + 1e-8), grads)
    if mode == GradientNormalization.CLIP_L2_PER_LAYER:
        scale = jnp.where(total > threshold, threshold / (total + 1e-8), 1.0)
        return jax.tree.map(lambda g: g * scale, grads)
    raise ValueError(f"Unknown gradient normalization {mode}")


def aggregate_updater_states(states: list) -> Pytree:
    """Element-wise mean of updater states across workers (reference
    UpdaterAggregator / UpdaterAggregatorCombiner, SparkDl4jMultiLayer
    :371-378). For SPMD use, prefer a psum inside the step instead."""
    n = len(states)
    return jax.tree.map(lambda *xs: sum(xs) / n, *states)
