"""ComputationGraph: DAG network runtime.

Mirror of reference nn/graph/ComputationGraph.java:59 (1,598 LoC):
topologicalSortOrder :593, computeGradientAndScore :656, feedForward :689,
multi-input/multi-output fit. Same TPU inversion as MultiLayerNetwork: the
whole DAG forward + multi-output loss + backward + update is one jitted XLA
computation; vertex structure is resolved at trace time (static), so XLA
sees a flat fused graph.
"""

from __future__ import annotations

import functools
import os
import pickle
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.enums import (
    BackpropType,
    OptimizationAlgorithm,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ComputationGraphConfiguration,
    DuplicateToTimeSeriesVertex,
    ElementWiseOp,
    ElementWiseVertex,
    LastTimeStepVertex,
    LayerVertex,
    MergeVertex,
    PreprocessorVertex,
    SubsetVertex,
)
from deeplearning4j_tpu.nn.gradient import Gradient
from deeplearning4j_tpu.nn.layers import get_impl
from deeplearning4j_tpu.nn.multilayer import (
    _REGULARIZED_KEYS,
    _cast_floating,
    _dtype_of,
    _resolve_compute_dtype,
)
from deeplearning4j_tpu.nn.updater.updaters import (
    make_layer_updater,
    normalize_gradients,
    resolve_lr,
)
from deeplearning4j_tpu.optimize.telemetry import (
    TrainTelemetry,
    batch_counts,
    grad_health,
    window_counts,
)

Array = jax.Array


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        conf.validate()
        for out in conf.network_outputs:
            v = conf.vertices[out]
            if not (
                isinstance(v, LayerVertex)
                and isinstance(v.conf.layer, (L.BaseOutputLayer,))
            ):
                raise ValueError(
                    f"Network output {out!r} must be an output layer vertex "
                    "(OutputLayer/RnnOutputLayer) to compute a loss"
                )
        self.conf = conf
        self.order = conf.topological_order()
        self.params: Dict[str, Dict[str, Array]] = {}
        self.state: Dict[str, Any] = {}
        self.updater_state: Dict[str, Any] = {}
        self.iteration = 0
        self.score_value = float("nan")
        self.listeners: List = []
        # Per-step phase clock (see MultiLayerNetwork.train_telemetry).
        self.train_telemetry = TrainTelemetry()
        self._rnn_state: Dict[str, Any] = {}
        self._generate_fns: Dict[int, Any] = {}
        self._layer_vertices = {
            name: v
            for name, v in conf.vertices.items()
            if isinstance(v, LayerVertex)
        }
        self._impls = {
            name: get_impl(v.conf.layer)
            for name, v in self._layer_vertices.items()
        }
        self._updaters = {
            name: make_layer_updater(v.conf)
            for name, v in self._layer_vertices.items()
        }
        first = next(iter(self._layer_vertices.values()), None)
        self._dtype = _dtype_of(first.conf.dtype if first else "float32")
        self._compute_dtype = _resolve_compute_dtype(
            self._dtype, first.conf.compute_dtype if first else None)
        seed = first.conf.seed if first else 12345
        self._key = jax.random.key(seed)
        self._seed = seed
        self._initialized = False

    # ------------------------------------------------------------------
    def init(self) -> "ComputationGraph":
        if self._initialized:
            return self
        key = jax.random.key(self._seed)
        names = sorted(self._layer_vertices)
        keys = jax.random.split(key, max(1, len(names)))
        for k, name in zip(keys, names):
            v = self._layer_vertices[name]
            impl = self._impls[name]
            self.params[name] = impl.init(k, v.conf, self._dtype)
            st = impl.init_state(v.conf, self._dtype)
            if st is not None:
                self.state[name] = st
            self.updater_state[name] = self._updaters[name].init(
                self.params[name]
            )
        self._initialized = True
        return self

    # ------------------------------------------------------------------
    def _forward_fn(
        self,
        params,
        state,
        inputs: Dict[str, Array],
        rng,
        train: bool,
        masks: Optional[Dict[str, Array]] = None,
        rnn_state: Optional[Dict[str, Any]] = None,
        stop_at: Optional[str] = None,
    ):
        """Topological-order forward. Returns
        (activation dict, new_state, new_rnn_state) — ``rnn_state`` is the
        per-vertex recurrent carry (reference ComputationGraph
        rnnActivateUsingStoredState :1233: stored state fed back in for
        streaming inference and truncated-BPTT window chaining)."""
        # Output-layer vertices run at the master dtype (same rationale
        # as MultiLayerNetwork._forward_fn: a bf16 softmax quantizes
        # probabilities coarsely enough to stall training).
        out_f32_vertices = (
            set(self.conf.network_outputs)
            if self._compute_dtype is not None else set())
        if self._compute_dtype is not None:
            # Mixed precision: bf16 compute, f32 master params (same
            # scheme as MultiLayerNetwork._forward_fn)
            cast = functools.partial(
                _cast_floating, dtype=self._compute_dtype)
            params = {
                k: (sub if k in out_f32_vertices
                    else jax.tree_util.tree_map(cast, sub))
                for k, sub in params.items()
            }
            inputs = {k: cast(v) for k, v in inputs.items()}
        acts: Dict[str, Array] = dict(inputs)
        new_state = dict(state) if state else {}
        new_rnn: Dict[str, Any] = {}
        # Masks propagate along edges: a vertex inherits its first input's
        # time mask, so stacked recurrent layers stay masked (parity with
        # MultiLayerNetwork, which hands feature_mask to every recurrent
        # layer). Time-collapsing vertices drop the mask.
        vmasks: Dict[str, Optional[Array]] = dict(masks or {})
        n_layers = max(1, len(self._layer_vertices))
        if rng is not None:
            layer_keys = dict(
                zip(
                    sorted(self._layer_vertices),
                    jax.random.split(rng, n_layers),
                )
            )
        else:
            layer_keys = {}
        for name in self.order:
            vertex = self.conf.vertices[name]
            in_names = self.conf.vertex_inputs[name]
            xs = [acts[i] for i in in_names]
            in_mask = vmasks.get(in_names[0])
            if isinstance(vertex, LastTimeStepVertex):
                vmasks[name] = None  # collapses the time axis
            else:
                vmasks[name] = in_mask
            if isinstance(vertex, LayerVertex):
                x = xs[0]
                if vertex.preprocessor is not None:
                    x = vertex.preprocessor.pre_process(
                        x, layer_keys.get(name) if train else None
                    )
                impl = self._impls[name]
                layer_state = new_state.get(name)
                if layer_state is None and rnn_state:
                    layer_state = rnn_state.get(name)
                is_recurrent = isinstance(
                    vertex.conf.layer, L.RECURRENT_LAYER_TYPES
                )
                mask = in_mask if is_recurrent else None
                if name in out_f32_vertices:
                    x = _cast_floating(x, self._dtype)
                out, st = impl.apply(
                    vertex.conf,
                    params[name],
                    x,
                    state=layer_state,
                    train=train,
                    rng=layer_keys.get(name) if train else None,
                    mask=mask,
                )
                if st is not None:
                    if self._compute_dtype is not None:
                        # carried state stays at master dtype so repeated
                        # steps see stable input dtypes (no recompiles)
                        st = jax.tree_util.tree_map(
                            functools.partial(_cast_floating,
                                              dtype=self._dtype), st)
                    if name in new_state:
                        new_state[name] = st
                    else:
                        # recurrent carry (h, c): returned separately so
                        # rnn_time_step/tBPTT can chain it across calls
                        new_rnn[name] = st
                acts[name] = out
            elif isinstance(vertex, MergeVertex):
                acts[name] = jnp.concatenate(xs, axis=1)
            elif isinstance(vertex, ElementWiseVertex):
                acts[name] = _elementwise(vertex.op, xs)
            elif isinstance(vertex, SubsetVertex):
                acts[name] = xs[0][:, vertex.from_index : vertex.to_index + 1]
            elif isinstance(vertex, PreprocessorVertex):
                acts[name] = vertex.preprocessor.pre_process(xs[0])
            elif isinstance(vertex, LastTimeStepVertex):
                acts[name] = _last_time_step(
                    xs[0], vmasks.get(vertex.mask_input)
                )
            elif isinstance(vertex, DuplicateToTimeSeriesVertex):
                ref = acts[vertex.reference_input]
                acts[name] = jnp.broadcast_to(
                    xs[0][:, :, None],
                    xs[0].shape + (ref.shape[-1],),
                )
            else:
                raise ValueError(f"Unknown vertex type {type(vertex).__name__}")
            if name == stop_at:
                # partial forward (pretraining): downstream vertices are
                # never consumed, so don't trace them at all
                break
        return acts, new_state, new_rnn

    def _loss_fn(self, params, state, rng, inputs, labels, masks, label_masks,
                 rnn_state=None):
        acts, new_state, new_rnn = self._forward_fn(
            params, state, inputs, rng, True, masks, rnn_state
        )
        score = 0.0
        for out_name, y in zip(self.conf.network_outputs, labels):
            impl = self._impls[out_name]
            v = self._layer_vertices[out_name]
            lm = None if label_masks is None else label_masks.get(out_name)
            out = acts[out_name]
            if self._compute_dtype is not None:
                out = _cast_floating(out, dtype=self._dtype)  # loss in f32
            score = score + impl.loss(v.conf, out, y, lm)
        score = score + self._reg_score(params)
        score = score + self._aux_score(new_state)
        return score, (new_state, new_rnn)

    def _aux_score(self, new_state):
        """Auxiliary training losses vertices emit through the state
        channel (MoeDense load-balancing loss), gate-weighted per conf."""
        aux = 0.0
        for name, v in self._layer_vertices.items():
            w = getattr(v.conf.layer, "aux_weight", None)
            st = new_state.get(name) if new_state else None
            if w and st and "aux_loss" in st:
                aux = aux + w * st["aux_loss"]
        return aux

    def _reg_score(self, params):
        reg = 0.0
        for name, v in self._layer_vertices.items():
            c = v.conf
            if not c.use_regularization:
                continue
            l1 = float(c.resolved("l1") or 0.0)
            l2 = float(c.resolved("l2") or 0.0)
            if l1 == 0.0 and l2 == 0.0:
                continue
            for pname, p in params[name].items():
                if pname not in _REGULARIZED_KEYS:
                    continue
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(p))
                if l2:
                    reg = reg + 0.5 * l2 * jnp.sum(p * p)
        return reg

    # ------------------------------------------------------------------
    def _apply_updates(self, params, upd_state, grads, iteration,
                       grad_scale=1.0):
        """Per-vertex normalize → scale → updater → subtract (shared by
        the standard and tBPTT steps)."""
        new_params = {}
        new_upd = {}
        for name, v in self._layer_vertices.items():
            c = v.conf
            g = normalize_gradients(
                c.resolved("gradient_normalization"),
                grads[name],
                float(c.resolved("gradient_normalization_threshold")),
            )
            # see MultiLayerNetwork._apply_updates: ACCUM-without-divide
            g = jax.tree.map(lambda a: a * grad_scale, g)
            updates, new_upd[name] = self._updaters[name].update(
                g, upd_state[name], resolve_lr(c, iteration), iteration
            )
            new_params[name] = jax.tree.map(
                lambda p, u: p - u, params[name], updates
            )
        return new_params, new_upd

    def _step_body(self, params, state, upd_state, iteration, rng, inputs,
                   labels, masks, label_masks, grad_scale=1.0):
        (score, (new_state, _)), grads = jax.value_and_grad(
            self._loss_fn, has_aux=True
        )(params, state, rng, inputs, labels, masks, label_masks)
        new_params, new_upd = self._apply_updates(
            params, upd_state, grads, iteration, grad_scale)
        # Same-executable gradient-health outputs (see
        # MultiLayerNetwork._step_body).
        health = grad_health(grads, params, new_params)
        return new_params, new_state, new_upd, score, health

    @functools.cached_property
    def _train_step(self):
        return jax.jit(self._step_body, donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _train_steps_scan(self):
        """K graph train steps fused into one lax.scan computation (the
        ComputationGraph counterpart of MultiLayerNetwork.fit_scan).
        Mask dicts ride the scan as extra xs (a dict pytree scans
        leaf-wise): an absent mask is an EMPTY dict, which contributes
        no scan leaves and which the loss path already treats like None
        — one compiled kernel per mask-dict structure, keyed by jit
        itself."""

        def steps(params, state, upd_state, iteration, rng, inputs_k,
                  labels_k, masks_k, lmasks_k, grad_scale=1.0):
            def body(carry, inp):
                p, s, u, it, key = carry
                key, sub = jax.random.split(key)
                xs, ys, m, lm = inp
                p, s, u, score, health = self._step_body(
                    p, s, u, it, sub, xs, ys, m, lm, grad_scale)
                return (p, s, u, it + 1, key), (score, health)

            (p, s, u, it, _), (scores, health) = jax.lax.scan(
                body, (params, state, upd_state, iteration, rng),
                (inputs_k, labels_k, masks_k, lmasks_k))
            return p, s, u, scores, health

        return jax.jit(steps, donate_argnums=(0, 1, 2))

    def fit_scan(self, inputs_stacked, labels_stacked,
                 masks_stacked=None, label_masks_stacked=None,
                 grad_scale: float = 1.0):
        """Run K fused steps over pre-stacked batches. ``inputs_stacked``:
        dict input-name -> [K, B, ...] (or a single array for
        single-input graphs); ``labels_stacked``: list of [K, B, ...]
        per output (or a single array). Optional masks:
        ``masks_stacked`` dict input-name -> [K, B, T] (or a single
        array for single-input graphs), ``label_masks_stacked`` dict
        output-name -> [K, B, T] — they ride the scan as extra xs, so
        masked time-series graphs get the same fused fast path.
        Plain-SGD; returns the K per-step scores lazily (device array)."""
        if self.conf.backprop_type == BackpropType.TRUNCATED_BPTT:
            raise ValueError(
                "fit_scan is the full-BPTT SGD fast path; truncated-BPTT "
                "graphs must train via fit()")
        for name, v in self._layer_vertices.items():
            algo = v.conf.optimization_algo
            if algo != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
                raise ValueError(
                    f"fit_scan only supports SGD, but vertex {name!r} is "
                    f"configured with {algo}; use fit()")
        self.init()
        if not isinstance(inputs_stacked, dict):
            inputs_stacked = {
                self.conf.network_inputs[0]: inputs_stacked}
        if not isinstance(labels_stacked, (list, tuple)):
            labels_stacked = [labels_stacked]
        if set(inputs_stacked) != set(self.conf.network_inputs):
            raise ValueError(
                f"fit_scan got inputs {sorted(inputs_stacked)} but graph "
                f"has inputs {sorted(self.conf.network_inputs)}")
        if len(labels_stacked) != len(self.conf.network_outputs):
            raise ValueError(
                f"fit_scan got {len(labels_stacked)} label arrays but "
                f"graph has {len(self.conf.network_outputs)} outputs")
        inputs_k = {k: jnp.asarray(v, self._dtype)
                    for k, v in inputs_stacked.items()}
        labels_k = [jnp.asarray(y, self._dtype) for y in labels_stacked]
        if masks_stacked is not None and not isinstance(masks_stacked, dict):
            masks_stacked = {self.conf.network_inputs[0]: masks_stacked}
        if (label_masks_stacked is not None
                and not isinstance(label_masks_stacked, dict)):
            label_masks_stacked = {
                self.conf.network_outputs[0]: label_masks_stacked}
        # Mask keys are looked up with .get() downstream, so a mistyped
        # name would silently train unmasked — validate here.
        if masks_stacked is not None:
            bad = set(masks_stacked) - set(self.conf.network_inputs)
            if bad:
                raise ValueError(
                    f"masks_stacked has keys {sorted(bad)} that are not "
                    f"network inputs {sorted(self.conf.network_inputs)}")
        if label_masks_stacked is not None:
            bad = set(label_masks_stacked) - set(self.conf.network_outputs)
            if bad:
                raise ValueError(
                    f"label_masks_stacked has keys {sorted(bad)} that "
                    f"are not network outputs "
                    f"{sorted(self.conf.network_outputs)}")
        masks_k = {k: jnp.asarray(v)
                   for k, v in (masks_stacked or {}).items()}
        lmasks_k = {k: jnp.asarray(v)
                    for k, v in (label_masks_stacked or {}).items()}
        self._key, sub = jax.random.split(self._key)
        start = self.iteration
        t0 = time.perf_counter()
        self.params, self.state, self.updater_state, scores, health = (
            self._train_steps_scan(
                self.params, self.state, self.updater_state,
                self.iteration, sub, inputs_k, labels_k,
                masks_k, lmasks_k, grad_scale))
        k, examples, tokens = window_counts(
            next(iter(inputs_k.values())).shape)
        self.train_telemetry.record_step(
            dispatch_s=time.perf_counter() - t0, steps=k,
            examples=examples, tokens=tokens, health=health)
        self.iteration += k
        self.score_value = scores[-1]
        from deeplearning4j_tpu.optimize.listeners import fire_crossed

        fire_crossed(self.listeners, self, start, self.iteration)
        return scores

    @functools.cached_property
    def _output_fn(self):
        def out(params, state, inputs):
            acts, _, _ = self._forward_fn(params, state, inputs, None, False)
            return [acts[name] for name in self.conf.network_outputs]

        return jax.jit(out)

    # ------------------------------------------------------------------
    def _coerce_multi(self, data) -> Tuple[Dict[str, Array], List[Array], Optional[Dict], Optional[Dict]]:
        """Accept DataSet (single in/out), MultiDataSet, or
        (features-list, labels-list) tuples."""
        from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet

        if isinstance(data, MultiDataSet):
            if len(data.features) != len(self.conf.network_inputs):
                raise ValueError(
                    f"MultiDataSet has {len(data.features)} feature "
                    f"arrays but graph has "
                    f"{len(self.conf.network_inputs)} inputs"
                )
            if len(data.labels) != len(self.conf.network_outputs):
                raise ValueError(
                    f"MultiDataSet has {len(data.labels)} label arrays "
                    f"but graph has {len(self.conf.network_outputs)} "
                    f"outputs"
                )
            inputs = {
                n: jnp.asarray(f, self._dtype)
                for n, f in zip(self.conf.network_inputs, data.features)
            }
            labels = [jnp.asarray(y, self._dtype) for y in data.labels]
            masks = None
            if data.features_masks is not None:
                masks = {
                    n: jnp.asarray(m)
                    for n, m in zip(
                        self.conf.network_inputs, data.features_masks
                    )
                    if m is not None
                } or None
            lmasks = None
            if data.labels_masks is not None:
                lmasks = {
                    n: jnp.asarray(m)
                    for n, m in zip(
                        self.conf.network_outputs, data.labels_masks
                    )
                    if m is not None
                } or None
            return inputs, labels, masks, lmasks
        if isinstance(data, DataSet):
            inputs = {
                self.conf.network_inputs[0]: jnp.asarray(
                    data.features, self._dtype
                )
            }
            labels = [jnp.asarray(data.labels, self._dtype)]
            masks = (
                None
                if data.features_mask is None
                else {
                    self.conf.network_inputs[0]: jnp.asarray(data.features_mask)
                }
            )
            lmasks = (
                None
                if data.labels_mask is None
                else {
                    self.conf.network_outputs[0]: jnp.asarray(data.labels_mask)
                }
            )
            return inputs, labels, masks, lmasks
        features, labels = data  # (list-of-arrays, list-of-arrays)
        inputs = {
            n: jnp.asarray(f, self._dtype)
            for n, f in zip(self.conf.network_inputs, features)
        }
        return inputs, [jnp.asarray(y, self._dtype) for y in labels], None, None

    def _host_multi(self, data):
        """Host-side sibling of ``_coerce_multi``: same name mapping,
        NO device transfer or dtype cast — the windowing/stacking path
        must keep batches in their minimal wire format (u8 pixels,
        int token ids) until the one per-window upload."""
        import numpy as _np

        from deeplearning4j_tpu.datasets.dataset import (
            DataSet,
            MultiDataSet,
        )

        def name_masks(names, masks):
            if masks is None:
                return None
            return {n: _np.asarray(m)
                    for n, m in zip(names, masks)
                    if m is not None} or None

        if isinstance(data, MultiDataSet):
            if len(data.features) != len(self.conf.network_inputs):
                raise ValueError(
                    f"MultiDataSet has {len(data.features)} feature "
                    f"arrays but graph has "
                    f"{len(self.conf.network_inputs)} inputs")
            if len(data.labels) != len(self.conf.network_outputs):
                raise ValueError(
                    f"MultiDataSet has {len(data.labels)} label arrays "
                    f"but graph has {len(self.conf.network_outputs)} "
                    f"outputs")
            inputs = {n: _np.asarray(f) for n, f in zip(
                self.conf.network_inputs, data.features)}
            labels = [_np.asarray(y) for y in data.labels]
            return (inputs, labels,
                    name_masks(self.conf.network_inputs,
                               data.features_masks),
                    name_masks(self.conf.network_outputs,
                               data.labels_masks))
        if isinstance(data, DataSet):
            fm = (None if data.features_mask is None else
                  {self.conf.network_inputs[0]:
                   _np.asarray(data.features_mask)})
            lm = (None if data.labels_mask is None else
                  {self.conf.network_outputs[0]:
                   _np.asarray(data.labels_mask)})
            return ({self.conf.network_inputs[0]:
                     _np.asarray(data.features)},
                    [_np.asarray(data.labels)], fm, lm)
        feats, labels = data
        return ({n: _np.asarray(f) for n, f in zip(
                    self.conf.network_inputs, feats)},
                [_np.asarray(y) for y in labels], None, None)

    def fit_stream(self, iterator, scan_steps: int = 16,
                   ingest=None, ingest_labels=None,
                   sync_each_window: bool = False):
        """Host-fed graph training: the ComputationGraph counterpart of
        ``MultiLayerNetwork.fit_stream`` (see its docstring for the
        windowing/transport rationale; reference AsyncDataSetIterator,
        datasets/iterator/AsyncDataSetIterator.java:1). Consumes
        DataSet/MultiDataSet batches from the iterator, stacks
        ``scan_steps`` of them into [K, B, ...] pytrees host-side (wire
        format preserved until the one per-window upload), and trains
        each window in ONE fused ``fit_scan`` dispatch. ``ingest`` /
        ``ingest_labels`` receive the stacked input DICT / label LIST
        — and also apply on ragged tails (stacked [1, B, ...], then
        trained per-batch via ``fit``). Returns the last window's score
        array."""
        import numpy as _np

        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.nn.streaming_fit import (
            drive_stream_windows,
        )

        self.init()
        scores = None
        in_names = self.conf.network_inputs

        def stack_masks(masks_per_batch, what):
            if all(m is None for m in masks_per_batch):
                return None
            if any(m is None for m in masks_per_batch):
                raise ValueError(
                    f"fit_stream window mixes batches with and "
                    f"without {what}")
            names = set(masks_per_batch[0])
            if any(set(m) != names for m in masks_per_batch):
                raise ValueError(
                    f"fit_stream window mixes {what} name sets")
            return {k: _np.stack([m[k] for m in masks_per_batch])
                    for k in names}

        def stacked(coerced):
            inputs = {
                k: _np.stack([c[0][k] for c in coerced])
                for k in coerced[0][0]
            }
            labels = [
                _np.stack([c[1][i] for c in coerced])
                for i in range(len(coerced[0][1]))
            ]
            fm = stack_masks([c[2] for c in coerced], "feature masks")
            lm = stack_masks([c[3] for c in coerced], "label masks")
            return inputs, labels, fm, lm

        def transform(inputs, labels):
            inputs = {k: jax.device_put(v) for k, v in inputs.items()}
            labels = [jax.device_put(y) for y in labels]
            if sync_each_window:
                # materialize uploads BEFORE dispatching compute (see
                # MultiLayerNetwork.fit_stream transport note)
                for leaf in jax.tree.leaves((inputs, labels)):
                    leaf.block_until_ready()
            if ingest is not None:
                inputs = ingest(inputs)
            if ingest_labels is not None:
                labels = ingest_labels(labels)
            return inputs, labels

        def flush(window, fused):
            nonlocal scores
            if fused:
                inputs, labels, fm, lm = stacked(
                    [self._host_multi(b) for b in window])
                inputs, labels = transform(inputs, labels)
                scores = self.fit_scan(
                    inputs, labels, masks_stacked=fm,
                    label_masks_stacked=lm)
                if sync_each_window:
                    _np.asarray(scores[-1])
                return
            for b in window:  # ragged: correctness over throughput
                inputs, labels, fm, lm = stacked([self._host_multi(b)])
                inputs, labels = transform(inputs, labels)
                self._fit_one(MultiDataSet(
                    [_np.asarray(inputs[n])[0] for n in in_names],
                    [_np.asarray(y)[0] for y in labels],
                    None if fm is None else
                    [fm.get(n, [None])[0] for n in in_names],
                    None if lm is None else
                    [lm.get(n, [None])[0]
                     for n in self.conf.network_outputs]))
            scores = jnp.asarray([self.score_value])

        def batch_shape(ds):
            # full signature: label shapes too — identical features
            # with variable-length labels must also break a window
            inputs, labels, _, _ = self._host_multi(ds)
            return ({k: _np.shape(v) for k, v in inputs.items()},
                    tuple(_np.shape(y) for y in labels))

        drive_stream_windows(iterator, scan_steps, flush, batch_shape,
                             telemetry=self.train_telemetry)
        return scores

    def fit(self, data, labels=None) -> None:
        self.init()
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterator import DataSetIterator

        if labels is not None:
            data = DataSet(data, labels)
        if isinstance(data, DataSetIterator):
            if self.conf.pretrain:
                self.pretrain(data)
                data.reset()
            if not self.conf.backprop:
                return
            it = iter(data)
            while True:
                t0 = time.perf_counter()
                ds = next(it, None)
                self.train_telemetry.add_data_wait(
                    time.perf_counter() - t0)
                if ds is None:
                    break
                self._fit_one(ds)
        else:
            self._fit_one(data)

    def _fit_one(self, data) -> None:
        if self.conf.backprop_type == BackpropType.TRUNCATED_BPTT:
            self._fit_tbptt(data)
            return
        first_conf = next(iter(self._layer_vertices.values())).conf
        if (first_conf.optimization_algo
                != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT):
            from deeplearning4j_tpu.optimize.solver import Solver

            Solver(self).optimize(data)
            return
        inputs, labels, masks, lmasks = self._coerce_multi(data)
        n_iter = max(1, first_conf.num_iterations)
        examples, tokens = batch_counts(next(iter(inputs.values())))
        for _ in range(n_iter):
            self._key, sub = jax.random.split(self._key)
            t0 = time.perf_counter()
            (
                self.params,
                self.state,
                self.updater_state,
                score,
                health,
            ) = self._train_step(
                self.params, self.state, self.updater_state,
                self.iteration, sub, inputs, labels, masks, lmasks,
            )
            self.train_telemetry.record_step(
                dispatch_s=time.perf_counter() - t0, examples=examples,
                tokens=tokens, health=health)
            self.score_value = score
            self.iteration += 1
            for listener in self.listeners:
                if listener.invoked_every <= 1 or (
                    self.iteration % listener.invoked_every == 0
                ):
                    listener.iteration_done(self, self.iteration)

    # ------------------------------------------------------------------
    # Truncated BPTT (reference ComputationGraph.doTruncatedBPTT :1349):
    # chop the time axis into fwd-length windows, carry per-vertex
    # recurrent state (stop-gradient) across windows. Non-temporal (2-D)
    # inputs are fed whole into every window, as the reference does.
    # ------------------------------------------------------------------
    def _fit_tbptt(self, data) -> None:
        inputs, labels, masks, lmasks = self._coerce_multi(data)
        length = self.conf.tbptt_fwd_length
        temporal = [v.shape[2] for v in list(inputs.values()) + labels
                    if v.ndim == 3]
        if not temporal:
            raise ValueError(
                "truncated BPTT requires at least one [B, C, T] input or "
                "label")
        t_total = max(temporal)
        rnn_state: Dict[str, Any] = {}
        for start in range(0, t_total, length):
            end = min(start + length, t_total)
            iw = {k: (v[:, :, start:end] if v.ndim == 3 else v)
                  for k, v in inputs.items()}
            lw = [y[:, :, start:end] if y.ndim == 3 else y for y in labels]
            mw = (None if masks is None
                  else {k: m[:, start:end] for k, m in masks.items()})
            lmw = (None if lmasks is None
                   else {k: m[:, start:end] for k, m in lmasks.items()})
            self._key, sub = jax.random.split(self._key)
            t0 = time.perf_counter()
            (self.params, self.state, self.updater_state, rnn_state,
             score, health) = self._tbptt_step(
                self.params, self.state, self.updater_state,
                self.iteration, sub, iw, lw, mw, lmw, rnn_state)
            first_in = next(iter(iw.values()))
            self.train_telemetry.record_step(
                dispatch_s=time.perf_counter() - t0,
                examples=int(first_in.shape[0]),
                tokens=int(first_in.shape[0]) * (end - start),
                health=health)
            self.score_value = score
            self.iteration += 1
            for listener in self.listeners:
                if listener.invoked_every <= 1 or (
                    self.iteration % listener.invoked_every == 0
                ):
                    listener.iteration_done(self, self.iteration)

    @functools.cached_property
    def _tbptt_step(self):
        def step(params, state, upd_state, iteration, rng, inputs, labels,
                 masks, lmasks, rnn_state):
            (score, (new_state, new_rnn)), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True
            )(params, state, rng, inputs, labels, masks, lmasks, rnn_state)
            new_params, new_upd = self._apply_updates(
                params, upd_state, grads, iteration)
            new_rnn = jax.lax.stop_gradient(new_rnn)
            health = grad_health(grads, params, new_params)
            return new_params, new_state, new_upd, new_rnn, score, health

        return jax.jit(step)

    # ------------------------------------------------------------------
    # RNN streaming inference (reference ComputationGraph.rnnTimeStep
    # :1196): stateful step-by-step forward carrying hidden state between
    # calls; 2-D inputs are treated as one time step and the output is
    # squeezed back to 2-D, matching the reference's shape contract.
    # ------------------------------------------------------------------
    def rnn_time_step(self, *features) -> List[Array]:
        self.init()
        from deeplearning4j_tpu.nn.layers.attention import (
            guard_streamable,
        )

        guard_streamable(
            (name, lv.conf.layer)
            for name, lv in self._layer_vertices.items())
        # Direct consumers of each network input: a 2-D input consumed by
        # recurrent layers is ONE time step (expand to [B, C, 1], as the
        # reference's BaseRecurrentLayer.rnnTimeStep does internally); a
        # 2-D input consumed by non-recurrent vertices (Dense,
        # DuplicateToTimeSeries) is static and keeps its rank.
        consumers: Dict[str, List[str]] = {}
        for vname, in_names in self.conf.vertex_inputs.items():
            for inp in in_names:
                consumers.setdefault(inp, []).append(vname)
        inputs = {}
        ranks = []
        for n, f in zip(self.conf.network_inputs, features):
            x = jnp.asarray(f, self._dtype)
            ranks.append(x.ndim)
            if x.ndim == 2:
                cons = consumers.get(n, [])
                rec = [c for c in cons
                       if isinstance(self.conf.vertices[c], LayerVertex)
                       and isinstance(self.conf.vertices[c].conf.layer,
                                      L.RECURRENT_LAYER_TYPES)]
                if rec and len(rec) == len(cons):
                    x = x[:, :, None]
                elif rec:
                    raise ValueError(
                        f"Input {n!r} feeds both recurrent ({rec}) and "
                        f"non-recurrent vertices; pass it as 3-D "
                        f"[B, C, 1] to disambiguate one-time-step intent")
            inputs[n] = x
        # squeeze outputs back to 2-D only when ALL inputs were 2-D
        # (mixed-rank calls keep the full time axis — a 3-D input's
        # T-step output must not be truncated to step 0)
        squeeze = bool(ranks) and all(r == 2 for r in ranks)
        acts, _, new_rnn = self._rnn_step_jit(
            self.params, self.state, inputs, self._rnn_state)
        self._rnn_state = new_rnn
        outs = [acts[name] for name in self.conf.network_outputs]
        if squeeze:
            outs = [o[:, :, 0] if o.ndim == 3 else o for o in outs]
        return outs

    @functools.cached_property
    def _rnn_step_jit(self):
        # One jitted computation per streaming step instead of one host
        # dispatch per XLA op (mirrors MultiLayerNetwork._rnn_step_jit).
        def f(params, state, inputs, rnn_state):
            return self._forward_fn(
                params, state, inputs, None, False,
                rnn_state=rnn_state or None,
            )

        return jax.jit(f)

    def rnn_clear_previous_state(self, slots=None) -> None:
        """Reset streaming state (reference rnnClearPreviousState).
        ``slots=[...]`` zeroes only those batch rows across every
        vertex's carried state — the per-slot eviction hook shared
        with MultiLayerNetwork (nn/streaming.py)."""
        from deeplearning4j_tpu.nn.streaming import reset_streaming_state

        self._rnn_state = reset_streaming_state(self._rnn_state, slots)

    def lm_shape(self):
        """(input name, output name, vocab) for an LM-shaped graph:
        single input, single output, first-layer n_in == output n_out.
        Shared by ``generate`` and ``serving.DecodeEngine``; raises
        ValueError for any other topology."""
        if (len(self.conf.network_inputs) != 1
                or len(self.conf.network_outputs) != 1):
            raise ValueError(
                "requires a single-input/single-output LM-shaped graph")
        in_name = self.conf.network_inputs[0]
        out_name = self.conf.network_outputs[0]
        first = None
        for vname, ins in self.conf.vertex_inputs.items():
            if in_name in ins and vname in self._layer_vertices:
                first = self._layer_vertices[vname]
                break
        vocab = getattr(first.conf.layer, "n_in", None) if first else None
        out_bean = self._layer_vertices[out_name].conf.layer
        if vocab is None or vocab != getattr(out_bean, "n_out", None):
            raise ValueError(
                "LM-shaped graph requires input n_in == output n_out "
                f"(got {vocab} vs {getattr(out_bean, 'n_out', None)})")
        return in_name, out_name, vocab

    def generate(self, prompt, n_tokens: int):
        """Greedy autoregressive generation fused on device — the
        ComputationGraph counterpart of
        ``MultiLayerNetwork.generate`` (see its docstring): prefill
        the one-hot prompt [B, V, Tp] through ``rnn_time_step``, then
        ONE jitted ``lax.scan`` emits ``n_tokens`` ids with the
        per-vertex streaming state in the scan carry.

        Requires an LM-shaped single-input/single-output graph
        (input n_in == output n_out). Returns int32 ids
        [B, n_tokens]."""
        if n_tokens < 1:
            raise ValueError(f"n_tokens {n_tokens} < 1")
        self.init()
        in_name, _, vocab = self.lm_shape()
        out = self.rnn_time_step(prompt)[0]
        tok0 = jnp.argmax(out[:, :, -1], axis=1).astype(jnp.int32)
        if n_tokens == 1:
            return tok0[:, None]
        # Scan length bucketed to pow2 with the true length traced —
        # bounded compile count under varied request lengths, same ids
        # and final state (mirrors MultiLayerNetwork.generate).
        from deeplearning4j_tpu.nn.streaming import (
            make_bucketed_generate,
            scan_length_bucket,
        )

        n_rem = n_tokens - 1
        bucket = scan_length_bucket(n_rem)
        gen = self._generate_fns.get(bucket)
        if gen is None:
            def step(params, state, x, rnn):
                acts, _, new_rnn = self._forward_fn(
                    params, state, {in_name: x}, None, False,
                    rnn_state=rnn)
                return acts[self.conf.network_outputs[0]], new_rnn

            gen = self._generate_fns[bucket] = make_bucketed_generate(
                step, vocab, self._dtype, bucket)
        toks, self._rnn_state = gen(
            self.params, self.state, self._rnn_state, tok0,
            jnp.asarray(n_rem, jnp.int32))
        return jnp.concatenate([tok0[:, None], toks[:, :n_rem]], axis=1)

    # ------------------------------------------------------------------
    # Greedy layer-wise pretraining (reference ComputationGraph.pretrain
    # :341-427): for each pretrainable layer vertex in topological order,
    # feed each batch forward (inference mode) to the vertex's input,
    # then run that vertex's unsupervised update (RBM CD-k / AE).
    # ------------------------------------------------------------------
    def pretrain(self, data_iter) -> None:
        self.init()
        from deeplearning4j_tpu.optimize.pretrainer import pretrain_graph

        pretrain_graph(self, data_iter)

    def _pretrain_input(self, name: str, ds) -> Array:
        """Activations feeding vertex ``name`` (inference mode), with the
        vertex's own preprocessor applied — the graph analog of
        MultiLayerNetwork's activationFromPrevLayer. The partial forward
        stops at the feeding vertex (downstream vertices are not traced)
        and is jitted, cached per feeding vertex."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if isinstance(ds, DataSet) and ds.labels is None:
            # feature-only data — the normal input to unsupervised
            # pretraining; _coerce_multi would choke on labels=None
            inputs = {self.conf.network_inputs[0]: jnp.asarray(
                ds.features, self._dtype)}
            masks = (None if ds.features_mask is None else {
                self.conf.network_inputs[0]: jnp.asarray(ds.features_mask)})
        else:
            inputs, _, masks, _ = self._coerce_multi(ds)
        vertex = self.conf.vertices[name]
        in_name = self.conf.vertex_inputs[name][0]
        if in_name in inputs:
            x = inputs[in_name]
        else:
            cache = getattr(self, "_pretrain_fwd_cache", None)
            if cache is None:
                cache = self._pretrain_fwd_cache = {}
            fn = cache.get(in_name)
            if fn is None:
                def fwd(params, state, inputs, masks, _n=in_name):
                    acts, _, _ = self._forward_fn(
                        params, state, inputs, None, False, masks,
                        stop_at=_n)
                    return acts[_n]

                fn = cache[in_name] = jax.jit(fwd)
            x = fn(self.params, self.state, inputs, masks)
        if vertex.preprocessor is not None:
            x = vertex.preprocessor.pre_process(x)
        return x

    # ------------------------------------------------------------------
    def output(self, *features) -> List[Array]:
        self.init()
        inputs = {
            n: jnp.asarray(f, self._dtype)
            for n, f in zip(self.conf.network_inputs, features)
        }
        return self._output_fn(self.params, self.state, inputs)

    def feed_forward(self, *features) -> Dict[str, Array]:
        self.init()
        inputs = {
            n: jnp.asarray(f, self._dtype)
            for n, f in zip(self.conf.network_inputs, features)
        }
        acts, _, _ = self._forward_fn(
            self.params, self.state, inputs, None, False)
        return acts

    def score(self, data=None) -> float:
        if data is None:
            return float(self.score_value)
        self.init()
        inputs, labels, masks, lmasks = self._coerce_multi(data)
        s, _ = self._loss_fn(
            self.params, self.state, None, inputs, labels, masks, lmasks
        )
        return float(s)

    def compute_gradient_and_score(self, data) -> Tuple[float, Gradient]:
        self.init()
        inputs, labels, masks, lmasks = self._coerce_multi(data)
        (score, _), grads = jax.value_and_grad(self._loss_fn, has_aux=True)(
            self.params, self.state, None, inputs, labels, masks, lmasks
        )
        flat = {}
        for name in sorted(grads):
            for pname, g in grads[name].items():
                flat[f"{name}_{pname}"] = g
        return float(score), Gradient(flat)

    def evaluate(self, data_iter):
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        self.init()
        ev = Evaluation()
        for ds in data_iter:
            out = self.output(ds.features)[0]
            if np.asarray(ds.labels).ndim == 3:
                ev.eval_time_series(ds.labels, out, ds.labels_mask)
            else:
                ev.eval(ds.labels, out)
        return ev

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    # ------------------------------------------------------------------
    def params_flat(self) -> Array:
        flat, _ = ravel_pytree(self.params)
        return flat

    def num_params(self) -> int:
        return int(self.params_flat().shape[0])

    def clone(self) -> "ComputationGraph":
        """Deep-copy (buffers AND conf: the train step donates
        params/state, so aliased references would be deleted by the
        donor's next step; conf isolation matches
        MultiLayerNetwork.clone). Skips init() — its random params would
        be immediately overwritten."""
        copy = functools.partial(jax.tree.map, jnp.copy)
        net = ComputationGraph(self.conf.clone())
        net.params = copy(self.params)
        net.updater_state = copy(self.updater_state)
        net.state = copy(self.state)
        net.iteration = self.iteration
        net._initialized = True
        return net

    def save(self, path: str) -> None:
        """One-zip checkpoint (util/model_serializer format)."""
        from deeplearning4j_tpu.util.model_serializer import write_model

        write_model(self, path)

    @staticmethod
    def load(path: str) -> "ComputationGraph":
        from deeplearning4j_tpu.util.model_serializer import restore_model

        net = restore_model(path)
        if not isinstance(net, ComputationGraph):
            raise TypeError(f"{path} holds a {type(net).__name__}")
        return net


def _elementwise(op: ElementWiseOp, xs: Sequence[Array]) -> Array:
    if op == ElementWiseOp.ADD:
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out
    if op == ElementWiseOp.SUBTRACT:
        if len(xs) != 2:
            raise ValueError("SUBTRACT requires exactly 2 inputs")
        return xs[0] - xs[1]
    if op == ElementWiseOp.PRODUCT:
        out = xs[0]
        for x in xs[1:]:
            out = out * x
        return out
    if op == ElementWiseOp.AVERAGE:
        return sum(xs) / len(xs)
    if op == ElementWiseOp.MAX:
        out = xs[0]
        for x in xs[1:]:
            out = jnp.maximum(out, x)
        return out
    raise ValueError(f"Unknown elementwise op {op}")


def _last_time_step(x: Array, mask: Optional[Array]) -> Array:
    if mask is None:
        return x[:, :, -1]
    # Index of last nonzero mask entry per example.
    idx = (
        mask.shape[1]
        - 1
        - jnp.argmax(jnp.flip(mask, axis=1) > 0, axis=1)
    ).astype(jnp.int32)
    return jnp.take_along_axis(x, idx[:, None, None], axis=2)[:, :, 0]
