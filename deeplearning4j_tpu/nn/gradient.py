"""Gradient container: ordered map paramName -> gradient array.

Mirror of reference nn/gradient/{Gradient,DefaultGradient}.java. Keys use the
reference's flat naming "<layerIdx>_<param>" (e.g. "0_W", "2_b" — see
MultiLayerNetwork.calcBackpropGradients :1226,:1245) so gradient-check and
updater tests can address parameters identically.
"""

from __future__ import annotations

from typing import Dict

import jax

Array = jax.Array


class Gradient:
    def __init__(self, flat: Dict[str, Array] | None = None):
        self._map: Dict[str, Array] = dict(flat or {})

    @staticmethod
    def from_tree(tree: Dict[str, Dict[str, Array]]) -> "Gradient":
        flat = {}
        for idx in sorted(tree, key=int):
            for name, g in tree[idx].items():
                flat[f"{idx}_{name}"] = g
        return Gradient(flat)

    def to_tree(self) -> Dict[str, Dict[str, Array]]:
        tree: Dict[str, Dict[str, Array]] = {}
        for key, g in self._map.items():
            idx, name = key.split("_", 1)
            tree.setdefault(idx, {})[name] = g
        return tree

    def gradient_for_variable(self, key: str) -> Array:
        return self._map[key]

    def set_gradient_for(self, key: str, value: Array) -> None:
        self._map[key] = value

    def gradient_map(self) -> Dict[str, Array]:
        return dict(self._map)

    def keys(self):
        return self._map.keys()

    def __iter__(self):
        return iter(self._map.items())
