"""Polymorphic JSON serde for configuration beans.

Replaces the reference's Jackson polymorphic type registry
(reference nn/conf/layers/Layer.java:43-56 ``@JsonSubTypes`` list). Beans are
dataclasses registered under a stable type name; serialization tags each
object with ``"@type"`` so heterogeneous lists (layers, preprocessors,
vertices) round-trip.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Type, TypeVar

_REGISTRY: dict[str, type] = {}
_TYPE_KEY = "@type"

T = TypeVar("T")


def register_bean(name: str):
    """Class decorator: register a dataclass under a stable JSON type name."""

    def deco(cls):
        if name in _REGISTRY and _REGISTRY[name] is not cls:
            raise ValueError(f"Duplicate bean name {name!r}")
        _REGISTRY[name] = cls
        cls.__bean_name__ = name
        return cls

    return deco


def bean_name(obj_or_cls) -> str:
    cls = obj_or_cls if isinstance(obj_or_cls, type) else type(obj_or_cls)
    try:
        return cls.__bean_name__
    except AttributeError:
        raise ValueError(f"{cls.__name__} is not a registered bean") from None


def to_jsonable(obj: Any) -> Any:
    """Recursively convert beans/enums/containers to plain JSON values."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {_TYPE_KEY: bean_name(obj)}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            out[f.name] = to_jsonable(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): to_jsonable(v) for k, v in obj.items()}
    raise TypeError(f"Cannot serialize {type(obj).__name__}: {obj!r}")


def from_jsonable(data: Any) -> Any:
    """Inverse of :func:`to_jsonable`; rebuilds beans from ``@type`` tags."""
    if isinstance(data, dict):
        if _TYPE_KEY in data:
            d = dict(data)
            name = d.pop(_TYPE_KEY)
            try:
                cls = _REGISTRY[name]
            except KeyError:
                raise ValueError(f"Unknown bean type {name!r}") from None
            field_types = {f.name: f.type for f in dataclasses.fields(cls)}
            kwargs = {}
            for k, v in d.items():
                if k not in field_types:
                    continue  # forward-compat: ignore unknown fields
                kwargs[k] = from_jsonable(v)
            obj = cls(**kwargs)
            return _coerce_enums(obj)
        return {k: from_jsonable(v) for k, v in data.items()}
    if isinstance(data, list):
        return [from_jsonable(v) for v in data]
    return data


_HINTS_CACHE: dict[type, dict] = {}


def enum_field_type(cls: type, field_name: str):
    """The Enum type a dataclass field is declared with (unwrapping
    Optional/union hints), or None."""
    import typing
    import types as _types

    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = typing.get_type_hints(cls)
        _HINTS_CACHE[cls] = hints
    t = hints.get(field_name)
    if typing.get_origin(t) in (typing.Union, _types.UnionType):
        args = [a for a in typing.get_args(t) if a is not type(None)]
        enum_args = [
            a for a in args if isinstance(a, type) and issubclass(a, enum.Enum)
        ]
        t = enum_args[0] if enum_args else None
    if isinstance(t, type) and issubclass(t, enum.Enum):
        return t
    return None


def coerce_enum_value(cls: type, field_name: str, value):
    """Coerce a string into the field's Enum member, accepting either
    the member NAME ("LBFGS") or its wire value ("lbfgs") — shared by
    JSON deserialization and the fluent Builder setters."""
    t = enum_field_type(cls, field_name)
    if t is not None and isinstance(value, str) and not isinstance(value, t):
        try:
            return t[value.upper()]
        except KeyError:
            return t(value)
    return value


def _coerce_enums(obj):
    """Coerce string field values back into Enum members where the dataclass
    declared an Enum type (JSON carries only the value)."""
    cls = type(obj)
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if not isinstance(v, str):
            continue
        coerced = coerce_enum_value(cls, f.name, v)
        if coerced is not v:
            object.__setattr__(obj, f.name, coerced)
    return obj


def to_json(obj: Any, indent: int | None = 2) -> str:
    return json.dumps(to_jsonable(obj), indent=indent)


def from_json(s: str) -> Any:
    return from_jsonable(json.loads(s))
