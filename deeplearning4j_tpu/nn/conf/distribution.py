"""Weight-init distributions as config beans.

Mirror of the reference's ``nn/conf/distribution`` beans backing
``WeightInit.DISTRIBUTION`` (reference nn/weights/WeightInitUtil.java uses
``Nd4j.getDistributions()``). Sampling here is a stateless ``jax.random``
draw from a threaded key — the TPU-native replacement for ND4J's stateful
device RNG (SURVEY.md §2.9 RNG row).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.serde import register_bean


@register_bean("NormalDistribution")
@dataclasses.dataclass
class NormalDistribution:
    mean: float = 0.0
    std: float = 1.0

    def sample(self, key, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.normal(key, shape, dtype)


@register_bean("UniformDistribution")
@dataclasses.dataclass
class UniformDistribution:
    lower: float = -1.0
    upper: float = 1.0

    def sample(self, key, shape, dtype=jnp.float32):
        return jax.random.uniform(
            key, shape, dtype, minval=self.lower, maxval=self.upper
        )


@register_bean("BinomialDistribution")
@dataclasses.dataclass
class BinomialDistribution:
    number_of_trials: int = 1
    probability_of_success: float = 0.5

    def sample(self, key, shape, dtype=jnp.float32):
        draws = jax.random.bernoulli(
            key,
            self.probability_of_success,
            (self.number_of_trials,) + tuple(shape),
        )
        return jnp.sum(draws, axis=0).astype(dtype)


Distribution = NormalDistribution | UniformDistribution | BinomialDistribution
