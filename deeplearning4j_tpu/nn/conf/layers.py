"""Layer configuration beans.

Mirror of reference nn/conf/layers/*.java — one bean per layer type, all 15
JSON subtypes from the reference registry (nn/conf/layers/Layer.java:43-56):
AutoEncoder, ConvolutionLayer, ImageLSTM, GravesLSTM, GravesBidirectionalLSTM,
GRU, OutputLayer, RnnOutputLayer, RBM, DenseLayer, RecursiveAutoEncoder,
SubsamplingLayer, LocalResponseNormalization, EmbeddingLayer,
BatchNormalization.

Hierarchy mirrors the reference (FeedForwardLayer <- BasePretrainNetwork /
BaseOutputLayer / BaseRecurrentLayer). Every hyperparameter field defaulting
to ``None`` inherits the global value from :class:`NeuralNetConfiguration`
(the reference's layer-over-global override semantics,
nn/conf/NeuralNetConfiguration.java:286-628).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence

from deeplearning4j_tpu.nn.conf.enums import (
    GradientNormalization,
    Updater,
    WeightInit,
)
from deeplearning4j_tpu.nn.conf.distribution import (
    BinomialDistribution,
    NormalDistribution,
    UniformDistribution,
)
from deeplearning4j_tpu.nn.conf.serde import register_bean
from deeplearning4j_tpu.ops.losses import LossFunction

Distribution = NormalDistribution | UniformDistribution | BinomialDistribution


@dataclasses.dataclass
class Layer:
    """Abstract layer bean (reference nn/conf/layers/Layer.java:60).

    ``None`` means "inherit from the enclosing NeuralNetConfiguration".
    """

    activation: Optional[str] = None
    weight_init: Optional[WeightInit] = None
    dist: Optional[Distribution] = None
    bias_init: Optional[float] = None
    dropout: Optional[float] = None
    learning_rate: Optional[float] = None
    momentum: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    updater: Optional[Updater] = None
    rho: Optional[float] = None
    rms_decay: Optional[float] = None
    adam_mean_decay: Optional[float] = None
    adam_var_decay: Optional[float] = None
    gradient_normalization: Optional[GradientNormalization] = None
    gradient_normalization_threshold: Optional[float] = None

    def num_params(self) -> str:
        raise NotImplementedError


@dataclasses.dataclass
class FeedForwardLayer(Layer):
    """Reference nn/conf/layers/FeedForwardLayer.java:11."""

    n_in: int = 0
    n_out: int = 0


@register_bean("DenseLayer")
@dataclasses.dataclass
class DenseLayer(FeedForwardLayer):
    pass


@dataclasses.dataclass
class BasePretrainNetwork(FeedForwardLayer):
    """Reference nn/conf/layers/BasePretrainNetwork.java."""

    loss_function: LossFunction = LossFunction.RECONSTRUCTION_CROSSENTROPY
    visible_bias_init: float = 0.0


@register_bean("AutoEncoder")
@dataclasses.dataclass
class AutoEncoder(BasePretrainNetwork):
    corruption_level: float = 0.3
    sparsity: float = 0.0


@register_bean("RecursiveAutoEncoder")
@dataclasses.dataclass
class RecursiveAutoEncoder(BasePretrainNetwork):
    pass


class HiddenUnit(str, enum.Enum):
    BINARY = "binary"
    GAUSSIAN = "gaussian"
    RECTIFIED = "rectified"
    SOFTMAX = "softmax"


class VisibleUnit(str, enum.Enum):
    BINARY = "binary"
    GAUSSIAN = "gaussian"
    LINEAR = "linear"
    SOFTMAX = "softmax"


@register_bean("RBM")
@dataclasses.dataclass
class RBM(BasePretrainNetwork):
    """Restricted Boltzmann machine (reference nn/conf/layers/RBM.java;
    runtime nn/layers/feedforward/rbm/RBM.java:110 CD-k)."""

    hidden_unit: HiddenUnit = HiddenUnit.BINARY
    visible_unit: VisibleUnit = VisibleUnit.BINARY
    k: int = 1
    sparsity: float = 0.0


@dataclasses.dataclass
class BaseOutputLayer(FeedForwardLayer):
    """Reference nn/conf/layers/BaseOutputLayer.java."""

    loss_function: LossFunction = LossFunction.NEGATIVELOGLIKELIHOOD


@register_bean("OutputLayer")
@dataclasses.dataclass
class OutputLayer(BaseOutputLayer):
    pass


@register_bean("RnnOutputLayer")
@dataclasses.dataclass
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep output layer for [N, C, T] activations
    (reference nn/conf/layers/RnnOutputLayer.java)."""


@dataclasses.dataclass
class BaseRecurrentLayer(FeedForwardLayer):
    """Reference nn/conf/layers/BaseRecurrentLayer.java.

    ``ring_axis``: when set and the layer runs inside a
    sequence-parallel ``shard_map`` over that mesh axis
    (``ParallelTrainer(sp_axis=...)``), the time dimension is sharded:
    attention cores run the ring/Ulysses schedule and scan recurrences
    (LSTM/GRU) run as a distributed ``sp_scan`` whose carry hops
    device-to-device — exact full BPTT with O(T/P) activation memory
    per device (the reference's only long-sequence device was
    TRUNCATED BPTT; SURVEY.md §5.7)."""

    ring_axis: "str | None" = None


@register_bean("GravesLSTM")
@dataclasses.dataclass
class GravesLSTM(BaseRecurrentLayer):
    """LSTM with peepholes per Graves (2013) (reference
    nn/conf/layers/GravesLSTM.java; runtime nn/layers/recurrent/
    LSTMHelpers.java:147 — here a ``lax.scan`` over time)."""

    forget_gate_bias_init: float = 1.0


@register_bean("GravesBidirectionalLSTM")
@dataclasses.dataclass
class GravesBidirectionalLSTM(BaseRecurrentLayer):
    forget_gate_bias_init: float = 1.0


@register_bean("GRU")
@dataclasses.dataclass
class GRU(BaseRecurrentLayer):
    pass


@register_bean("ImageLSTM")
@dataclasses.dataclass
class ImageLSTM(BaseRecurrentLayer):
    """Karpathy-style image-captioning LSTM (reference nn/conf/layers/
    ImageLSTM.java + nn/layers/recurrent/ImageLSTM.java): time step 0 is
    the image embedding, the remaining steps are word embeddings; the
    decoder head drops the image step. ``n_hidden`` is the LSTM cell
    width — the reference hard-codes 8 with a TODO to make it an
    attribute (ImageLSTMParamInitializer.java:52); here it is one.
    ``n_in`` is the embedding width, ``n_out`` the decoder (vocabulary)
    width."""

    n_hidden: int = 8


@register_bean("EmbeddingLayer")
@dataclasses.dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Index -> dense row lookup (reference nn/conf/layers/EmbeddingLayer.java).
    On TPU this is a one-hot matmul / ``take`` that XLA lowers to a gather."""


@register_bean("ConvolutionLayer")
@dataclasses.dataclass
class ConvolutionLayer(FeedForwardLayer):
    """2-D convolution (reference nn/conf/layers/ConvolutionLayer.java).

    The reference computes conv as im2col + GEMM
    (nn/layers/convolution/ConvolutionLayer.java:135); here the runtime uses
    ``lax.conv_general_dilated`` which XLA tiles directly onto the MXU.
    ``n_in``/``n_out`` are channel counts (set by shape inference).
    """

    kernel_size: Sequence[int] = (5, 5)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)


class PoolingType(str, enum.Enum):
    MAX = "max"
    AVG = "avg"
    SUM = "sum"


@register_bean("SubsamplingLayer")
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """Spatial pooling (reference nn/conf/layers/SubsamplingLayer.java;
    runtime nn/layers/convolution/subsampling/SubsamplingLayer.java).
    Parameter-free; runtime is ``lax.reduce_window``."""

    pooling_type: PoolingType = PoolingType.MAX
    kernel_size: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)
    padding: Sequence[int] = (0, 0)


@register_bean("LocalResponseNormalization")
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """Across-channel LRN (reference nn/conf/layers/
    LocalResponseNormalization.java)."""

    n: float = 5.0
    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75


@register_bean("LayerNormalization")
@dataclasses.dataclass
class LayerNormalization(FeedForwardLayer):
    """Per-example LayerNorm over the channel axis (TPU-native addition
    — the reference's only normalizations are batch-level
    BatchNormalization.java and LRN; transformer stacks need the
    batch-independent variant). Works on [N, C] and [N, C, T]
    activations; ``n_in == n_out`` (a pure normalizer). The standard
    final-norm for pre-LN transformer stacks: without it the residual
    stream reaches the output head at depth-growing magnitude (measured:
    width-1024 x 8 init loss 9.1 vs ln V = 4.16 — BENCHMARKS.md
    flagship section)."""

    eps: float = 1e-5


@register_bean("BatchNormalization")
@dataclasses.dataclass
class BatchNormalization(FeedForwardLayer):
    """Batch normalization (reference nn/conf/layers/BatchNormalization.java;
    runtime nn/layers/normalization/BatchNormalization.java). Running
    mean/var live in the network's mutable-state pytree, threaded
    functionally through apply()."""

    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    lock_gamma_beta: bool = False


# Layer kinds that consume/produce [N, C, T] time series. Matching on the
# base classes keeps extensions (e.g. MultiHeadSelfAttention) covered.
RECURRENT_LAYER_TYPES = (
    BaseRecurrentLayer,
    RnnOutputLayer,
)

# Layer kinds that operate on [N, C, H, W] images.
CONVOLUTIONAL_LAYER_TYPES = (ConvolutionLayer, SubsamplingLayer,
                             LocalResponseNormalization)

# Pretrainable layer kinds (greedy layer-wise pretraining, reference
# MultiLayerNetwork.pretrain :150).
PRETRAIN_LAYER_TYPES = (RBM, AutoEncoder, RecursiveAutoEncoder)
