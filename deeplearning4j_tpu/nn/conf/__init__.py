"""Configuration system: JSON-serializable beans + fluent builders.

Mirror of reference nn/conf (NeuralNetConfiguration.java:52,
MultiLayerConfiguration.java, nn/conf/layers/*.java). Configurations are
frozen-ish dataclasses with polymorphic JSON serde; the JSON is the wire
format for distributed training exactly as in the reference
(SparkDl4jMultiLayer ships conf.toJson() to executors, reference
spark/.../SparkDl4jMultiLayer.java:319).
"""

from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf import layers
from deeplearning4j_tpu.nn.conf import preprocessors
from deeplearning4j_tpu.nn.conf.enums import (
    BackpropType,
    GradientNormalization,
    OptimizationAlgorithm,
    Updater,
)
