"""Input preprocessors: shape adapters between heterogeneous layers.

Mirror of reference nn/conf/preprocessor/*.java (13 beans, applied in
MultiLayerNetwork.calcBackpropGradients :1229-1252). In the reference each
preprocessor implements both ``preProcess`` and ``backprop`` (the reshape
adjoint); here only the forward reshape is written — the backward pass falls
out of ``jax.grad`` over the traced step function.

Layout conventions (same as reference): feed-forward [N, C]; CNN
[N, C, H, W]; RNN [N, C, T].
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.serde import register_bean

Array = jax.Array


@dataclasses.dataclass
class InputPreProcessor:
    def pre_process(self, x: Array, rng: Optional[Array] = None) -> Array:
        raise NotImplementedError


@register_bean("CnnToFeedForwardPreProcessor")
@dataclasses.dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def pre_process(self, x, rng=None):
        return x.reshape(x.shape[0], -1)


@register_bean("FeedForwardToCnnPreProcessor")
@dataclasses.dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    input_height: int = 0
    input_width: int = 0
    num_channels: int = 1

    def pre_process(self, x, rng=None):
        if x.ndim == 4:
            return x
        return x.reshape(
            x.shape[0], self.num_channels, self.input_height, self.input_width
        )


@register_bean("RnnToFeedForwardPreProcessor")
@dataclasses.dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[N, C, T] -> [N*T, C] (reference RnnToFeedForwardPreProcessor)."""

    def pre_process(self, x, rng=None):
        return jnp.transpose(x, (0, 2, 1)).reshape(-1, x.shape[1])


@register_bean("FeedForwardToRnnPreProcessor")
@dataclasses.dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[N*T, C] -> [N, C, T]; needs the minibatch size captured at trace
    time via ``miniBatchSize`` (reference passes it through preProcess)."""

    minibatch_size: int = 0

    def pre_process(self, x, rng=None):
        n = self.minibatch_size or 1
        t = x.shape[0] // n
        return jnp.transpose(x.reshape(n, t, x.shape[1]), (0, 2, 1))


@register_bean("CnnToRnnPreProcessor")
@dataclasses.dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0
    minibatch_size: int = 0

    def pre_process(self, x, rng=None):
        # [N*T, C, H, W] -> [N, C*H*W, T]
        n = self.minibatch_size or 1
        t = x.shape[0] // n
        flat = x.reshape(n, t, -1)
        return jnp.transpose(flat, (0, 2, 1))


@register_bean("RnnToCnnPreProcessor")
@dataclasses.dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def pre_process(self, x, rng=None):
        # [N, C*H*W, T] -> [N*T, C, H, W]
        n, _, t = x.shape
        xt = jnp.transpose(x, (0, 2, 1)).reshape(
            n * t, self.num_channels, self.input_height, self.input_width
        )
        return xt


@register_bean("ReshapePreProcessor")
@dataclasses.dataclass
class ReshapePreProcessor(InputPreProcessor):
    shape: Sequence[int] = ()

    def pre_process(self, x, rng=None):
        return x.reshape(tuple(self.shape))


@register_bean("ZeroMeanPrePreProcessor")
@dataclasses.dataclass
class ZeroMeanPrePreProcessor(InputPreProcessor):
    def pre_process(self, x, rng=None):
        return x - jnp.mean(x, axis=0, keepdims=True)


@register_bean("ZeroMeanAndUnitVariancePreProcessor")
@dataclasses.dataclass
class ZeroMeanAndUnitVariancePreProcessor(InputPreProcessor):
    def pre_process(self, x, rng=None):
        mu = jnp.mean(x, axis=0, keepdims=True)
        sd = jnp.std(x, axis=0, keepdims=True) + 1e-8
        return (x - mu) / sd


@register_bean("UnitVarianceProcessor")
@dataclasses.dataclass
class UnitVarianceProcessor(InputPreProcessor):
    def pre_process(self, x, rng=None):
        return x / (jnp.std(x, axis=0, keepdims=True) + 1e-8)


@register_bean("BinomialSamplingPreProcessor")
@dataclasses.dataclass
class BinomialSamplingPreProcessor(InputPreProcessor):
    """Bernoulli-sample the input probabilities (reference
    BinomialSamplingPreProcessor); identity when no rng key is threaded."""

    def pre_process(self, x, rng=None):
        if rng is None:
            return x
        return jax.random.bernoulli(rng, x).astype(x.dtype)


@register_bean("ComposableInputPreProcessor")
@dataclasses.dataclass
class ComposableInputPreProcessor(InputPreProcessor):
    components: Sequence[InputPreProcessor] = ()

    def pre_process(self, x, rng=None):
        for p in self.components:
            x = p.pre_process(x, rng)
        return x
