"""ComputationGraph configuration: DAG of layer + structural vertices.

Mirror of reference nn/conf/ComputationGraphConfiguration.java:56 and the
``NeuralNetConfiguration.Builder.graphBuilder()`` flow; vertex beans mirror
nn/conf/graph/*.java and the runtime vertices nn/graph/vertex/impl/
{LayerVertex,MergeVertex,ElementWiseVertex,SubsetVertex,PreprocessorVertex}
.java + rnn/{LastTimeStepVertex,DuplicateToTimeSeriesVertex}.java.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.enums import BackpropType
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.preprocessors import InputPreProcessor
from deeplearning4j_tpu.nn.conf.serde import (
    from_json as _from_json,
    register_bean,
    to_json as _to_json,
)


# ----------------------------------------------------------------------
# Vertex beans
# ----------------------------------------------------------------------
@dataclasses.dataclass
class GraphVertex:
    pass


@register_bean("LayerVertex")
@dataclasses.dataclass
class LayerVertex(GraphVertex):
    conf: Optional[NeuralNetConfiguration] = None
    preprocessor: Optional[InputPreProcessor] = None


@register_bean("MergeVertex")
@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concatenate inputs along the feature axis (axis 1)."""


class ElementWiseOp(str, enum.Enum):
    ADD = "add"
    SUBTRACT = "subtract"
    PRODUCT = "product"
    AVERAGE = "average"
    MAX = "max"


@register_bean("ElementWiseVertex")
@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    op: ElementWiseOp = ElementWiseOp.ADD


@register_bean("SubsetVertex")
@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from, to] inclusive (reference SubsetVertex)."""

    from_index: int = 0
    to_index: int = 0


@register_bean("PreprocessorVertex")
@dataclasses.dataclass
class PreprocessorVertex(GraphVertex):
    preprocessor: Optional[InputPreProcessor] = None


@register_bean("LastTimeStepVertex")
@dataclasses.dataclass
class LastTimeStepVertex(GraphVertex):
    """[N, C, T] -> [N, C] at the last (mask-aware) timestep. ``mask_input``
    names the network input whose mask selects the step."""

    mask_input: Optional[str] = None


@register_bean("DuplicateToTimeSeriesVertex")
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[N, C] -> [N, C, T], T taken from the named reference input."""

    reference_input: Optional[str] = None


@register_bean("InputVertexMarker")
@dataclasses.dataclass
class InputVertexMarker(GraphVertex):
    """Marks a network input (reference InputVertex is runtime-only)."""


# ----------------------------------------------------------------------
# Graph configuration
# ----------------------------------------------------------------------
@register_bean("ComputationGraphConfiguration")
@dataclasses.dataclass
class ComputationGraphConfiguration:
    network_inputs: List[str] = dataclasses.field(default_factory=list)
    network_outputs: List[str] = dataclasses.field(default_factory=list)
    vertices: Dict[str, GraphVertex] = dataclasses.field(default_factory=dict)
    vertex_inputs: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict
    )
    backprop: bool = True
    pretrain: bool = False
    backprop_type: BackpropType = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20

    def to_json(self) -> str:
        return _to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        obj = _from_json(s)
        if not isinstance(obj, ComputationGraphConfiguration):
            raise ValueError(
                "JSON does not encode a ComputationGraphConfiguration"
            )
        return obj

    def clone(self) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_json(self.to_json())

    # -- validation + ordering -----------------------------------------
    def topological_order(self) -> List[str]:
        """Kahn topological sort over vertices (reference
        ComputationGraph.topologicalSortOrder :593)."""
        indeg = {name: 0 for name in self.vertices}
        children: Dict[str, List[str]] = {name: [] for name in self.vertices}
        for name, inputs in self.vertex_inputs.items():
            for inp in inputs:
                if inp in self.network_inputs:
                    continue
                if inp not in self.vertices:
                    raise ValueError(
                        f"Vertex {name!r} consumes unknown input {inp!r}"
                    )
                indeg[name] += 1
                children[inp].append(name)
        queue = sorted(n for n, d in indeg.items() if d == 0)
        order: List[str] = []
        while queue:
            n = queue.pop(0)
            order.append(n)
            for ch in children[n]:
                indeg[ch] -= 1
                if indeg[ch] == 0:
                    queue.append(ch)
        if len(order) != len(self.vertices):
            raise ValueError("Graph has a cycle")
        return order

    def validate(self) -> None:
        if not self.network_inputs:
            raise ValueError("Graph has no network inputs")
        if not self.network_outputs:
            raise ValueError("Graph has no network outputs")
        for out in self.network_outputs:
            if out not in self.vertices:
                raise ValueError(f"Unknown network output {out!r}")
        for name in self.vertices:
            if name not in self.vertex_inputs or not self.vertex_inputs[name]:
                raise ValueError(f"Vertex {name!r} has no inputs")
        self.topological_order()


class GraphBuilder:
    """Reference ``ComputationGraphConfiguration.GraphBuilder`` via
    ``NeuralNetConfiguration.Builder().graphBuilder()``."""

    def __init__(self, base: NeuralNetConfiguration):
        self._base = base
        self._conf = ComputationGraphConfiguration()

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._conf.network_inputs.extend(names)
        return self

    def add_layer(
        self,
        name: str,
        layer_bean: L.Layer,
        *inputs: str,
        preprocessor: Optional[InputPreProcessor] = None,
    ) -> "GraphBuilder":
        c = self._base.clone()
        c.layer = layer_bean
        self._conf.vertices[name] = LayerVertex(conf=c, preprocessor=preprocessor)
        self._conf.vertex_inputs[name] = list(inputs)
        return self

    def add_vertex(
        self, name: str, vertex: GraphVertex, *inputs: str
    ) -> "GraphBuilder":
        self._conf.vertices[name] = vertex
        self._conf.vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._conf.network_outputs = list(names)
        return self

    def backprop(self, flag: bool) -> "GraphBuilder":
        self._conf.backprop = flag
        return self

    def pretrain(self, flag: bool) -> "GraphBuilder":
        self._conf.pretrain = flag
        return self

    def backprop_type(self, t: BackpropType) -> "GraphBuilder":
        self._conf.backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "GraphBuilder":
        self._conf.tbptt_fwd_length = n
        return self

    def t_bptt_backward_length(self, n: int) -> "GraphBuilder":
        self._conf.tbptt_bwd_length = n
        return self

    def build(self) -> ComputationGraphConfiguration:
        self._conf.validate()
        return self._conf
