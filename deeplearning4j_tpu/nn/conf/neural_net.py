"""NeuralNetConfiguration: global hyperparameters + one layer bean.

Mirror of reference nn/conf/NeuralNetConfiguration.java:52-683. The fluent
``Builder`` exposes the same knob set as the reference builder (:286-628:
activation :502, weightInit :510, learningRate :529, l1/l2 :548/:554,
dropOut :559, momentum :565, updater :580, rho/rmsDecay/adam :590-609,
gradientNormalization :618) with snake_case names.

A ``NeuralNetConfiguration`` is pure data; the runtime builds pure jitted
step functions from it (SURVEY.md §7 design inversion).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.distribution import (
    BinomialDistribution,
    NormalDistribution,
    UniformDistribution,
)
from deeplearning4j_tpu.nn.conf.enums import (
    GradientNormalization,
    OptimizationAlgorithm,
    Updater,
    WeightInit,
)
from deeplearning4j_tpu.nn.conf.serde import from_json as _from_json
from deeplearning4j_tpu.nn.conf.serde import register_bean, to_json as _to_json

Distribution = NormalDistribution | UniformDistribution | BinomialDistribution


@register_bean("NeuralNetConfiguration")
@dataclasses.dataclass
class NeuralNetConfiguration:
    layer: Optional[L.Layer] = None

    # Global hyperparameters (overridable per layer bean).
    activation: str = "sigmoid"
    weight_init: WeightInit = WeightInit.XAVIER
    dist: Optional[Distribution] = None
    bias_init: float = 0.0
    learning_rate: float = 1e-1
    learning_rate_schedule: Optional[Dict[int, float]] = None
    # Smooth lr policy (TPU-native addition; the reference only has the
    # piecewise ``learningRateAfter`` map above): "warmup_cosine" ramps
    # linearly from 0 over ``lr_warmup_steps`` then follows a cosine to
    # ``lr_min_fraction``*lr at ``lr_total_steps`` — the standard
    # schedule for transformer convergence at width >= 1024, where a
    # flat lr diverges (BENCHMARKS.md flagship section). Mutually
    # exclusive with learning_rate_schedule. jit-safe: pure jnp ops on
    # the iteration counter.
    lr_policy: Optional[str] = None
    lr_warmup_steps: int = 0
    lr_total_steps: int = 0
    lr_min_fraction: float = 0.1
    momentum: float = 0.5
    momentum_schedule: Optional[Dict[int, float]] = None
    l1: float = 0.0
    l2: float = 0.0
    use_regularization: bool = False
    dropout: float = 0.0
    use_drop_connect: bool = False
    updater: Updater = Updater.SGD
    rho: float = 0.95
    rms_decay: float = 0.95
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    epsilon: float = 1e-8
    gradient_normalization: GradientNormalization = GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0

    # Optimization loop.
    optimization_algo: OptimizationAlgorithm = (
        OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT
    )
    num_iterations: int = 1
    max_num_line_search_iterations: int = 5
    minimize: bool = True
    mini_batch: bool = True

    # Determinism / numerics (TPU-native additions).
    seed: int = 12345
    dtype: str = "float32"
    # Mixed precision: run forward/backward math in this dtype while
    # params/updater state stay in ``dtype`` (f32 master weights). The
    # TPU-idiomatic setting is "bfloat16" — matmuls/convs hit the MXU at
    # 2x f32 rate; grads accumulate in f32 through the cast transpose.
    compute_dtype: Optional[str] = None

    # ------------------------------------------------------------------
    # Per-layer hyperparameter resolution (layer override -> global).
    # ------------------------------------------------------------------
    def resolved(self, name: str):
        """Value of hyperparameter ``name`` for this conf's layer, applying
        the reference's layer-over-global override rule."""
        if self.layer is not None:
            v = getattr(self.layer, name, None)
            if v is not None:
                return v
        return getattr(self, name)

    # ------------------------------------------------------------------
    # JSON serde (reference toJson :96 / fromJson :110 on the multi-layer
    # conf; single-conf serde also exists there).
    # ------------------------------------------------------------------
    def to_json(self) -> str:
        return _to_json(self)

    @staticmethod
    def from_json(s: str) -> "NeuralNetConfiguration":
        obj = _from_json(s)
        if not isinstance(obj, NeuralNetConfiguration):
            raise ValueError("JSON does not encode a NeuralNetConfiguration")
        return obj

    def clone(self) -> "NeuralNetConfiguration":
        return dataclasses.replace(
            self, layer=dataclasses.replace(self.layer) if self.layer else None
        )

    # ------------------------------------------------------------------
    # Fluent builder (reference NeuralNetConfiguration.Builder :286).
    # ------------------------------------------------------------------
    class Builder:
        def __init__(self):
            self._conf = NeuralNetConfiguration()

        def __getattr__(self, name):
            # Generic chained setter for any dataclass field.
            fields = {f.name for f in dataclasses.fields(NeuralNetConfiguration)}
            if name in fields:

                def setter(value):
                    # Accept the enum member or its name/value as a
                    # string ("LBFGS", "lbfgs") — the tolerance the
                    # reference gets from Jackson enum deserialization.
                    from deeplearning4j_tpu.nn.conf.serde import (
                        coerce_enum_value,
                    )

                    setattr(self._conf, name, coerce_enum_value(
                        NeuralNetConfiguration, name, value))
                    return self

                return setter
            raise AttributeError(name)

        # Named setters with semantics beyond plain assignment.
        def drop_out(self, p: float):
            self._conf.dropout = p
            return self

        def regularization(self, use: bool):
            self._conf.use_regularization = use
            return self

        def iterations(self, n: int):
            self._conf.num_iterations = n
            return self

        def layer(self, layer_bean: L.Layer):
            self._conf.layer = layer_bean
            return self

        def list(self):
            """Start a multi-layer list builder (reference ``.list(n)``)."""
            from deeplearning4j_tpu.nn.conf.multi_layer import ListBuilder

            return ListBuilder(self._conf)

        def graph_builder(self):
            """Start a ComputationGraph configuration builder
            (reference ``.graphBuilder()``)."""
            from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder

            return GraphBuilder(self._conf)

        def build(self) -> "NeuralNetConfiguration":
            return self._conf
