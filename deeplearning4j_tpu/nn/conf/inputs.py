"""Input types + automatic shape inference / preprocessor insertion.

Mirror of reference nn/conf/inputs/InputType.java and
nn/conf/layers/setup/ConvolutionLayerSetup.java:36: walk the layer list,
compute each layer's input/output type, fill in ``n_in``/``n_out`` channel
and size fields, and insert the right InputPreProcessor at every
representation boundary (CNN<->FF<->RNN).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    RnnToCnnPreProcessor,
    RnnToFeedForwardPreProcessor,
)
from deeplearning4j_tpu.nn.conf.serde import register_bean


@dataclasses.dataclass
class InputType:
    @staticmethod
    def feed_forward(size: int) -> "InputTypeFeedForward":
        return InputTypeFeedForward(size=size)

    @staticmethod
    def recurrent(size: int) -> "InputTypeRecurrent":
        return InputTypeRecurrent(size=size)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputTypeConvolutional":
        return InputTypeConvolutional(
            height=height, width=width, channels=channels
        )


@register_bean("InputTypeFeedForward")
@dataclasses.dataclass
class InputTypeFeedForward(InputType):
    size: int = 0


@register_bean("InputTypeRecurrent")
@dataclasses.dataclass
class InputTypeRecurrent(InputType):
    size: int = 0


@register_bean("InputTypeConvolutional")
@dataclasses.dataclass
class InputTypeConvolutional(InputType):
    height: int = 0
    width: int = 0
    channels: int = 1


def _conv_out(size: int, k: int, s: int, p: int) -> int:
    out = (size + 2 * p - k) // s + 1
    if out <= 0:
        raise ValueError(
            f"Invalid conv/pool geometry: size={size} kernel={k} "
            f"stride={s} pad={p}"
        )
    return out


def setup_shapes(conf, input_type: InputType) -> None:
    """Infer n_in/n_out for every layer of a MultiLayerConfiguration and
    insert preprocessors at representation boundaries (reference
    ConvolutionLayerSetup). Mutates ``conf`` in place."""
    cur = input_type
    for i, c in enumerate(conf.confs):
        lc = c.layer
        pp = conf.preprocessor_for(i)
        if pp is None:
            pp = _boundary_preprocessor(cur, lc)
            if pp is not None:
                conf.input_preprocessors[str(i)] = pp
        cur = _apply_preprocessor_type(cur, pp)
        cur = _fill_and_advance(lc, cur)


def _boundary_preprocessor(cur: InputType, lc: L.Layer):
    if isinstance(lc, (L.BatchNormalization, L.LayerNormalization)):
        return None  # shape-preserving in every representation
    wants_cnn = isinstance(lc, (L.ConvolutionLayer, L.SubsamplingLayer,
                                L.LocalResponseNormalization))
    wants_rnn = isinstance(lc, L.RECURRENT_LAYER_TYPES)
    if isinstance(cur, InputTypeConvolutional):
        if wants_cnn:
            return None
        if wants_rnn:
            return CnnToRnnPreProcessor(
                cur.height, cur.width, cur.channels
            )
        return CnnToFeedForwardPreProcessor(
            cur.height, cur.width, cur.channels
        )
    if isinstance(cur, InputTypeRecurrent):
        if wants_rnn:
            return None
        if wants_cnn:
            raise ValueError(
                "RNN -> CNN requires an explicit RnnToCnnPreProcessor with "
                "image geometry"
            )
        return RnnToFeedForwardPreProcessor()
    # FeedForward input
    if wants_cnn:
        raise ValueError(
            "FF -> CNN requires an explicit FeedForwardToCnnPreProcessor "
            "with image geometry"
        )
    if wants_rnn:
        return FeedForwardToRnnPreProcessor()
    return None


def _apply_preprocessor_type(cur: InputType, pp) -> InputType:
    if pp is None:
        return cur
    if isinstance(pp, CnnToFeedForwardPreProcessor):
        return InputType.feed_forward(
            pp.input_height * pp.input_width * pp.num_channels
            if pp.input_height
            else cur.height * cur.width * cur.channels
        )
    if isinstance(pp, CnnToRnnPreProcessor):
        return InputType.recurrent(
            pp.input_height * pp.input_width * pp.num_channels
        )
    if isinstance(pp, RnnToFeedForwardPreProcessor):
        return InputType.feed_forward(cur.size)
    if isinstance(pp, FeedForwardToRnnPreProcessor):
        return InputType.recurrent(cur.size)
    if isinstance(pp, FeedForwardToCnnPreProcessor):
        return InputType.convolutional(
            pp.input_height, pp.input_width, pp.num_channels
        )
    if isinstance(pp, RnnToCnnPreProcessor):
        return InputType.convolutional(
            pp.input_height, pp.input_width, pp.num_channels
        )
    return cur


def _fill_and_advance(lc: L.Layer, cur: InputType) -> InputType:
    """Set lc.n_in from ``cur``, return the layer's output type."""
    if isinstance(lc, L.ConvolutionLayer):
        if not isinstance(cur, InputTypeConvolutional):
            raise ValueError("ConvolutionLayer needs convolutional input")
        if not lc.n_in:
            lc.n_in = cur.channels
        kh, kw = lc.kernel_size
        sh, sw = lc.stride
        ph, pw = lc.padding
        return InputType.convolutional(
            _conv_out(cur.height, kh, sh, ph),
            _conv_out(cur.width, kw, sw, pw),
            lc.n_out,
        )
    if isinstance(lc, L.SubsamplingLayer):
        if not isinstance(cur, InputTypeConvolutional):
            raise ValueError("SubsamplingLayer needs convolutional input")
        kh, kw = lc.kernel_size
        sh, sw = lc.stride
        ph, pw = lc.padding
        return InputType.convolutional(
            _conv_out(cur.height, kh, sh, ph),
            _conv_out(cur.width, kw, sw, pw),
            cur.channels,
        )
    if isinstance(lc, L.LocalResponseNormalization):
        return cur
    if isinstance(lc, (L.BatchNormalization, L.LayerNormalization)):
        # Pure normalizers: representation-preserving (the input type
        # passes through unchanged — no FF coercion of recurrent/CNN
        # activations).
        if isinstance(cur, InputTypeConvolutional):
            if not lc.n_in:
                lc.n_in = cur.channels
        elif isinstance(cur, (InputTypeFeedForward, InputTypeRecurrent)):
            if not lc.n_in:
                lc.n_in = cur.size
        if not lc.n_out:
            lc.n_out = lc.n_in
        return cur
    if isinstance(lc, L.RECURRENT_LAYER_TYPES):
        if not isinstance(cur, InputTypeRecurrent):
            raise ValueError(f"{type(lc).__name__} needs recurrent input")
        if not lc.n_in:
            lc.n_in = cur.size
        return InputType.recurrent(lc.n_out)
    if isinstance(lc, L.FeedForwardLayer):
        size = cur.size if isinstance(
            cur, (InputTypeFeedForward, InputTypeRecurrent)
        ) else cur.height * cur.width * cur.channels
        if not lc.n_in:
            lc.n_in = size
        return InputType.feed_forward(lc.n_out)
    # Parameter-free layers keep the type.
    return cur
