"""Core configuration enums.

Mirrors of: reference nn/conf/Updater.java, nn/weights/WeightInit.java:37,
nn/api/OptimizationAlgorithm.java:26, nn/conf/BackpropType.java,
nn/conf/GradientNormalization.java, and nn/api/Layer.java ``Type``.
"""

from __future__ import annotations

import enum


class Updater(str, enum.Enum):
    SGD = "sgd"
    ADAM = "adam"
    ADADELTA = "adadelta"
    NESTEROVS = "nesterovs"
    ADAGRAD = "adagrad"
    RMSPROP = "rmsprop"
    NONE = "none"
    CUSTOM = "custom"


class WeightInit(str, enum.Enum):
    DISTRIBUTION = "distribution"
    NORMALIZED = "normalized"
    SIZE = "size"
    UNIFORM = "uniform"
    VI = "vi"
    ZERO = "zero"
    XAVIER = "xavier"
    RELU = "relu"


class OptimizationAlgorithm(str, enum.Enum):
    STOCHASTIC_GRADIENT_DESCENT = "stochastic_gradient_descent"
    LINE_GRADIENT_DESCENT = "line_gradient_descent"
    CONJUGATE_GRADIENT = "conjugate_gradient"
    LBFGS = "lbfgs"
    HESSIAN_FREE = "hessian_free"


class BackpropType(str, enum.Enum):
    STANDARD = "standard"
    TRUNCATED_BPTT = "truncated_bptt"


class GradientNormalization(str, enum.Enum):
    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renormalize_l2_per_layer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "renormalize_l2_per_param_type"
    CLIP_ELEMENT_WISE_ABSOLUTE_VALUE = "clip_element_wise_absolute_value"
    CLIP_L2_PER_LAYER = "clip_l2_per_layer"
    CLIP_L2_PER_PARAM_TYPE = "clip_l2_per_param_type"


class LayerType(str, enum.Enum):
    """Reference nn/api/Layer.java ``Type`` enum."""

    FEED_FORWARD = "feed_forward"
    RECURRENT = "recurrent"
    CONVOLUTIONAL = "convolutional"
    SUBSAMPLING = "subsampling"
    RECURSIVE = "recursive"
    MULTILAYER = "multilayer"
    NORMALIZATION = "normalization"
