"""MultiLayerConfiguration + ListBuilder.

Mirror of reference nn/conf/MultiLayerConfiguration.java (345 LoC; toJson :96,
fromJson :110) and the ``NeuralNetConfiguration.Builder.list()`` ->
``ListBuilder`` flow the reference uses to assemble stacked networks.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.enums import BackpropType
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.preprocessors import InputPreProcessor
from deeplearning4j_tpu.nn.conf.serde import (
    from_json as _from_json,
    register_bean,
    to_json as _to_json,
)


@register_bean("MultiLayerConfiguration")
@dataclasses.dataclass
class MultiLayerConfiguration:
    confs: List[NeuralNetConfiguration] = dataclasses.field(default_factory=list)
    input_preprocessors: Dict[str, InputPreProcessor] = dataclasses.field(
        default_factory=dict
    )
    backprop: bool = True
    pretrain: bool = False
    backprop_type: BackpropType = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20
    # Rematerialize per-layer activations in backward (jax.checkpoint):
    # trades recompute FLOPs for HBM — the TPU answer to deep stacks /
    # long sequences whose activation footprint exceeds HBM.
    remat: bool = False

    def __post_init__(self):
        # JSON object keys are strings; keep them that way internally and
        # expose int-keyed access via preprocessor_for().
        self.input_preprocessors = {
            str(k): v for k, v in self.input_preprocessors.items()
        }

    def conf(self, i: int) -> NeuralNetConfiguration:
        return self.confs[i]

    def preprocessor_for(self, i: int) -> Optional[InputPreProcessor]:
        return self.input_preprocessors.get(str(i))

    @property
    def seed(self) -> int:
        return self.confs[0].seed if self.confs else 12345

    @property
    def dtype(self) -> str:
        return self.confs[0].dtype if self.confs else "float32"

    @property
    def compute_dtype(self):
        return self.confs[0].compute_dtype if self.confs else None

    def to_json(self) -> str:
        return _to_json(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        obj = _from_json(s)
        if not isinstance(obj, MultiLayerConfiguration):
            raise ValueError("JSON does not encode a MultiLayerConfiguration")
        return obj

    def clone(self) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_json(self.to_json())


class ListBuilder:
    """Reference ``NeuralNetConfiguration.ListBuilder``: per-index layer
    beans + preprocessors + backprop/pretrain flags."""

    def __init__(self, base: NeuralNetConfiguration):
        self._base = base
        self._layers: Dict[int, L.Layer] = {}
        self._preprocessors: Dict[int, InputPreProcessor] = {}
        self._backprop = True
        self._pretrain = False
        self._backprop_type = BackpropType.STANDARD
        self._tbptt_fwd = 20
        self._tbptt_bwd = 20
        self._input_type = None
        self._remat = False

    def layer(self, index: int, layer_bean: L.Layer) -> "ListBuilder":
        self._layers[index] = layer_bean
        return self

    def input_pre_processor(
        self, index: int, pp: InputPreProcessor
    ) -> "ListBuilder":
        self._preprocessors[index] = pp
        return self

    def backprop(self, flag: bool) -> "ListBuilder":
        self._backprop = flag
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def backprop_type(self, t: BackpropType) -> "ListBuilder":
        self._backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "ListBuilder":
        self._tbptt_fwd = n
        return self

    def t_bptt_backward_length(self, n: int) -> "ListBuilder":
        self._tbptt_bwd = n
        return self

    def remat(self, flag: bool = True) -> "ListBuilder":
        self._remat = flag
        return self

    def set_input_type(self, input_type) -> "ListBuilder":
        """Enable shape inference + automatic preprocessor insertion
        (reference ConvolutionLayerSetup / setInputType)."""
        self._input_type = input_type
        return self

    def cnn_input_size(self, height: int, width: int, channels: int) -> "ListBuilder":
        from deeplearning4j_tpu.nn.conf.inputs import InputType

        return self.set_input_type(
            InputType.convolutional(height, width, channels)
        )

    def build(self) -> MultiLayerConfiguration:
        if not self._layers:
            raise ValueError("No layers configured")
        n = max(self._layers) + 1
        missing = [i for i in range(n) if i not in self._layers]
        if missing:
            raise ValueError(f"Missing layer indices: {missing}")
        confs = []
        for i in range(n):
            c = self._base.clone()
            # Copy the bean so shape inference never mutates caller-owned
            # objects (they may be reused across builders).
            c.layer = dataclasses.replace(self._layers[i])
            confs.append(c)
        conf = MultiLayerConfiguration(
            confs=confs,
            input_preprocessors={str(k): v for k, v in self._preprocessors.items()},
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_bwd_length=self._tbptt_bwd,
            remat=self._remat,
        )
        if self._input_type is not None:
            from deeplearning4j_tpu.nn.conf.inputs import setup_shapes

            setup_shapes(conf, self._input_type)
        return conf
