"""Weight initialization schemes.

Mirror of reference nn/weights/WeightInit.java:37 (DISTRIBUTION, NORMALIZED,
SIZE, UNIFORM, VI, ZERO, XAVIER, RELU) and WeightInitUtil. Sampling is a
stateless ``jax.random`` draw (replaces ND4J's stateful device RNG).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.enums import WeightInit

Array = jax.Array


def _fans(shape: Sequence[int]) -> tuple[int, int]:
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # Conv kernels [out_ch, in_ch, kh, kw]: receptive-field scaled fans.
    receptive = int(jnp.prod(jnp.array(shape[2:])))
    return shape[1] * receptive, shape[0] * receptive


def init_weights(
    key: Array,
    shape: Sequence[int],
    scheme: WeightInit,
    dist=None,
    dtype=jnp.float32,
) -> Array:
    """Draw one weight tensor (reference WeightInitUtil.initWeights)."""
    shape = tuple(int(s) for s in shape)
    fan_in, fan_out = _fans(shape)
    if scheme == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if scheme == WeightInit.XAVIER:
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == WeightInit.RELU:
        std = math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if scheme == WeightInit.UNIFORM:
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.VI:
        # Variance-scaled uniform over both fans (reference "VI").
        r = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, minval=-r, maxval=r)
    if scheme == WeightInit.SIZE:
        # Scaled by tensor size (legacy scheme kept for parity).
        a = 1.0 / math.sqrt(fan_in + fan_out)
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.NORMALIZED:
        return (
            jax.random.uniform(key, shape, dtype) - 0.5
        ) / float(max(fan_in, 1))
    if scheme == WeightInit.DISTRIBUTION:
        if dist is None:
            raise ValueError("WeightInit.DISTRIBUTION requires a distribution")
        return dist.sample(key, shape, dtype)
    raise ValueError(f"Unknown weight init scheme {scheme}")
