"""MultiLayerNetwork: the sequential-stack network.

Mirror of reference nn/multilayer/MultiLayerNetwork.java:67 (2,343 LoC):
init() :335, fit(DataSetIterator) :1130, feedForward :578-715, backprop
:1176, pretrain :150, doTruncatedBPTT :1262, params pack/unpack :984-1063.

TPU-native inversion (SURVEY.md §3.1 takeaway): where the reference runs
eager op-by-op INDArray dispatch with a JVM->JNI->BLAS crossing per op, here
the entire train step — forward, loss, backward (``jax.value_and_grad``),
gradient normalization, updater — is ONE jitted XLA computation, compiled
once per (shape, dtype) and cached. Backprop is never hand-written; the
per-parameter gradient map ("0_W", "1_b", ...) is recovered from the pytree
for updater/gradient-check parity.
"""

from __future__ import annotations

import functools
import io
import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.optimize.telemetry import (
    TrainTelemetry,
    batch_counts,
    grad_health,
    window_counts,
)
from deeplearning4j_tpu.nn.conf.enums import BackpropType, OptimizationAlgorithm
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.gradient import Gradient
from deeplearning4j_tpu.nn.layers import get_impl
from deeplearning4j_tpu.nn.updater.updaters import (
    make_layer_updater,
    normalize_gradients,
    resolve_lr,
)

Array = jax.Array


_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
           "float16": jnp.float16, "float64": jnp.float64}


def _dtype_of(name: str):
    if name not in _DTYPES:
        raise ValueError(
            f"unknown dtype {name!r} (dtype/compute_dtype accepts "
            f"{sorted(_DTYPES)})")
    return _DTYPES[name]


def _cast_floating(a, dtype):
    """Cast floating arrays, leave ints/bools (masks, indices) alone."""
    if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
        return a.astype(dtype)
    return a


def _resolve_compute_dtype(master_dtype, compute_dtype_name):
    """Mixed-precision compute dtype, or None when it matches master."""
    if not compute_dtype_name:
        return None
    cd = _dtype_of(compute_dtype_name)
    return cd if cd != master_dtype else None


_REGULARIZED_KEYS = ("W", "RW", "W_bwd", "RW_bwd")


def layer_reg_score(c, layer_params):
    """l1/l2 penalty of ONE layer's params — shared by the full-model
    ``_reg_score`` and PipelineTrainer's per-stage reg branches (a fix
    here must apply to both, or PP trajectories drift)."""
    if not c.use_regularization:
        return 0.0
    l1 = float(c.resolved("l1") or 0.0)
    l2 = float(c.resolved("l2") or 0.0)
    if l1 == 0.0 and l2 == 0.0:
        return 0.0
    reg = 0.0
    for name, p in layer_params.items():
        if name not in _REGULARIZED_KEYS:
            continue
        if l1:
            reg = reg + l1 * jnp.sum(jnp.abs(p))
        if l2:
            reg = reg + 0.5 * l2 * jnp.sum(p * p)
    return reg


def layer_update(c, updater, grads, upd_state, iteration, grad_scale=1.0):
    """normalize -> scale -> updater rule for ONE layer; returns
    (updates, new_state) and the caller applies ``params -= updates``.
    Shared by ``_apply_updates`` and PipelineTrainer's per-stage update
    branches.

    grad_scale=1.0 normally; dp-size under ACCUM_GRADIENT-
    without-divide (reference DIVIDE_ACCUM_GRADIENT=false: sum of
    per-worker gradients = mean times worker count). Applied AFTER
    normalization. NOTE: this computes n*normalize(mean), which matches
    the reference's sum-of-per-worker-normalized gradients exactly for
    plain SGD and whenever normalization is inactive or uniform across
    workers; with per-worker clipping that differs between shards the
    reference's sum can diverge from this global form (a documented
    deviation — the global batch here is ONE gradient, not N)."""
    g = normalize_gradients(
        c.resolved("gradient_normalization"),
        grads,
        float(c.resolved("gradient_normalization_threshold")),
    )
    g = jax.tree.map(lambda a: a * grad_scale, g)
    lr = resolve_lr(c, iteration)
    return updater.update(g, upd_state, lr, iteration)


class MultiLayerNetwork:
    """Sequential network over layer conf beans.

    Also usable as a building block the way the reference's
    MultiLayerNetwork implements ``Layer`` (nn/api/Layer.java nesting).
    """

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.params: Dict[str, Dict[str, Array]] = {}
        self.state: Dict[str, Any] = {}
        self.updater_state: Dict[str, Any] = {}
        self.iteration = 0
        self.score_value = float("nan")
        self.listeners: List = []
        # Host-side per-step phase clock (data-wait/dispatch walls,
        # throughput counts, latest gradient-health outputs) — stamped
        # by every fit path, drained by TracingIterationListener.
        self.train_telemetry = TrainTelemetry()
        self._impls = [get_impl(c.layer) for c in conf.confs]
        self._updaters = [make_layer_updater(c) for c in conf.confs]
        self._rnn_state: Dict[str, Any] = {}
        self._generate_fns: Dict[int, Any] = {}
        self._initialized = False
        # Bumped by in-place param mutation APIs (set_param) so caches
        # that mirror params (e.g. PipelineTrainer's stage-sharded
        # buffers) can detect staleness without deep comparison.
        self.params_version = 0
        self._dtype = _dtype_of(conf.dtype)
        self._compute_dtype = _resolve_compute_dtype(
            self._dtype, conf.compute_dtype)
        self._key = jax.random.key(conf.seed)

    # ------------------------------------------------------------------
    # Initialization (reference init() :335-370)
    # ------------------------------------------------------------------
    def init(self) -> "MultiLayerNetwork":
        if self._initialized:
            return self
        key = jax.random.key(self.conf.seed)
        n = len(self.conf.confs)
        keys = jax.random.split(key, n)
        for i, (c, impl) in enumerate(zip(self.conf.confs, self._impls)):
            self.params[str(i)] = impl.init(keys[i], c, self._dtype)
            st = impl.init_state(c, self._dtype)
            if st is not None:
                self.state[str(i)] = st
        for i, upd in enumerate(self._updaters):
            self.updater_state[str(i)] = upd.init(self.params[str(i)])
        self._initialized = True
        return self

    @property
    def n_layers(self) -> int:
        return len(self.conf.confs)

    # ------------------------------------------------------------------
    # Pure functional forward (traced under jit)
    # ------------------------------------------------------------------
    def _forward_fn(
        self,
        params,
        state,
        x,
        rng,
        train: bool,
        feature_mask=None,
        rnn_state=None,
        collect: bool = False,
    ):
        """Returns (final_or_all_activations, new_state, new_rnn_state)."""
        cd = self._compute_dtype
        # The OUTPUT layer always runs at the master dtype: a bf16
        # softmax quantizes probabilities coarsely enough to stall
        # training at a calibration plateau (measured on LeNet/MNIST:
        # bf16-everywhere pins at 0.905 accuracy / 1.76 loss while f32
        # head converges to ~1.0; the conv/dense bulk keeps the MXU
        # bf16 rate). Casting AFTER the softmax (the loss-side cast
        # below) is too late — the quantization already happened.
        out_f32 = (cd is not None
                   and isinstance(self.conf.confs[-1].layer,
                                  L.BaseOutputLayer))
        last_si = str(self.n_layers - 1)
        if cd is not None:
            # Mixed precision: compute in cd (bf16 on the MXU), master
            # params stay f32 — the cast's transpose accumulates grads
            # back in f32.
            cast = functools.partial(_cast_floating, dtype=cd)
            params = {
                si: (sub if (out_f32 and si == last_si)
                     else jax.tree_util.tree_map(cast, sub))
                for si, sub in params.items()
            }
            x = cast(x)
        acts = []
        new_state = dict(state) if state else {}
        new_rnn = {}
        rngs = (
            jax.random.split(rng, self.n_layers)
            if rng is not None
            else [None] * self.n_layers
        )
        for i, (c, impl) in enumerate(zip(self.conf.confs, self._impls)):
            si = str(i)
            pp = self.conf.preprocessor_for(i)
            if pp is not None:
                x = pp.pre_process(x, rngs[i] if train else None)
            layer_state = None
            if state and si in state:
                layer_state = state[si]
            elif rnn_state and si in rnn_state:
                layer_state = rnn_state[si]
            is_recurrent = isinstance(c.layer, L.RECURRENT_LAYER_TYPES)
            mask = feature_mask if is_recurrent else None

            def _apply(p, xin, lst, lrng, lmask, _c=c, _impl=impl):
                return _impl.apply(
                    _c, p, xin, state=lst, train=train, rng=lrng,
                    mask=lmask,
                )

            if self.conf.remat:
                _apply = jax.checkpoint(_apply)
            if out_f32 and si == last_si:
                x = _cast_floating(x, self._dtype)
            x, st = _apply(
                params[si], x, layer_state,
                rngs[i] if train else None, mask,
            )
            if st is not None:
                if cd is not None:
                    # keep carried state at the master dtype so repeated
                    # steps see stable input dtypes (no recompiles)
                    st = jax.tree_util.tree_map(
                        functools.partial(_cast_floating,
                                          dtype=self._dtype), st)
                if state and si in state:
                    new_state[si] = st
                else:
                    new_rnn[si] = st
            if collect:
                acts.append(x)
        return (acts if collect else x), new_state, new_rnn

    def _loss_fn(
        self, params, state, rng, features, labels, feature_mask, label_mask
    ):
        out, new_state, _ = self._forward_fn(
            params, state, features, rng, True, feature_mask
        )
        out_conf = self.conf.confs[-1]
        impl = self._impls[-1]
        if not hasattr(impl, "loss"):
            raise ValueError(
                "Last layer must be an output layer to compute a score"
            )
        if self._compute_dtype is not None:
            out = _cast_floating(out, dtype=self._dtype)  # loss in f32
        score = impl.loss(out_conf, out, labels, label_mask)
        score = score + self._reg_score(params)
        score = score + self._aux_score(new_state)
        return score, new_state

    def _reg_score(self, params):
        reg = 0.0
        for i, c in enumerate(self.conf.confs):
            reg = reg + layer_reg_score(c, params[str(i)])
        return reg

    def _aux_score(self, new_state):
        """Auxiliary training losses layers emit through the state
        channel (MoeDense load-balancing loss), gate-weighted per conf."""
        aux = 0.0
        for i, c in enumerate(self.conf.confs):
            w = getattr(c.layer, "aux_weight", None)
            st = new_state.get(str(i)) if new_state else None
            if w and st and "aux_loss" in st:
                aux = aux + w * st["aux_loss"]
        return aux

    # ------------------------------------------------------------------
    # The jitted train step (whole §3.1 stack as one XLA computation)
    # ------------------------------------------------------------------
    def _apply_updates(self, params, upd_state, grads, iteration,
                       grad_scale=1.0):
        """Per-layer normalize → scale → updater → subtract (shared by
        the standard and tBPTT steps)."""
        new_params = {}
        new_upd = {}
        for i, (c, upd) in enumerate(zip(self.conf.confs, self._updaters)):
            si = str(i)
            updates, new_upd[si] = layer_update(
                c, upd, grads[si], upd_state[si], iteration, grad_scale)
            new_params[si] = jax.tree.map(
                lambda p, u: p - u, params[si], updates
            )
        return new_params, new_upd

    def _step_body(self, params, state, upd_state, iteration, rng, features,
                   labels, feature_mask, label_mask, grad_scale=1.0):
        (score, new_state), grads = jax.value_and_grad(
            self._loss_fn, has_aux=True
        )(params, state, rng, features, labels, feature_mask, label_mask)
        new_params, new_upd = self._apply_updates(
            params, upd_state, grads, iteration, grad_scale)
        # Gradient-health scalars ride as extra outputs of THE SAME
        # executable whether a listener is attached or not: telemetry
        # on/off cannot change compile counts or the param trajectory
        # (ISSUE 8 invariant). Unfetched, they cost a few reduction ops.
        health = grad_health(grads, params, new_params)
        return new_params, new_state, new_upd, score, health

    @functools.cached_property
    def _train_step(self):
        return jax.jit(self._step_body, donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _train_steps_scan(self):
        """K train steps as ONE XLA computation via ``lax.scan`` — one
        host dispatch per K batches instead of per batch. This is the
        dispatch-latency killer for small models: per-step launches over
        PCIe/tunnel otherwise dominate sub-millisecond step times."""

        def steps(params, state, upd_state, iteration, rng, feats, labels,
                  grad_scale=1.0):
            def body(carry, inp):
                p, s, u, it, key = carry
                key, sub = jax.random.split(key)
                f, y = inp
                p, s, u, score, health = self._step_body(
                    p, s, u, it, sub, f, y, None, None, grad_scale)
                return (p, s, u, it + 1, key), (score, health)

            (p, s, u, it, _), (scores, health) = jax.lax.scan(
                body, (params, state, upd_state, iteration, rng),
                (feats, labels))
            return p, s, u, scores, health

        return jax.jit(steps, donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _train_steps_scan_masked(self):
        """Masked variant of _train_steps_scan: the per-batch feature and
        label masks ride the scan as extra xs, so masked time-series
        training gets the same one-dispatch-per-K-batches fast path."""

        def steps(params, state, upd_state, iteration, rng, feats, labels,
                  fms, lms, grad_scale=1.0):
            def body(carry, inp):
                p, s, u, it, key = carry
                key, sub = jax.random.split(key)
                f, y, fm, lm = inp
                p, s, u, score, health = self._step_body(
                    p, s, u, it, sub, f, y, fm, lm, grad_scale)
                return (p, s, u, it + 1, key), (score, health)

            (p, s, u, it, _), (scores, health) = jax.lax.scan(
                body, (params, state, upd_state, iteration, rng),
                (feats, labels, fms, lms))
            return p, s, u, scores, health

        return jax.jit(steps, donate_argnums=(0, 1, 2))

    def fit_scan(self, features_stacked, labels_stacked,
                 features_mask_stacked=None, labels_mask_stacked=None,
                 grad_scale: float = 1.0):
        """Run one scanned pass over pre-stacked batches
        ([K, B, ...], [K, B, n_out], optional masks [K, B, T]); returns
        the K per-step scores as a device array (convert with np.asarray
        to force a sync — kept lazy here so chained calls pipeline
        without a host round-trip each). Plain-SGD fast path — use fit()
        when tBPTT or a second-order solver is configured."""
        if self.conf.backprop_type == BackpropType.TRUNCATED_BPTT:
            raise ValueError(
                "fit_scan is the full-BPTT SGD fast path; truncated-BPTT "
                "configs must train via fit()")
        algo = self.conf.confs[0].optimization_algo
        if algo != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            raise ValueError(
                f"fit_scan only supports SGD, not {algo}; use fit()")
        self.init()
        feats = jnp.asarray(features_stacked, self._dtype)
        labels = jnp.asarray(labels_stacked, self._dtype)
        self._key, sub = jax.random.split(self._key)
        start = self.iteration
        if features_mask_stacked is not None or labels_mask_stacked is not None:
            # Synthesize the missing mask as all-ones so one masked
            # kernel covers every presence combination.
            fms = (jnp.asarray(features_mask_stacked)
                   if features_mask_stacked is not None
                   else jnp.ones(feats.shape[:2] + (feats.shape[-1],),
                                 self._dtype))
            lms = (jnp.asarray(labels_mask_stacked)
                   if labels_mask_stacked is not None
                   else jnp.ones(labels.shape[:2] + (labels.shape[-1],),
                                 self._dtype))
            step_fn = self._train_steps_scan_masked
            extra = (fms, lms)
        else:
            step_fn = self._train_steps_scan
            extra = ()
        t0 = time.perf_counter()
        (self.params, self.state, self.updater_state, scores,
         health) = step_fn(
            self.params, self.state, self.updater_state,
            self.iteration, sub, feats, labels, *extra, grad_scale)
        k, examples, tokens = window_counts(feats.shape)
        self.train_telemetry.record_step(
            dispatch_s=time.perf_counter() - t0, steps=k,
            examples=examples, tokens=tokens, health=health)
        self.iteration += k
        self.score_value = scores[-1]  # lazy device scalar, like _fit_batch
        from deeplearning4j_tpu.optimize.listeners import fire_crossed

        fire_crossed(self.listeners, self, start, self.iteration)
        return scores

    def fit_stream(self, iterator, scan_steps: int = 16,
                   ingest=None, ingest_labels=None,
                   sync_each_window: bool = False):
        """Host-fed training: consume a DataSetIterator (typically an
        async prefetcher over on-disk binaries — the reference's
        AsyncDataSetIterator role, datasets/iterator/
        AsyncDataSetIterator.java:1) while keeping the chip busy.

        ``scan_steps`` consecutive batches are stacked host-side,
        shipped in ONE transfer, and trained in ONE fused ``fit_scan``
        dispatch — so disk reads, host stacking, and the next window's
        H2D ride under the previous window's device compute instead of
        costing a per-batch host round-trip. ``ingest`` /
        ``ingest_labels`` are optional jitted device-side transforms on
        the stacked [K, B, ...] feature/label windows (e.g. u8 pixels →
        normalized compute dtype, token ids → one-hot), keeping the
        wire format minimal. ``sync_each_window`` fetches each window's
        last score before uploading the next — on transports where H2D
        cannot overlap compute (BENCHMARKS.md "host-fed" notes), a
        serialized upload is faster than a degraded concurrent one for
        byte-heavy windows.

        A ragged tail (iterator exhausts mid-window, or a final batch
        smaller than the rest) falls back to per-batch ``fit``. Returns
        the last window's score array."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        scores = None

        def flush(window, fused):
            nonlocal scores
            def stack_masks(attr):
                ms = [getattr(b, attr) for b in window]
                if all(m is None for m in ms):
                    return None
                if any(m is None for m in ms):
                    raise ValueError(
                        f"fit_stream window mixes batches with and "
                        f"without {attr}")
                return np.stack([np.asarray(m) for m in ms])

            if fused:
                feats = jax.device_put(
                    np.stack([np.asarray(b.features) for b in window]))
                labels = jax.device_put(
                    np.stack([np.asarray(b.labels) for b in window]))
                fms = stack_masks("features_mask")
                lms = stack_masks("labels_mask")
                if sync_each_window:
                    # Materialize the upload BEFORE dispatching compute:
                    # on transports where transfers degrade while a
                    # computation is in flight, dispatching fit_scan
                    # first would make the scan stall on a crawling
                    # transfer of its own input.
                    feats.block_until_ready()
                    labels.block_until_ready()
                if ingest is not None:
                    feats = ingest(feats)
                if ingest_labels is not None:
                    labels = ingest_labels(labels)
                scores = self.fit_scan(
                    feats, labels, features_mask_stacked=fms,
                    labels_mask_stacked=lms)
                if sync_each_window:
                    np.asarray(scores[-1])
                return
            for b in window:  # ragged: correctness over throughput
                f = jnp.asarray(np.asarray(b.features)[None])
                y = jnp.asarray(np.asarray(b.labels)[None])
                if ingest is not None:
                    f = ingest(f)
                if ingest_labels is not None:
                    y = ingest_labels(y)
                self._fit_batch(DataSet(
                    f[0], y[0], b.features_mask, b.labels_mask))
            scores = jnp.asarray([self.score_value])

        from deeplearning4j_tpu.nn.streaming_fit import (
            drive_stream_windows,
        )

        drive_stream_windows(
            iterator, scan_steps, flush,
            lambda ds: np.shape(ds.features),
            telemetry=self.train_telemetry)
        return scores

    @functools.cached_property
    def _grad_and_score(self):
        def gs(params, state, rng, features, labels, feature_mask, label_mask):
            (score, new_state), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True
            )(params, state, rng, features, labels, feature_mask, label_mask)
            return score, grads, new_state

        return jax.jit(gs)

    @functools.cached_property
    def _output_fn(self):
        def out(params, state, x):
            y, _, _ = self._forward_fn(params, state, x, None, False)
            return y

        return jax.jit(out)

    # ------------------------------------------------------------------
    # Public training API (reference fit(...) :1130)
    # ------------------------------------------------------------------
    def fit(self, data, labels=None) -> None:
        """fit(DataSet) / fit(features, labels) / fit(DataSetIterator)."""
        self.init()
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if labels is not None:
            self._fit_batch(DataSet(data, labels))
        elif isinstance(data, DataSet):
            self._fit_batch(data)
        else:  # iterator
            if self.conf.pretrain:
                self.pretrain(data)
                data.reset()
            if self.conf.backprop:
                it = iter(data)
                while True:
                    t0 = time.perf_counter()
                    ds = next(it, None)
                    self.train_telemetry.add_data_wait(
                        time.perf_counter() - t0)
                    if ds is None:
                        break
                    self._fit_batch(ds)

    def _fit_batch(self, ds) -> None:
        if self.conf.backprop_type == BackpropType.TRUNCATED_BPTT:
            self._fit_tbptt(ds)
            return
        algo = self.conf.confs[0].optimization_algo
        if algo != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            from deeplearning4j_tpu.optimize.solver import Solver

            Solver(self).optimize(ds)
            return
        n_iter = max(1, self.conf.confs[0].num_iterations)
        feats = jnp.asarray(ds.features, self._dtype)
        labels = jnp.asarray(ds.labels, self._dtype)
        fm = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        examples, tokens = batch_counts(feats)
        for _ in range(n_iter):
            self._key, sub = jax.random.split(self._key)
            t0 = time.perf_counter()
            self.params, self.state, self.updater_state, score, health = (
                self._train_step(
                    self.params, self.state, self.updater_state,
                    self.iteration, sub, feats, labels, fm, lm,
                )
            )
            self.train_telemetry.record_step(
                dispatch_s=time.perf_counter() - t0, examples=examples,
                tokens=tokens, health=health)
            self.score_value = score
            self.iteration += 1
            for listener in self.listeners:
                if listener.invoked_every <= 1 or (
                    self.iteration % listener.invoked_every == 0
                ):
                    listener.iteration_done(self, self.iteration)

    def _fit_tbptt(self, ds) -> None:
        """Truncated BPTT (reference doTruncatedBPTT :1262-1320): chop the
        time axis into windows, carry rnn state (stop-gradient) across."""
        length = self.conf.tbptt_fwd_length
        feats = jnp.asarray(ds.features, self._dtype)
        labels = jnp.asarray(ds.labels, self._dtype)
        t_total = feats.shape[2]
        rnn_state = None
        for start in range(0, t_total, length):
            end = min(start + length, t_total)
            fw = feats[:, :, start:end]
            lw = labels[:, :, start:end]
            fmw = (
                None
                if ds.features_mask is None
                else jnp.asarray(ds.features_mask)[:, start:end]
            )
            lmw = (
                None
                if ds.labels_mask is None
                else jnp.asarray(ds.labels_mask)[:, start:end]
            )
            self._key, sub = jax.random.split(self._key)
            t0 = time.perf_counter()
            (
                self.params,
                self.state,
                self.updater_state,
                rnn_state,
                score,
                health,
            ) = self._tbptt_step(
                self.params, self.state, self.updater_state,
                self.iteration, sub, fw, lw, fmw, lmw, rnn_state,
            )
            self.train_telemetry.record_step(
                dispatch_s=time.perf_counter() - t0,
                examples=int(fw.shape[0]),
                tokens=int(fw.shape[0]) * int(fw.shape[2]),
                health=health)
            self.score_value = score
            self.iteration += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration)

    @functools.cached_property
    def _tbptt_step(self):
        def loss(params, state, rng, f, y, fm, lm, rnn_state):
            out, new_state, new_rnn = self._forward_fn(
                params, state, f, rng, True, fm, rnn_state=rnn_state
            )
            if self._compute_dtype is not None:
                out = _cast_floating(out, dtype=self._dtype)  # loss in f32
            impl = self._impls[-1]
            score = impl.loss(self.conf.confs[-1], out, y, lm)
            score = score + self._reg_score(params)
            score = score + self._aux_score(new_state)
            return score, (new_state, new_rnn)

        def step(params, state, upd_state, iteration, rng, f, y, fm, lm,
                 rnn_state):
            (score, (new_state, new_rnn)), grads = jax.value_and_grad(
                loss, has_aux=True
            )(params, state, rng, f, y, fm, lm, rnn_state)
            new_params, new_upd = self._apply_updates(
                params, upd_state, grads, iteration)
            new_rnn = jax.lax.stop_gradient(new_rnn)
            health = grad_health(grads, params, new_params)
            return new_params, new_state, new_upd, new_rnn, score, health

        return jax.jit(step)

    # ------------------------------------------------------------------
    # Pretraining (reference pretrain :150-226, §3.3)
    # ------------------------------------------------------------------
    def pretrain(self, data_iter) -> None:
        """Greedy layer-wise pretraining of RBM/AutoEncoder layers."""
        self.init()
        from deeplearning4j_tpu.optimize.pretrainer import pretrain_network

        pretrain_network(self, data_iter)

    # ------------------------------------------------------------------
    # Inference (reference output/feedForward :578-715)
    # ------------------------------------------------------------------
    def output(self, x, train: bool = False) -> Array:
        self.init()
        x = jnp.asarray(x, self._dtype)
        return self._output_fn(self.params, self.state, x)

    def feed_forward(self, x, train: bool = False) -> List[Array]:
        """All layer activations, input first (reference feedForward)."""
        self.init()
        x = jnp.asarray(x, self._dtype)
        acts, _, _ = self._forward_fn(
            self.params, self.state, x, None, False, collect=True
        )
        return [x] + list(acts)

    def predict(self, x) -> np.ndarray:
        """Argmax class predictions (reference Classifier.predict)."""
        out = self.output(x)
        return np.asarray(jnp.argmax(out, axis=1))

    def score(self, ds=None) -> float:
        if ds is None:
            return float(self.score_value)
        self.init()
        feats = jnp.asarray(ds.features, self._dtype)
        labels = jnp.asarray(ds.labels, self._dtype)
        fm = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        s, _ = self._loss_eval(self.params, self.state, feats, labels, fm, lm)
        return float(s)

    @functools.cached_property
    def _loss_eval(self):
        def f(params, state, x, y, fm, lm):
            out, _, _ = self._forward_fn(params, state, x, None, False, fm)
            if self._compute_dtype is not None:
                out = _cast_floating(out, dtype=self._dtype)  # loss in f32
            impl = self._impls[-1]
            score = impl.loss(self.conf.confs[-1], out, y, lm)
            return score + self._reg_score(params), out

        return jax.jit(f)

    # ------------------------------------------------------------------
    # Gradient access for gradient checks (reference
    # computeGradientAndScore + gradient())
    # ------------------------------------------------------------------
    def compute_gradient_and_score(self, ds) -> Tuple[float, Gradient]:
        self.init()
        feats = jnp.asarray(ds.features, self._dtype)
        labels = jnp.asarray(ds.labels, self._dtype)
        fm = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        score, grads, _ = self._grad_and_score(
            self.params, self.state, None, feats, labels, fm, lm
        )
        return float(score), Gradient.from_tree(grads)

    # ------------------------------------------------------------------
    # RNN streaming + state (reference rnnTimeStep, stateMap)
    # ------------------------------------------------------------------
    @functools.cached_property
    def _rnn_step_jit(self):
        # One jitted computation per streaming step instead of one host
        # dispatch per XLA op (the serving loop's hot path); retraces
        # only when the rnn-state pytree structure flips from empty
        # (first call) to populated.
        def f(params, state, x, rnn_state):
            return self._forward_fn(
                params, state, x, None, False,
                rnn_state=rnn_state or None,
            )

        return jax.jit(f)

    def rnn_time_step(self, x) -> Array:
        """Stateful single/multi-step inference carrying hidden state
        between calls (reference rnnTimeStep)."""
        self.init()
        from deeplearning4j_tpu.nn.layers.attention import (
            guard_streamable,
        )

        guard_streamable(
            (str(i), c.layer) for i, c in enumerate(self.conf.confs))
        x = jnp.asarray(x, self._dtype)
        if x.ndim == 2:
            x = x[:, :, None]
        out, _, new_rnn = self._rnn_step_jit(
            self.params, self.state, x, self._rnn_state)
        self._rnn_state = new_rnn
        return out

    def rnn_clear_previous_state(self, slots=None) -> None:
        """Reset streaming state (reference rnnClearPreviousState).

        ``slots=None`` wipes the whole batch. ``slots=[...]`` zeroes
        only those batch rows — the serving engine's per-slot eviction
        hook (nn/streaming.py: a zeroed attention row IS the
        empty-cache state, so the cleared slot streams as fresh while
        its neighbours keep decoding mid-flight)."""
        from deeplearning4j_tpu.nn.streaming import reset_streaming_state

        self._rnn_state = reset_streaming_state(self._rnn_state, slots)

    def generate(self, prompt, n_tokens: int):
        """Greedy autoregressive generation fused on device: prefill
        the one-hot prompt [B, V, Tp] through ``rnn_time_step``, then
        ONE jitted ``lax.scan`` emits ``n_tokens`` ids with the KV
        cache riding in the scan carry — serving throughput without a
        host round-trip per token. The per-token equivalent is a
        ``rnn_time_step`` loop (reference rnnTimeStep streaming,
        nn/layers/recurrent/BaseRecurrentLayer.java:1); numerics are
        identical (tests/test_decode_generate.py).

        The scan length is BUCKETED to the next power of two
        (nn/streaming.py scan_length_bucket) and the true length rides
        as a traced operand: steps past it freeze the carry, so the
        compiled-executable count stays O(log max_tokens) under varied
        request lengths instead of one compile per distinct
        ``n_tokens``, and the rnn state still lands exactly at the
        post-generation position.

        Requires an LM-shaped net (n_classes == n_in, one-hot io).
        Returns int32 ids [B, n_tokens]; leaves the rnn state at the
        post-generation position."""
        from deeplearning4j_tpu.nn.streaming import (
            make_bucketed_generate,
            scan_length_bucket,
        )

        if n_tokens < 1:
            raise ValueError(f"n_tokens {n_tokens} < 1")
        self.init()
        vocab = self.conf.confs[0].layer.n_in
        out = self.rnn_time_step(prompt)  # prefill (guards streamable)
        tok0 = jnp.argmax(out[:, :, -1], axis=1).astype(jnp.int32)
        if n_tokens == 1:
            return tok0[:, None]
        n_rem = n_tokens - 1
        bucket = scan_length_bucket(n_rem)
        gen = self._generate_fns.get(bucket)
        if gen is None:
            def step(params, state, x, rnn):
                o, _, new_rnn = self._forward_fn(
                    params, state, x, None, False, rnn_state=rnn)
                return o, new_rnn

            gen = self._generate_fns[bucket] = make_bucketed_generate(
                step, vocab, self._dtype, bucket)
        toks, self._rnn_state = gen(
            self.params, self.state, self._rnn_state, tok0,
            jnp.asarray(n_rem, jnp.int32))
        return jnp.concatenate([tok0[:, None], toks[:, :n_rem]], axis=1)

    # ------------------------------------------------------------------
    # Parameter pack/unpack (reference params() :984-1063)
    # ------------------------------------------------------------------
    def params_flat(self) -> Array:
        flat, _ = ravel_pytree(self.params)
        return flat

    def set_params_flat(self, flat) -> None:
        _, unravel = ravel_pytree(self.params)
        self.params = unravel(jnp.asarray(flat))
        self.params_version += 1

    def num_params(self) -> int:
        return int(self.params_flat().shape[0])

    def param_table(self) -> Dict[str, Array]:
        """Flat "idx_name" -> array view (reference paramTable())."""
        out = {}
        for idx in sorted(self.params, key=int):
            for name, p in self.params[idx].items():
                out[f"{idx}_{name}"] = p
        return out

    def set_param(self, key: str, value) -> None:
        idx, name = key.split("_", 1)
        self.params[idx][name] = jnp.asarray(value, self._dtype)
        self.params_version += 1

    # ------------------------------------------------------------------
    # Evaluation + listeners
    # ------------------------------------------------------------------
    def evaluate(self, data_iter):
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        self.init()
        ev = Evaluation()
        for ds in data_iter:
            out = self.output(ds.features)
            if ds.labels_mask is not None or (
                np.asarray(ds.labels).ndim == 3
            ):
                ev.eval_time_series(ds.labels, out, ds.labels_mask)
            else:
                ev.eval(ds.labels, out)
        return ev

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    # ------------------------------------------------------------------
    # Serialization (reference checkpoint triple: conf JSON + params +
    # updater, SURVEY.md §5.4; here conf JSON + params npz + updater npz)
    # ------------------------------------------------------------------
    def save(self, path: str) -> None:
        """One-zip checkpoint (util/model_serializer format)."""
        from deeplearning4j_tpu.util.model_serializer import write_model

        write_model(self, path)

    @staticmethod
    def load(path: str) -> "MultiLayerNetwork":
        from deeplearning4j_tpu.util.model_serializer import restore_model

        net = restore_model(path)
        if not isinstance(net, MultiLayerNetwork):
            raise TypeError(f"{path} holds a {type(net).__name__}")
        return net

    def clone(self) -> "MultiLayerNetwork":
        # Deep-copy the buffers: the train step DONATES params/state, so
        # aliased references in a clone would be deleted by the donor's
        # next step ("Array has been deleted"). Skip init() — its random
        # params would be immediately overwritten.
        copy = functools.partial(jax.tree.map, jnp.copy)
        net = MultiLayerNetwork(self.conf.clone())
        net.params = copy(self.params)
        net.updater_state = copy(self.updater_state)
        net.state = copy(self.state)
        net.iteration = self.iteration
        net._initialized = True
        return net

    def unsharded_clone(self) -> "MultiLayerNetwork":
        """A clone with every bean's mesh-axis fields (``ring_axis``,
        ``ep_axis``) cleared — the single-device serving/eval view of a
        mesh-trained net. The ring/Ulysses and dense attention paths
        (and sp_scan vs lax.scan recurrences, and all-to-all vs dense
        MoE dispatch) are numerically equivalent (parity-tested), so
        scores/outputs match the mesh-trained model; use this for score
        calculators, evaluate(), or rnn_time_step, which run outside
        the mesh.

        Build it ONCE per serving/eval site and refresh weights per
        evaluation (``serving.params = jax.tree.map(jnp.copy,
        net.params)``; likewise ``state``) — a fresh clone per call
        would re-jit the forward every time."""
        net = self.clone()
        for c in net.conf.confs:
            for axis_field in ("ring_axis", "ep_axis"):
                if getattr(c.layer, axis_field, None):
                    setattr(c.layer, axis_field, None)
        return net
