"""NLP: embeddings (Word2Vec/GloVe/ParagraphVectors) + text pipeline.

Mirror of reference deeplearning4j-scaleout/deeplearning4j-nlp (32,749 LoC
— SURVEY.md §2.8): SequenceVectors engine, Word2Vec skip-gram with
hierarchical softmax + negative sampling, vocabulary construction with
Huffman coding, tokenizers/sentence iterators, vector serialization.

TPU inversion (SURVEY.md §7 stage 11): the reference trains via Hogwild —
N threads racing lock-free on shared syn0/syn1 tables
(SequenceVectors.fit :133-160, InMemoryLookupTable.iterateSample). Here
training is *batched deterministic SPMD*: pairs are mined host-side into
index arrays and the update is one jitted gather/scatter-add computation,
data-parallel over the mesh — same convergence role, reproducible, and the
scatter rides the MXU/VPU instead of the Java memory bus.
"""

from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
)
from deeplearning4j_tpu.nlp.sentence_iterator import (
    CollectionSentenceIterator,
    FileSentenceIterator,
    LineSentenceIterator,
)
