"""Bundled training fixtures for the out-of-the-box NLP models.

The reference's PoS tagging and tree parsing work with zero setup
because UIMA/ClearTK ship trained models as dependency artifacts
(reference text/tokenization/tokenizer/PosUimaTokenizer.java:35-50,
text/corpora/treeparser/TreeParser.java); the analogue here is a
bundled tagged corpus + treebank that ``HmmPosTagger.pretrained()`` /
``PcfgParser.pretrained()`` train from on first use (milliseconds,
then cached for the process).

Round 4: the fixtures are GENERATED at ~25k tokens / 1.5k trees by
scripts/gen_nlp_fixtures.py — a hand-written English grammar whose
derivations emit the tree and the word/TAG sequence together, with
real ambiguity (noun/verb homographs, PP attachment, relative
clauses, coordination, agreement). Synthetic by necessity (zero-egress
image; no real treebank can be downloaded) and said so here; held-out
splits (``*_heldout.txt``, disjoint derivations) gate measured quality
in tests/test_pos_pcfg.py: tagger accuracy 0.999, parser bracket-F1
0.986 (collapsed-unary normal form) at generation time.
"""

from __future__ import annotations

import os
from typing import List, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))


def load_tagged_corpus(
        name: str = "pos_en_fixture.txt",
) -> List[List[Tuple[str, str]]]:
    """Bundled word/TAG corpus -> [[(word, tag), ...], ...].
    ``pos_en_heldout.txt`` is the quality-gate split: generated from
    the same grammar (scripts/gen_nlp_fixtures.py) but disjoint
    derivations never seen by ``pretrained()``."""
    out = []
    with open(os.path.join(_DIR, name)) as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            sent = []
            for t in toks:
                word, _, tag = t.rpartition("/")
                sent.append((word, tag))
            out.append(sent)
    return out


def parse_bracketed(s: str):
    """One Penn-style bracketed tree string -> ParseTree. Raises
    ValueError (with the offending text) on truncated or malformed
    input instead of an uninformative IndexError from deep inside the
    scan."""
    from deeplearning4j_tpu.nlp.tree_parser import ParseTree

    pos = 0

    def fail(msg):
        raise ValueError(
            f"malformed bracketed tree at char {pos}: {msg} "
            f"in {s[:80]!r}")

    def scan_atom():
        nonlocal pos
        end = pos
        while end < len(s) and s[end] not in " ()":
            end += 1
        if end == pos:
            fail("expected a label/word")
        atom = s[pos:end]
        pos = end
        return atom

    def parse_node():
        nonlocal pos
        if pos >= len(s) or s[pos] != "(":
            fail("expected '('")
        pos += 1
        label = scan_atom()
        children = []
        word = None
        while True:
            while pos < len(s) and s[pos] == " ":
                pos += 1
            if pos >= len(s):
                fail(f"unclosed '({label}'")
            if s[pos] == ")":
                pos += 1
                break
            if s[pos] == "(":
                children.append(parse_node())
            else:
                word = scan_atom()
        if word is not None and children:
            fail(f"node ({label} ...) mixes children and a word")
        if word is not None:
            # Codebase pre-terminal convention (tree_parser.ParseTree):
            # "(DT the)" is a DT node wrapping a leaf that carries the
            # word — is_pre_terminal() relies on that shape.
            return ParseTree(label=label,
                             children=[ParseTree(label=label, word=word)])
        return ParseTree(label=label, children=children)

    while pos < len(s) and s[pos] == " ":
        pos += 1
    return parse_node()


def load_treebank(name: str = "trees_en_fixture.txt"):
    """Bundled bracketed treebank -> [ParseTree, ...].
    ``trees_en_heldout.txt`` is the bracket-F1 quality-gate split."""
    trees = []
    with open(os.path.join(_DIR, name)) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                trees.append(parse_bracketed(line))
            except ValueError as e:
                raise ValueError(
                    f"{name} line {lineno}: {e}") from None
    return trees
