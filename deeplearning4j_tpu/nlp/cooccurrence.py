"""Bounded-memory co-occurrence counting with binary spill shards.

Mirror of reference models/glove/AbstractCoOccurrences.java: the
reference counts into an in-memory CountMap while a ShadowCopyThread
dumps it to a binary spill file whenever the memory threshold is crossed
(:51 memory_threshold, :53 shadowThread, ShadowCopyThread.run), merging
successive dumps so corpora larger than RAM can be counted; the final
pair stream is read back from the merged file (:135 iterator()).

Here the same design is synchronous and explicit: counts accumulate in a
dict keyed by (row, col); when the dict exceeds ``max_pairs_in_memory``
it is flushed to a sorted .npy shard; ``iter_batches`` k-way-merges the
shards (heapq over mmap-backed chunk readers, summing duplicate keys)
and yields bounded-size (rows, cols, weights) batches — so peak memory
is O(max_pairs_in_memory + batch), never O(distinct pairs).
"""

from __future__ import annotations

import heapq
import os
import shutil
import tempfile
from typing import Dict, Iterable, Iterator, Optional, Sequence, Tuple

import numpy as np

_CHUNK = 1 << 16


class DiskBackedCoOccurrences:
    """Co-occurrence counter spilling to disk shards.

    ``max_pairs_in_memory`` bounds the distinct (row, col) pairs held in
    the in-memory map at once — the analogue of the reference's
    ``maxMemory`` builder knob (AbstractCoOccurrences.java:224).
    """

    def __init__(
        self,
        vocab,
        window: int = 15,
        symmetric: bool = True,
        max_pairs_in_memory: int = 1 << 22,
        spill_dir: Optional[str] = None,
    ):
        if max_pairs_in_memory < 1:
            raise ValueError("max_pairs_in_memory must be >= 1")
        self.vocab = vocab
        self.window = window
        self.symmetric = symmetric
        self.max_pairs = int(max_pairs_in_memory)
        self._own_dir = spill_dir is None
        self.spill_dir = spill_dir or tempfile.mkdtemp(prefix="dl4j_cooc_")
        os.makedirs(self.spill_dir, exist_ok=True)
        self._counts: Dict[int, float] = {}  # key = row * V + col
        self._shards = []
        self._n_spills = 0

    # -- counting ------------------------------------------------------
    def count_sequences(self, sequences: Iterable[Sequence[str]]) -> None:
        v = self.vocab.num_words()
        counts = self._counts
        for tokens in sequences:
            idxs = [
                self.vocab.index_of(t)
                for t in tokens
                if self.vocab.contains_word(t)
            ]
            for pos, center in enumerate(idxs):
                for off in range(1, self.window + 1):
                    j = pos + off
                    if j >= len(idxs):
                        break
                    w = 1.0 / off
                    other = idxs[j]
                    k = center * v + other
                    counts[k] = counts.get(k, 0.0) + w
                    if self.symmetric:
                        k2 = other * v + center
                        counts[k2] = counts.get(k2, 0.0) + w
            if len(counts) > self.max_pairs:
                self._spill()

    def _spill(self) -> None:
        if not self._counts:
            return
        keys = np.fromiter(self._counts.keys(), np.int64,
                           count=len(self._counts))
        vals = np.fromiter(self._counts.values(), np.float64,
                           count=len(self._counts))
        order = np.argsort(keys, kind="stable")
        path = os.path.join(self.spill_dir, f"shard{self._n_spills:05d}")
        np.save(path + ".keys.npy", keys[order])
        np.save(path + ".vals.npy", vals[order])
        self._shards.append(path)
        self._n_spills += 1
        # clear() (not reassignment): count_sequences holds a local
        # alias to this dict across spills.
        self._counts.clear()

    # -- merged streaming ---------------------------------------------
    @staticmethod
    def _shard_iter(path: str) -> Iterator[Tuple[int, float]]:
        keys = np.load(path + ".keys.npy", mmap_mode="r")
        vals = np.load(path + ".vals.npy", mmap_mode="r")
        for start in range(0, len(keys), _CHUNK):
            kc = np.asarray(keys[start:start + _CHUNK])
            vc = np.asarray(vals[start:start + _CHUNK])
            yield from zip(kc.tolist(), vc.tolist())

    def iter_batches(
        self, batch_size: int = 65536
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """K-way merge of the spill shards, duplicate keys summed,
        yielding (rows, cols, weights) batches in key order."""
        self._spill()  # flush the in-memory remainder
        if not self._shards:
            return
        v = self.vocab.num_words()
        merged = heapq.merge(*(self._shard_iter(p) for p in self._shards))
        rows: list = []
        cols: list = []
        vals: list = []
        cur_key, cur_val = None, 0.0

        def emit(k, val):
            rows.append(k // v)
            cols.append(k % v)
            vals.append(val)

        for k, val in merged:
            if k == cur_key:
                cur_val += val
                continue
            if cur_key is not None:
                emit(cur_key, cur_val)
                if len(rows) >= batch_size:
                    yield (np.asarray(rows, np.int32),
                           np.asarray(cols, np.int32),
                           np.asarray(vals, np.float32))
                    rows, cols, vals = [], [], []
            cur_key, cur_val = k, val
        if cur_key is not None:
            emit(cur_key, cur_val)
        if rows:
            yield (np.asarray(rows, np.int32),
                   np.asarray(cols, np.int32),
                   np.asarray(vals, np.float32))

    def n_shards(self) -> int:
        return len(self._shards) + (1 if self._counts else 0)

    def cleanup(self) -> None:
        if self._own_dir and os.path.isdir(self.spill_dir):
            shutil.rmtree(self.spill_dir, ignore_errors=True)
        self._shards = []
        self._counts = {}
