"""Constituency-style tree building, transforms, and vectorization.

TPU-native equivalent of the reference RNTN tree pipeline (reference
deeplearning4j-nlp/.../text/corpora/treeparser/{TreeParser,TreeVectorizer,
BinarizeTreeTransformer,CollapseUnaries,HeadWordFinder}.java): sentence →
parse tree → binarized, unary-collapsed tree whose nodes carry sentiment
labels — the input format RNTN trains on (nlp/rntn.py scan-linearizes the
result). The reference leans on a UIMA/ClearTK parser; here a
deterministic rule-based chunker (the same POS tagger the tokenizers use)
builds shallow constituents, so the pipeline is self-contained and
reproducible — swap ``TreeParser.chunk`` for a real parser when one is
available.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .rntn import Tree as RntnTree
from .sentiment import SentiWordNet
from .tokenization import RuleBasedPosTagger


@dataclass
class ParseTree:
    """N-ary labelled parse tree (reference treeparser Tree form)."""

    label: str
    word: Optional[str] = None
    children: List["ParseTree"] = field(default_factory=list)

    def is_leaf(self) -> bool:
        return not self.children

    def is_pre_terminal(self) -> bool:
        return len(self.children) == 1 and self.children[0].is_leaf()

    def yield_words(self) -> List[str]:
        if self.is_leaf():
            return [self.word] if self.word is not None else []
        out: List[str] = []
        for c in self.children:
            out.extend(c.yield_words())
        return out

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def __repr__(self) -> str:
        if self.is_leaf():
            return self.word or ""
        kids = " ".join(repr(c) for c in self.children)
        return f"({self.label} {kids})"


class TreeParser:
    """Sentence → shallow constituency ParseTree.

    POS-tags every token, groups maximal runs into NP/VP/PP chunks
    (determiner/adjective/noun runs → NP, modal/verb/adverb runs → VP,
    preposition-led runs → PP), and hangs the chunks under S — a
    deterministic stand-in for the reference's UIMA TreeParser.
    """

    _NP_TAGS = {"DT", "JJ", "NN", "PRP", "CD"}
    _VP_TAGS = {"VB", "MD", "RB"}

    def __init__(self, tagger: Optional[RuleBasedPosTagger] = None):
        self.tagger = tagger or RuleBasedPosTagger()

    def _chunk_label(self, tag: str) -> str:
        if tag in self._NP_TAGS:
            return "NP"
        if tag in self._VP_TAGS:
            return "VP"
        if tag == "IN":
            return "PP"
        return "X"

    def parse(self, sentence: str) -> ParseTree:
        tokens = [t for t in sentence.split() if t]
        if not tokens:
            return ParseTree(label="S")
        chunks: List[ParseTree] = []
        cur_label: Optional[str] = None
        cur_children: List[ParseTree] = []
        for tok in tokens:
            tag = self.tagger.tag(tok)
            label = self._chunk_label(tag)
            pre = ParseTree(label=tag,
                            children=[ParseTree(label=tag, word=tok)])
            # PP chunks absorb the following NP run (preposition-led)
            if cur_label == "PP" and label == "NP":
                cur_children.append(pre)
                continue
            if label != cur_label and cur_children:
                chunks.append(ParseTree(label=cur_label,
                                        children=cur_children))
                cur_children = []
            cur_label = label
            cur_children.append(pre)
        if cur_children:
            chunks.append(ParseTree(label=cur_label, children=cur_children))
        return ParseTree(label="S", children=chunks)

    def get_trees(self, text: str) -> List[ParseTree]:
        """One tree per sentence ('.'-split, reference getTrees)."""
        return [self.parse(s) for s in text.split(".") if s.strip()]


class CollapseUnaries:
    """Collapse unary chains X→Y→... to the bottom node (reference
    CollapseUnaries transformer)."""

    def transform(self, tree: ParseTree) -> ParseTree:
        if tree.is_leaf():
            return tree
        node = tree
        while len(node.children) == 1 and not node.children[0].is_leaf():
            node = node.children[0]
        if node.is_leaf():
            return node
        return ParseTree(
            label=tree.label, word=node.word,
            children=[self.transform(c) for c in node.children])


class BinarizeTreeTransformer:
    """Left-factored binarization: n-ary nodes become right-leaning
    chains of @label intermediates (reference BinarizeTreeTransformer)."""

    def transform(self, tree: ParseTree) -> ParseTree:
        if tree.is_leaf():
            return tree
        kids = [self.transform(c) for c in tree.children]
        if len(kids) == 1:
            return ParseTree(label=tree.label, children=kids)
        while len(kids) > 2:
            right = ParseTree(label="@" + tree.label, children=kids[-2:])
            kids = kids[:-2] + [right]
        return ParseTree(label=tree.label, children=kids)


class HeadWordFinder:
    """Head word per constituent (reference HeadWordFinder, Collins-style
    simplification): NPs head on their rightmost noun, VPs on their
    leftmost verb, else the rightmost child's head."""

    def find_head(self, tree: ParseTree) -> Optional[str]:
        if tree.is_leaf():
            return tree.word
        if tree.label == "NP":
            for c in reversed(tree.children):
                if c.label.startswith(("NN", "PRP", "CD")):
                    return self.find_head(c)
        if tree.label == "VP":
            for c in tree.children:
                if c.label.startswith(("VB", "MD")):
                    return self.find_head(c)
        return self.find_head(tree.children[-1])


class TreeVectorizer:
    """Sentence → binary sentiment-labelled RNTN trees (reference
    TreeVectorizer.getTreesWithLabels): parse, collapse unaries, binarize,
    then label every node from the polarity of its span (SentiWordNet
    scores, 0=negative 1=neutral 2=positive)."""

    def __init__(self, parser: Optional[TreeParser] = None,
                 sentiment: Optional[SentiWordNet] = None):
        self.parser = parser or TreeParser()
        self.sentiment = sentiment or SentiWordNet()
        self.collapse = CollapseUnaries()
        self.binarize = BinarizeTreeTransformer()

    def _label_of(self, words: List[str]) -> int:
        s = self.sentiment.score(words)
        if s > 0:
            return 2
        if s < 0:
            return 0
        return 1

    def _to_rntn(self, tree: ParseTree) -> RntnTree:
        words = tree.yield_words()
        label = self._label_of(words)
        if tree.is_leaf() or tree.is_pre_terminal():
            return RntnTree(label=label, word=words[0] if words else "")
        kids = tree.children
        if len(kids) == 1:
            return self._to_rntn(kids[0])
        return RntnTree(label=label, left=self._to_rntn(kids[0]),
                        right=self._to_rntn(kids[1]))

    def get_trees_with_labels(self, text: str) -> List[RntnTree]:
        out = []
        for parse in self.parser.get_trees(text):
            if not parse.children:
                continue
            t = self.binarize.transform(self.collapse.transform(parse))
            out.append(self._to_rntn(t))
        return out
