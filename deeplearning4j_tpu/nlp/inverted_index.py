"""Inverted indexes for the text pipeline.

Capability mirror of reference text/invertedindex/LuceneInvertedIndex
(SURVEY.md §2.8): word → document postings over tokenized docs, document
retrieval, mini-batch sampling for embedding training, and TF-IDF
scoring. Two stores behind one API:

- ``InvertedIndex`` — in-memory dict/array store (the fast default for
  corpora that fit in RAM).
- ``DiskInvertedIndex`` — sqlite-backed store that persists across
  process restarts and scales past RAM, the role Lucene's disk segments
  play for the reference (LuceneInvertedIndex.java:1: index directory
  on disk, reopened between runs). The tensor work stays in XLA either
  way.
"""

from __future__ import annotations

import math
import os
import sqlite3
import threading
from collections import Counter, defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


class InvertedIndex:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._docs: List[List[str]] = []
        self._labels: List[Optional[str]] = []
        self._postings: Dict[str, List[int]] = defaultdict(list)

    # -- building -------------------------------------------------------
    def add_doc(self, tokens: Sequence[str],
                label: Optional[str] = None) -> int:
        """Add a tokenized document; returns its doc id."""
        with self._lock:
            doc_id = len(self._docs)
            toks = list(tokens)
            self._docs.append(toks)
            self._labels.append(label)
            for w in set(toks):
                self._postings[w].append(doc_id)
            return doc_id

    # -- retrieval ------------------------------------------------------
    def num_documents(self) -> int:
        with self._lock:
            return len(self._docs)

    def document(self, doc_id: int) -> List[str]:
        with self._lock:
            return list(self._docs[doc_id])

    def label(self, doc_id: int) -> Optional[str]:
        with self._lock:
            return self._labels[doc_id]

    def documents_containing(self, word: str) -> List[int]:
        with self._lock:
            return list(self._postings.get(word, []))

    def document_frequency(self, word: str) -> int:
        return len(self.documents_containing(word))

    def vocab(self) -> List[str]:
        with self._lock:
            return sorted(self._postings)

    # -- scoring --------------------------------------------------------
    def tfidf(self, word: str, doc_id: int) -> float:
        """tf * log(N / df) (the reference's TfidfVectorizer weighting)."""
        doc = self.document(doc_id)
        if not doc:
            return 0.0
        tf = doc.count(word) / len(doc)
        df = self.document_frequency(word)
        if df == 0:
            return 0.0
        return tf * math.log(self.num_documents() / df)

    def search(self, query: Sequence[str], top_k: int = 10
               ) -> List[Tuple[int, float]]:
        """Rank documents by summed TF-IDF over query terms."""
        scores: Dict[int, float] = defaultdict(float)
        for w in query:
            for doc_id in self.documents_containing(w):
                scores[doc_id] += self.tfidf(w, doc_id)
        ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
        return ranked[:top_k]

    # -- training support ----------------------------------------------
    def sample_batch(self, batch_size: int,
                     rng: Optional[np.random.Generator] = None
                     ) -> List[List[str]]:
        """Random mini-batch of documents (the reference feeds W2V
        workers by sampling the index)."""
        rng = rng or np.random.default_rng()
        n = self.num_documents()
        if n == 0:
            return []
        idx = rng.integers(0, n, size=min(batch_size, n))
        return [self.document(int(i)) for i in idx]

    def all_documents(self) -> List[List[str]]:
        with self._lock:
            return [list(d) for d in self._docs]


class DiskInvertedIndex:
    """Sqlite-backed inverted index: same surface as ``InvertedIndex``
    but persistent (reopen the same path to resume) and bounded by
    disk, not RAM — the reference's Lucene directory role.

    Postings carry term frequencies so TF-IDF never re-tokenizes the
    document; searches aggregate in SQL. Tokens must not contain the
    space character (true post-tokenization); they are stored
    space-joined."""

    _SCHEMA = """
    CREATE TABLE IF NOT EXISTS docs(
        id INTEGER PRIMARY KEY, n_tokens INTEGER NOT NULL,
        tokens TEXT NOT NULL, label TEXT);
    CREATE TABLE IF NOT EXISTS postings(
        word TEXT NOT NULL, doc_id INTEGER NOT NULL,
        tf INTEGER NOT NULL);
    CREATE INDEX IF NOT EXISTS postings_word ON postings(word);
    """

    def __init__(self, path: str) -> None:
        self._lock = threading.RLock()
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.executescript(self._SCHEMA)
        self._conn.commit()

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            self._conn.commit()
            self._conn.close()

    def __enter__(self) -> "DiskInvertedIndex":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- building -------------------------------------------------------
    def add_doc(self, tokens: Sequence[str],
                label: Optional[str] = None) -> int:
        with self._lock:
            try:
                return self._insert(tokens, label, commit=True)
            except BaseException:
                # a docs row without its postings must not survive to
                # be flushed by a later unrelated commit
                self._conn.rollback()
                raise

    def add_docs(self, docs: Iterable[Sequence[str]],
                 labels: Optional[Iterable[Optional[str]]] = None
                 ) -> int:
        """Bulk ingestion: one transaction for the whole stream (the
        fast path for corpus-scale builds). Returns docs added."""
        labels = iter(labels) if labels is not None else None
        n = 0
        with self._lock:
            try:
                for toks in docs:
                    self._insert(
                        toks,
                        next(labels) if labels is not None else None,
                        commit=False)
                    n += 1
            except BaseException:
                # all-or-nothing: a later unrelated commit must not
                # persist a half-ingested corpus
                self._conn.rollback()
                raise
            self._conn.commit()
        return n

    def _insert(self, tokens, label, commit) -> int:
        toks = list(tokens)
        for t in toks:
            if " " in t:
                raise ValueError(
                    f"token {t!r} contains a space; tokenize first")
        cur = self._conn.execute(
            "INSERT INTO docs(n_tokens, tokens, label) VALUES (?,?,?)",
            (len(toks), " ".join(toks), label))
        doc_id = cur.lastrowid - 1  # 0-based ids like InvertedIndex
        self._conn.executemany(
            "INSERT INTO postings(word, doc_id, tf) VALUES (?,?,?)",
            [(w, doc_id, tf) for w, tf in Counter(toks).items()])
        if commit:
            self._conn.commit()
        return doc_id

    # -- retrieval ------------------------------------------------------
    def num_documents(self) -> int:
        with self._lock:
            return self._conn.execute(
                "SELECT COUNT(*) FROM docs").fetchone()[0]

    def _doc_row(self, doc_id: int):
        row = self._conn.execute(
            "SELECT tokens, label FROM docs WHERE id=?",
            (doc_id + 1,)).fetchone()
        if row is None:
            raise IndexError(f"no document {doc_id}")
        return row

    def document(self, doc_id: int) -> List[str]:
        with self._lock:
            toks = self._doc_row(doc_id)[0]
            return toks.split(" ") if toks else []

    def label(self, doc_id: int) -> Optional[str]:
        with self._lock:
            return self._doc_row(doc_id)[1]

    def documents_containing(self, word: str) -> List[int]:
        with self._lock:
            return [r[0] for r in self._conn.execute(
                "SELECT doc_id FROM postings WHERE word=? "
                "ORDER BY doc_id", (word,))]

    def document_frequency(self, word: str) -> int:
        with self._lock:
            return self._conn.execute(
                "SELECT COUNT(*) FROM postings WHERE word=?",
                (word,)).fetchone()[0]

    def vocab(self) -> List[str]:
        with self._lock:
            return [r[0] for r in self._conn.execute(
                "SELECT DISTINCT word FROM postings ORDER BY word")]

    # -- scoring --------------------------------------------------------
    def tfidf(self, word: str, doc_id: int) -> float:
        with self._lock:
            row = self._conn.execute(
                "SELECT p.tf, d.n_tokens FROM postings p "
                "JOIN docs d ON d.id = p.doc_id + 1 "
                "WHERE p.word=? AND p.doc_id=?",
                (word, doc_id)).fetchone()
            if row is None or row[1] == 0:
                return 0.0
            df = self.document_frequency(word)
            if df == 0:
                return 0.0
            return (row[0] / row[1]) * math.log(
                self.num_documents() / df)

    def search(self, query: Sequence[str], top_k: int = 10
               ) -> List[Tuple[int, float]]:
        """Rank documents by summed TF-IDF over query terms — one SQL
        aggregation instead of a Python loop over postings. Repeated
        query terms weight per OCCURRENCE, matching InvertedIndex."""
        term_counts = Counter(query)
        terms = list(term_counts)
        if not terms:
            return []
        with self._lock:
            n = max(1, self.num_documents())
            marks = ",".join("?" for _ in terms)
            dfs = dict(self._conn.execute(
                f"SELECT word, COUNT(*) FROM postings "
                f"WHERE word IN ({marks}) GROUP BY word", terms))
            scores: Dict[int, float] = defaultdict(float)
            for word, doc_id, tf, n_tokens in self._conn.execute(
                    f"SELECT p.word, p.doc_id, p.tf, d.n_tokens "
                    f"FROM postings p JOIN docs d ON d.id = p.doc_id+1 "
                    f"WHERE p.word IN ({marks})", terms):
                if n_tokens:
                    scores[doc_id] += (
                        term_counts[word] * (tf / n_tokens)
                        * math.log(n / dfs[word]))
            ranked = sorted(scores.items(),
                            key=lambda kv: (-kv[1], kv[0]))
            return ranked[:top_k]

    # -- training support ----------------------------------------------
    def sample_batch(self, batch_size: int,
                     rng: Optional[np.random.Generator] = None
                     ) -> List[List[str]]:
        rng = rng or np.random.default_rng()
        n = self.num_documents()
        if n == 0:
            return []
        idx = rng.integers(0, n, size=min(batch_size, n))
        return [self.document(int(i)) for i in idx]

    def iter_documents(self, batch_rows: int = 4096
                       ) -> Iterable[List[str]]:
        """Stream every document without materializing the corpus —
        the RAM-bounded path all_documents() cannot offer."""
        n = self.num_documents()
        for lo in range(0, n, batch_rows):
            with self._lock:
                rows = self._conn.execute(
                    "SELECT tokens FROM docs WHERE id > ? "
                    "ORDER BY id LIMIT ?", (lo, batch_rows)).fetchall()
            for (toks,) in rows:
                yield toks.split(" ") if toks else []

    def all_documents(self) -> List[List[str]]:
        return list(self.iter_documents())

    def size_bytes(self) -> int:
        with self._lock:
            self._conn.commit()
        return os.path.getsize(self.path)
