"""In-memory inverted index for the text pipeline.

Capability mirror of reference text/invertedindex/LuceneInvertedIndex
(SURVEY.md §2.8): word → document postings over tokenized docs, document
retrieval, mini-batch sampling for embedding training, and TF-IDF
scoring — without the Lucene dependency (host-side dict/array store; the
tensor work stays in XLA).
"""

from __future__ import annotations

import math
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class InvertedIndex:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._docs: List[List[str]] = []
        self._labels: List[Optional[str]] = []
        self._postings: Dict[str, List[int]] = defaultdict(list)

    # -- building -------------------------------------------------------
    def add_doc(self, tokens: Sequence[str],
                label: Optional[str] = None) -> int:
        """Add a tokenized document; returns its doc id."""
        with self._lock:
            doc_id = len(self._docs)
            toks = list(tokens)
            self._docs.append(toks)
            self._labels.append(label)
            for w in set(toks):
                self._postings[w].append(doc_id)
            return doc_id

    # -- retrieval ------------------------------------------------------
    def num_documents(self) -> int:
        with self._lock:
            return len(self._docs)

    def document(self, doc_id: int) -> List[str]:
        with self._lock:
            return list(self._docs[doc_id])

    def label(self, doc_id: int) -> Optional[str]:
        with self._lock:
            return self._labels[doc_id]

    def documents_containing(self, word: str) -> List[int]:
        with self._lock:
            return list(self._postings.get(word, []))

    def document_frequency(self, word: str) -> int:
        return len(self.documents_containing(word))

    def vocab(self) -> List[str]:
        with self._lock:
            return sorted(self._postings)

    # -- scoring --------------------------------------------------------
    def tfidf(self, word: str, doc_id: int) -> float:
        """tf * log(N / df) (the reference's TfidfVectorizer weighting)."""
        doc = self.document(doc_id)
        if not doc:
            return 0.0
        tf = doc.count(word) / len(doc)
        df = self.document_frequency(word)
        if df == 0:
            return 0.0
        return tf * math.log(self.num_documents() / df)

    def search(self, query: Sequence[str], top_k: int = 10
               ) -> List[Tuple[int, float]]:
        """Rank documents by summed TF-IDF over query terms."""
        scores: Dict[int, float] = defaultdict(float)
        for w in query:
            for doc_id in self.documents_containing(w):
                scores[doc_id] += self.tfidf(w, doc_id)
        ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
        return ranked[:top_k]

    # -- training support ----------------------------------------------
    def sample_batch(self, batch_size: int,
                     rng: Optional[np.random.Generator] = None
                     ) -> List[List[str]]:
        """Random mini-batch of documents (the reference feeds W2V
        workers by sampling the index)."""
        rng = rng or np.random.default_rng()
        n = self.num_documents()
        if n == 0:
            return []
        idx = rng.integers(0, n, size=min(batch_size, n))
        return [self.document(int(i)) for i in idx]

    def all_documents(self) -> List[List[str]]:
        with self._lock:
            return [list(d) for d in self._docs]
