"""Lexicon-based sentiment scoring.

Capability mirror of the reference's SentiWordNet support
(nlp text/corpora/sentiwordnet/SentiWordNet.java): load a word ->
(positivity, negativity) lexicon, score token sequences, classify
documents by aggregate polarity. The reference ships the SentiWordNet
TSV in its resources; redistribution terms differ, so a compact builtin
seed lexicon is embedded and ``load_lexicon`` accepts the standard
SentiWordNet 3.0 TSV format for users who supply their own copy.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

# word -> (pos_score, neg_score); seed list so the API is usable
# out-of-the-box (the reference bundles the full 117k-entry file).
_SEED_LEXICON: Dict[str, Tuple[float, float]] = {
    "good": (0.75, 0.0), "great": (0.8, 0.0), "excellent": (0.9, 0.0),
    "happy": (0.8, 0.0), "love": (0.85, 0.0), "wonderful": (0.9, 0.0),
    "best": (0.85, 0.0), "amazing": (0.85, 0.0), "nice": (0.6, 0.0),
    "awesome": (0.85, 0.0), "fantastic": (0.9, 0.0), "like": (0.5, 0.0),
    "enjoy": (0.7, 0.0), "perfect": (0.9, 0.0), "beautiful": (0.8, 0.0),
    "win": (0.6, 0.0), "better": (0.5, 0.0), "positive": (0.7, 0.0),
    "bad": (0.0, 0.75), "terrible": (0.0, 0.9), "awful": (0.0, 0.9),
    "sad": (0.0, 0.8), "hate": (0.0, 0.85), "horrible": (0.0, 0.9),
    "worst": (0.0, 0.9), "poor": (0.0, 0.6), "wrong": (0.0, 0.6),
    "fail": (0.0, 0.7), "failure": (0.0, 0.75), "negative": (0.0, 0.7),
    "ugly": (0.0, 0.7), "broken": (0.0, 0.6), "lose": (0.0, 0.6),
    "angry": (0.0, 0.8), "disappointing": (0.0, 0.8),
}

_NEGATORS = {"not", "no", "never", "n't", "dont", "don't", "cannot",
             "can't", "isn't", "wasn't", "won't"}


def load_lexicon(path: str) -> Dict[str, Tuple[float, float]]:
    """Parse a SentiWordNet 3.0 TSV (# comments; POS\\tID\\tPos\\tNeg\\t
    term#rank ... columns). Multiple senses of a term average."""
    sums: Dict[str, Tuple[float, float, int]] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            if not line.strip() or line.startswith("#"):
                continue
            cols = line.rstrip("\n").split("\t")
            if len(cols) < 5:
                continue
            try:
                pos_s, neg_s = float(cols[2]), float(cols[3])
            except ValueError:
                continue
            for term in cols[4].split():
                word = term.split("#")[0].replace("_", " ").lower()
                p, n, c = sums.get(word, (0.0, 0.0, 0))
                sums[word] = (p + pos_s, n + neg_s, c + 1)
    return {w: (p / c, n / c) for w, (p, n, c) in sums.items()}


class SentiWordNet:
    """Word-polarity lookup + document classification."""

    def __init__(self,
                 lexicon: Optional[Dict[str, Tuple[float, float]]] = None):
        self.lexicon = dict(_SEED_LEXICON if lexicon is None else lexicon)

    @classmethod
    def from_file(cls, path: str) -> "SentiWordNet":
        return cls(load_lexicon(path))

    def score_word(self, word: str) -> float:
        """Signed polarity in [-1, 1]: positivity - negativity."""
        p, n = self.lexicon.get(word.lower(), (0.0, 0.0))
        return p - n

    def score(self, tokens: Iterable[str]) -> float:
        """Mean signed polarity with single-token negation flips
        ("not good" scores as negative)."""
        total, count, negate = 0.0, 0, False
        for tok in tokens:
            w = tok.lower()
            if w in _NEGATORS:
                negate = True
                continue
            s = self.score_word(w)
            if s != 0.0:
                total += -s if negate else s
                count += 1
            negate = False
        return total / count if count else 0.0

    def classify(self, tokens: Iterable[str],
                 threshold: float = 0.0) -> str:
        s = self.score(tokens)
        if s > threshold:
            return "positive"
        if s < -threshold:
            return "negative"
        return "neutral"
