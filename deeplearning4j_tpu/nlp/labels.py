"""Label-aware document iteration for ParagraphVectors-style training.

TPU-native equivalent of the reference labelaware iterator stack
(reference deeplearning4j-nlp/.../text/documentiterator/
{LabelAwareIterator,LabelledDocument,LabelsSource,BasicLabelAwareIterator,
FileLabelAwareIterator,FilenamesLabelAwareIterator}.java): documents
paired with stable label strings, with LabelsSource generating and
tracking the label universe so doc-labels can live in the same vocab as
words (PV-DBOW labels-in-vocab).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional


class LabelsSource:
    """Generates and stores document labels (reference LabelsSource.java):
    either a fixed template ``DOC_%d`` or user-supplied labels."""

    def __init__(self, template: str = "DOC_%d",
                 labels: Optional[List[str]] = None):
        self.template = template
        self._labels: List[str] = list(labels or [])
        self._counter = 0
        self._fixed = labels is not None

    def next_label(self) -> str:
        if self._fixed:
            if self._counter >= len(self._labels):
                raise IndexError(
                    "LabelsSource exhausted: %d fixed labels but document "
                    "#%d requested one — the corpus has more documents "
                    "than labels (the reference errors here too)"
                    % (len(self._labels), self._counter))
            label = self._labels[self._counter]
        else:
            label = self.template % self._counter
            self._labels.append(label)
        self._counter += 1
        return label

    def get_labels(self) -> List[str]:
        return list(self._labels)

    def store_label(self, label: str) -> None:
        if label not in self._labels:
            self._labels.append(label)

    def reset(self) -> None:
        self._counter = 0
        if not self._fixed:
            self._labels = []


@dataclass
class LabelledDocument:
    """One document + its labels (reference LabelledDocument.java)."""

    content: str
    labels: List[str] = field(default_factory=list)

    @property
    def label(self) -> Optional[str]:
        return self.labels[0] if self.labels else None


class LabelAwareIterator:
    """Iterator of LabelledDocuments (reference LabelAwareIterator.java)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_document(self) -> LabelledDocument:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def get_labels_source(self) -> LabelsSource:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_document()


class BasicLabelAwareIterator(LabelAwareIterator):
    """Wrap a sentence iterator, generating a label per document
    (reference BasicLabelAwareIterator.java Builder)."""

    def __init__(self, sentence_iterator, labels_source: Optional[LabelsSource] = None):
        self.sentences = sentence_iterator
        self.labels_source = labels_source or LabelsSource()

    def has_next(self) -> bool:
        return self.sentences.has_next()

    def next_document(self) -> LabelledDocument:
        content = self.sentences.next_sentence()
        label = getattr(self.sentences, "current_label", None)
        if callable(label):
            lab = label()
            self.labels_source.store_label(lab)
        else:
            lab = self.labels_source.next_label()
        return LabelledDocument(content=content, labels=[lab])

    def reset(self) -> None:
        self.sentences.reset()
        self.labels_source.reset()

    def get_labels_source(self) -> LabelsSource:
        return self.labels_source


class FileLabelAwareIterator(LabelAwareIterator):
    """Directory-per-label corpus layout (reference
    FileLabelAwareIterator.java): ``root/<label>/<doc>.txt`` — each file
    is one document labelled with its parent directory name."""

    def __init__(self, root: str):
        self.root = root
        self.labels_source = LabelsSource(labels=[])
        self._files: List[tuple] = []
        for label in sorted(os.listdir(root)):
            d = os.path.join(root, label)
            if not os.path.isdir(d):
                continue
            self.labels_source.store_label(label)
            for fn in sorted(os.listdir(d)):
                path = os.path.join(d, fn)
                if os.path.isfile(path):
                    self._files.append((label, path))
        self._i = 0

    def has_next(self) -> bool:
        return self._i < len(self._files)

    def next_document(self) -> LabelledDocument:
        label, path = self._files[self._i]
        self._i += 1
        with open(path, encoding="utf-8", errors="replace") as f:
            return LabelledDocument(content=f.read(), labels=[label])

    def reset(self) -> None:
        self._i = 0

    def get_labels_source(self) -> LabelsSource:
        return self.labels_source


class FilenamesLabelAwareIterator(LabelAwareIterator):
    """Flat directory; each file's (base)name is its label (reference
    FilenamesLabelAwareIterator.java)."""

    def __init__(self, root: str, absolute_labels: bool = False):
        self.root = root
        self.labels_source = LabelsSource(labels=[])
        self._files: List[str] = [
            os.path.join(root, fn) for fn in sorted(os.listdir(root))
            if os.path.isfile(os.path.join(root, fn))
        ]
        self.absolute_labels = absolute_labels
        for p in self._files:
            self.labels_source.store_label(self._label_of(p))
        self._i = 0

    def _label_of(self, path: str) -> str:
        return path if self.absolute_labels else os.path.basename(path)

    def has_next(self) -> bool:
        return self._i < len(self._files)

    def next_document(self) -> LabelledDocument:
        path = self._files[self._i]
        self._i += 1
        with open(path, encoding="utf-8", errors="replace") as f:
            return LabelledDocument(content=f.read(),
                                    labels=[self._label_of(path)])

    def reset(self) -> None:
        self._i = 0

    def get_labels_source(self) -> LabelsSource:
        return self.labels_source
