"""Vocabulary: VocabWord, cache, constructor, Huffman coding.

Mirror of reference nlp models/word2vec/{VocabWord,Huffman}.java,
models/word2vec/wordstore/inmemory/InMemoryLookupCache.java and
models/sequencevectors' VocabConstructor. The Huffman tree assigns each
word a binary code + inner-node path for hierarchical softmax; codes are
padded into fixed [V, max_code_len] arrays so the HS loss is one dense
jitted computation (no per-word Java object walks).
"""

from __future__ import annotations

import dataclasses
import heapq
from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np


@dataclasses.dataclass
class VocabWord:
    word: str
    count: int = 1
    index: int = -1
    # Hierarchical-softmax coding (reference VocabWord codes/points).
    codes: List[int] = dataclasses.field(default_factory=list)
    points: List[int] = dataclasses.field(default_factory=list)


class VocabCache:
    """Word <-> index/count store (reference InMemoryLookupCache)."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []

    def add_token(self, word: str, count: int = 1) -> VocabWord:
        vw = self._words.get(word)
        if vw is None:
            vw = VocabWord(word=word, count=0)
            self._words[word] = vw
        vw.count += count
        return vw

    def finalize_indices(self) -> None:
        """Assign indices by descending frequency (reference behavior)."""
        self._by_index = sorted(
            self._words.values(), key=lambda w: (-w.count, w.word)
        )
        for i, vw in enumerate(self._by_index):
            vw.index = i

    def contains_word(self, word: str) -> bool:
        return word in self._words

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return -1 if vw is None else vw.index

    def word_at_index(self, index: int) -> str:
        return self._by_index[index].word

    def num_words(self) -> int:
        return len(self._words)

    def total_word_occurrences(self) -> int:
        return sum(w.count for w in self._words.values())

    def vocab_words(self) -> List[VocabWord]:
        return list(self._by_index)

    def words(self) -> List[str]:
        return [w.word for w in self._by_index]


def build_vocab(
    token_streams: Iterable[List[str]],
    min_word_frequency: int = 5,
) -> VocabCache:
    """Scan a corpus once counting tokens (reference VocabConstructor)."""
    counts: Counter = Counter()
    for tokens in token_streams:
        counts.update(tokens)
    cache = VocabCache()
    for word, c in counts.items():
        if c >= min_word_frequency:
            cache.add_token(word, c)
    cache.finalize_indices()
    return cache


def assign_huffman_codes(cache: VocabCache) -> None:
    """Build the Huffman tree over word frequencies and assign each word
    its binary code + inner-node path (reference Huffman.java)."""
    words = cache.vocab_words()
    if not words:
        return
    if len(words) == 1:
        words[0].codes = [0]
        words[0].points = [0]
        return
    heap: list = []
    for i, vw in enumerate(words):
        heapq.heappush(heap, (vw.count, i, ("leaf", i)))
    next_inner = 0
    nodes = {}  # inner id -> (left, right)
    while len(heap) > 1:
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        nid = next_inner
        next_inner += 1
        nodes[nid] = (n1, n2)
        heapq.heappush(heap, (c1 + c2, len(words) + nid, ("inner", nid)))
    _, _, root = heap[0]

    # Iterative walk to dodge recursion limits for big vocabularies.
    stack = [(root, [], [])]
    while stack:
        node, code, path = stack.pop()
        kind, idx = node
        if kind == "leaf":
            words[idx].codes = code
            words[idx].points = path
            continue
        left, right = nodes[idx]
        stack.append((left, code + [0], path + [idx]))
        stack.append((right, code + [1], path + [idx]))


def huffman_arrays(cache: VocabCache):
    """Pack codes/points into dense padded arrays for the jitted HS loss:
    returns (codes [V, L], points [V, L], mask [V, L]) with L = max code
    length; points index the syn1 inner-node table."""
    words = cache.vocab_words()
    if not words:
        return (np.zeros((0, 1), np.int32),) * 3
    max_len = max(len(w.codes) for w in words)
    v = len(words)
    codes = np.zeros((v, max_len), np.int32)
    points = np.zeros((v, max_len), np.int32)
    mask = np.zeros((v, max_len), np.float32)
    for w in words:
        n = len(w.codes)
        codes[w.index, :n] = w.codes
        points[w.index, :n] = w.points
        mask[w.index, :n] = 1.0
    return codes, points, mask


def unigram_table_probs(cache: VocabCache, power: float = 0.75) -> np.ndarray:
    """Negative-sampling distribution ~ count^0.75 (reference
    InMemoryLookupTable's negative table, as probabilities instead of the
    100M-slot sampling array)."""
    counts = np.array([w.count for w in cache.vocab_words()], np.float64)
    p = counts**power
    return (p / p.sum()).astype(np.float32)
