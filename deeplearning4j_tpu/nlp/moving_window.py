"""Word-level moving-window contexts for window-classification models.

TPU-native equivalent of the reference text/movingwindow package
(reference deeplearning4j-scaleout/deeplearning4j-nlp/.../text/movingwindow/
{Window,Windows,WindowConverter,ContextLabelRetriever,Util}.java and
text/inputsanitation/InputHomogenization.java): fixed-size word windows
around each focus word, padded with begin/end markers, converted to dense
example rows by concatenating embedding vectors — producing static-shape
batches that jit cleanly.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import numpy as np

BEGIN_LABEL = "<{}>"
END_LABEL = "</{}>"
PAD_START = "<s>"
PAD_END = "</s>"
NONE_LABEL = "NONE"


def input_homogenization(sentence: str, preserve_case: bool = False) -> str:
    """Normalize a sentence the way the reference InputHomogenization does:
    strip punctuation/special characters, optionally lower-case."""
    # keep label tags like <LABEL> ... </LABEL> intact (case included)
    parts = re.split(r"(</?[A-Za-z0-9_]+>)", sentence)
    out = []
    for part in parts:
        if re.fullmatch(r"</?[A-Za-z0-9_]+>", part or ""):
            out.append(part)
        else:
            cleaned = re.sub(r"[^\w\s]", "", part)
            out.append(cleaned if preserve_case else cleaned.lower())
    return " ".join(" ".join(out).split())


class Window:
    """One window of words with a focus word in the middle
    (reference movingwindow/Window.java)."""

    def __init__(
        self,
        words: Sequence[str],
        window_size: int,
        median: Optional[int] = None,
        label: str = NONE_LABEL,
    ):
        self.words = list(words)
        self.window_size = window_size
        self.median = len(self.words) // 2 if median is None else median
        self.label = label

    def focus_word(self) -> str:
        return self.words[self.median]

    def as_tokens(self) -> List[str]:
        return list(self.words)

    def __repr__(self) -> str:
        return f"Window({self.words}, focus={self.focus_word()!r}, label={self.label!r})"


def windows(
    sentence_or_tokens,
    window_size: int = 5,
    tokenizer=None,
    label: str = NONE_LABEL,
) -> List[Window]:
    """All windows of ``window_size`` words centred on each token, padded
    with ``<s>``/``</s>`` at the edges (reference movingwindow/Windows.java)."""
    if isinstance(sentence_or_tokens, str):
        if tokenizer is not None:
            tokens = tokenizer.create(sentence_or_tokens).get_tokens()
        else:
            tokens = sentence_or_tokens.split()
    else:
        tokens = list(sentence_or_tokens)
    if not tokens:
        return []
    half = window_size // 2
    padded = [PAD_START] * half + tokens + [PAD_END] * half
    out = []
    for i in range(len(tokens)):
        out.append(Window(padded[i:i + window_size], window_size, label=label))
    return out


def context_label_retriever(sentence: str) -> Tuple[str, List[Tuple[str, str]]]:
    """Split a ``<LABEL> words </LABEL>``-annotated sentence into plain text
    plus (word, label) pairs (reference movingwindow/ContextLabelRetriever.java)."""
    token_re = re.compile(r"<(/?)([A-Za-z0-9_]+)>")
    pairs: List[Tuple[str, str]] = []
    current = NONE_LABEL
    plain: List[str] = []
    for tok in sentence.split():
        m = token_re.fullmatch(tok)
        if m:
            current = NONE_LABEL if m.group(1) else m.group(2)
            continue
        plain.append(tok)
        pairs.append((tok, current))
    return " ".join(plain), pairs


class WindowConverter:
    """Windows → dense example rows using an embedding model as the lookup
    table (reference movingwindow/WindowConverter.java): each example is the
    concatenation of the window's word vectors."""

    @staticmethod
    def as_example_array(window: Window, vec, normalize: bool = False) -> np.ndarray:
        dim = vec.layer_size
        row = np.zeros(dim * window.window_size, dtype=np.float32)
        for i, word in enumerate(window.as_tokens()):
            v = vec.get_word_vector(word)
            if v is None:
                continue
            v = np.asarray(v, dtype=np.float32)
            if normalize:
                n = np.linalg.norm(v)
                if n > 0:
                    v = v / n
            row[i * dim:(i + 1) * dim] = v
        return row

    @staticmethod
    def as_example_matrix(
        windows_list: Sequence[Window], vec, normalize: bool = False
    ) -> np.ndarray:
        if not windows_list:
            return np.zeros((0, 0), dtype=np.float32)
        return np.stack(
            [WindowConverter.as_example_array(w, vec, normalize) for w in windows_list]
        )
