"""SequenceVectors: the generic embedding training engine.

Mirror of reference nlp models/sequencevectors/SequenceVectors.java (866
LoC; fit :100-176) + the learning-algorithm SPI (ElementsLearningAlgorithm
-> SkipGram, learning/impl/elements/SkipGram.java 234 LoC) and the
InMemoryLookupTable hot loop (iterateSample).

TPU inversion of the Hogwild design (SURVEY.md §7 "Hogwild -> synchronous"
hard part): instead of N threads racing on shared syn0/syn1, each epoch
mines (center, context) index pairs host-side, then a jitted step performs
the skip-gram update for a whole batch via gather -> dense HS/NS loss ->
scatter-add, with the learning rate annealed per batch exactly like the
reference's per-word anneal. Deterministic, reproducible, and batched onto
the VPU/MXU. Subsampling of frequent words matches word2vec semantics.
"""

from __future__ import annotations

import functools
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import (
    VocabCache,
    assign_huffman_codes,
    build_vocab,
    huffman_arrays,
    unigram_table_probs,
)

Array = jax.Array


def _sigmoid(x):
    return jax.nn.sigmoid(x)


class SequenceVectors:
    """Trains element embeddings over an iterable of token sequences."""

    def __init__(
        self,
        layer_size: int = 100,
        window: int = 5,
        learning_rate: float = 0.025,
        min_learning_rate: float = 1e-4,
        negative: int = 0,
        use_hierarchic_softmax: bool = True,
        min_word_frequency: int = 5,
        subsampling: float = 0.0,  # reference default: disabled (SequenceVectors.java:206)
        epochs: int = 1,
        batch_size: int = 4096,
        seed: int = 12345,
    ):
        self.layer_size = layer_size
        self.window = window
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        self.min_word_frequency = min_word_frequency
        self.subsampling = subsampling
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed

        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[Array] = None  # [V, D] word vectors
        self.syn1: Optional[Array] = None  # [V, D] HS inner-node weights
        self.syn1neg: Optional[Array] = None  # [V, D] NS context weights
        self._native_vocab = None  # C++ tokenizer hash (lazy, ABI v3)
        self._native_vocab_tried = False

    # ------------------------------------------------------------------
    # Vocab + weights
    # ------------------------------------------------------------------
    def build_vocab_from(self, sequences: Iterable[Sequence[str]]) -> None:
        self.vocab = build_vocab(
            (s.split() if isinstance(s, str) else s for s in sequences),
            self.min_word_frequency)
        if self.use_hs:
            assign_huffman_codes(self.vocab)
        self._native_vocab = None  # rebuilt lazily for the new vocab
        self._native_vocab_tried = False
        self._reset_weights()

    def _reset_weights(self) -> None:
        v = self.vocab.num_words()
        d = self.layer_size
        # Drop compiled-step caches: their closures captured the OLD
        # vocab's Huffman tables / unigram logits, and a re-built vocab
        # would otherwise train against stale (wrong-vocab) indices.
        self.__dict__.pop("_hs_step_cache", None)
        self.__dict__.pop("_ns_step", None)
        self.__dict__.pop("_ns_inner", None)
        key = jax.random.key(self.seed)
        # syn0 ~ U(-0.5, 0.5)/D (reference InMemoryLookupTable.resetWeights)
        self.syn0 = (
            jax.random.uniform(key, (v, d), jnp.float32) - 0.5
        ) / d
        self.syn1 = jnp.zeros((v, d), jnp.float32)
        self.syn1neg = jnp.zeros((v, d), jnp.float32)
        if self.use_hs:
            codes, points, mask = huffman_arrays(self.vocab)
            self._codes = jnp.asarray(codes)
            self._points = jnp.asarray(points)
            self._code_mask = jnp.asarray(mask)
            # host-side copies for the mining path (reading the device
            # arrays there would block behind queued compute on the
            # tunnel transport)
            self._code_len_np = mask.sum(axis=1)
            self._code_lmax = int(codes.shape[1])
        # Negative sampling draws from a PRECOMPUTED unigram table
        # (reference InMemoryLookupTable's table, sized 1e8 there):
        # table[uniform_int] is O(1) per draw, where categorical over
        # [V] logits materializes (B, K, V) gumbel noise — 4e9 floats
        # per batch at V=100k (measured ~130 ms/batch, the large-vocab
        # NS wall; BENCHMARKS.md W2V section). Table quantization of
        # p^0.75 matches the reference's sampling semantics exactly.
        probs = np.asarray(unigram_table_probs(self.vocab), np.float64)
        tsize = int(min(2 ** 24, max(2 ** 20, 16 * v)))
        # Cumulative fill (reference table construction): slot i holds
        # the word whose cumulative p^0.75 mass covers fraction i/tsize
        # — every word gets >= 0 slots with NO truncation bias against
        # the tail (a per-word min-1-then-truncate scheme would cut the
        # rarest words' slots whenever rounding overshoots).
        cum = np.cumsum(probs / probs.sum())
        self._neg_table = jnp.asarray(np.searchsorted(
            cum, (np.arange(tsize) + 0.5) / tsize).astype(np.int32))

    # ------------------------------------------------------------------
    # Pair mining (host side)
    # ------------------------------------------------------------------
    def _keep_probs(self) -> np.ndarray:
        """Frequent-word subsampling keep-probability per vocab index
        (word2vec formula, reference iterateSample's sampling branch)."""
        total = max(1, self.vocab.total_word_occurrences())
        counts = np.array(
            [w.count for w in self.vocab.vocab_words()], np.float64
        )
        if self.subsampling <= 0:
            return np.ones_like(counts)
        f = counts / total
        keep = (np.sqrt(f / self.subsampling) + 1) * self.subsampling / f
        return np.minimum(1.0, keep)

    def _tokenize_corpus(self, sequences: Iterable[Sequence[str]]):
        """Corpus -> (flat vocab-index array, sequence-id array).

        Fast path: the C++ vocab-hash tokenizer (ABI v3,
        native/dl4j_native.cpp dl4j_tokenize) — the corpus is joined
        into one newline-separated buffer with C-speed str.join and
        scanned natively, removing the per-token Python dict lookup
        that dominated round-2 host time (~0.55 s/1M words). Sequences
        may be token lists (tokens must be whitespace-free — true of
        any tokenizer output; the native and fallback paths otherwise
        disagree on how to split them) OR raw whitespace-separated
        strings (the reference's SentenceIterator contract; interior
        newlines are treated as plain spaces, matching str.split)."""
        from deeplearning4j_tpu.native_rt.lib import NativeVocab

        if self._native_vocab is None and self._native_vocab_tried is False:
            self._native_vocab_tried = True
            words = self.vocab.vocab_words()
            self._native_vocab = NativeVocab.create(
                [w.word for w in words],
                np.asarray([w.index for w in words], np.int32))
        if self._native_vocab is not None:
            # Materialize one-shot iterators first: the join consumes
            # them, and a native failure must still be able to fall
            # back (list of refs — cheap).
            if not isinstance(sequences, (list, tuple)):
                sequences = list(sequences)
            text = "\n".join(
                s.replace("\n", " ") if isinstance(s, str)
                else " ".join(s)
                for s in sequences)
            out = self._native_vocab.tokenize(text.encode("utf-8"))
            if out is not None:
                return out
        word_to_idx = {
            w.word: w.index for w in self.vocab.vocab_words()
        }
        flat_parts: List[np.ndarray] = []
        seq_parts: List[np.ndarray] = []
        for sid, tokens in enumerate(sequences):
            if isinstance(tokens, str):
                tokens = tokens.split()
            idxs = [word_to_idx[t] for t in tokens if t in word_to_idx]
            if idxs:
                arr = np.asarray(idxs, np.int32)
                flat_parts.append(arr)
                seq_parts.append(np.full(len(arr), sid, np.int32))
        if not flat_parts:
            return np.empty(0, np.int32), np.empty(0, np.int32)
        return np.concatenate(flat_parts), np.concatenate(seq_parts)

    def _mine_pairs(
        self, sequences: Iterable[Sequence[str]], rng: np.random.Generator
    ):
        flat, seq_id = self._tokenize_corpus(sequences)
        yield from self._mine_pairs_from_ids(flat, seq_id, rng)

    def _mine_pairs_from_ids(
        self, flat: np.ndarray, seq_id: np.ndarray,
        rng: np.random.Generator,
    ):
        """Yield (center_idx, context_idx) int32 arrays in batches, applying
        frequent-word subsampling and the word2vec per-center random window
        shrink. Fully vectorized: the corpus is flattened into one index
        array with sequence ids, and every window offset is one numpy
        slice-compare — no per-token Python loop (this mining is the
        words/sec hot path feeding the jitted update)."""
        if len(flat) == 0:
            return
        keep_prob = self._keep_probs()
        # Native C++ fast path: subsample + window walk + shuffle in one
        # call (native/dl4j_native.cpp dl4j_mine_pairs); numpy below is
        # the portable fallback with identical semantics.
        from deeplearning4j_tpu.native_rt.lib import (
            mine_pairs as _native,
            native_available,
        )

        kp_tok = keep_prob[flat]  # one O(corpus) gather, shared below
        if native_available():
            native = _native(
                flat, seq_id, self.window,
                kp_tok.astype(np.float32) if self.subsampling > 0 else None,
                int(rng.integers(2 ** 63)))
            if native is not None:
                centers, contexts = native
                if len(centers) == 0:
                    return
                yield from self._pad_and_batch(centers, contexts, rng)
                return
        # Subsample frequent words (removal shortens the effective window
        # distance, as in word2vec).
        keep = rng.random(len(flat)) < kp_tok
        flat, seq_id = flat[keep], seq_id[keep]
        if len(flat) == 0:
            return
        # Per-center random window size b in [1, window].
        b = rng.integers(1, self.window + 1, size=len(flat))
        cen_parts: List[np.ndarray] = []
        ctx_parts: List[np.ndarray] = []
        for d in range(1, self.window + 1):
            if d >= len(flat):
                break
            same = seq_id[:-d] == seq_id[d:]
            # (center=i, context=i+d) if d <= b[i]; and the mirror pair.
            m1 = same & (d <= b[:-d])
            m2 = same & (d <= b[d:])
            cen_parts.append(flat[:-d][m1])
            ctx_parts.append(flat[d:][m1])
            cen_parts.append(flat[d:][m2])
            ctx_parts.append(flat[:-d][m2])
        if not cen_parts:
            return  # corpus degenerated to (at most) one surviving token
        centers = np.concatenate(cen_parts)
        contexts = np.concatenate(ctx_parts)
        if len(centers) == 0:
            return
        # Shuffle so batches mix offsets/sequences (SGD quality).
        order = rng.permutation(len(centers))
        centers, contexts = centers[order], contexts[order]
        yield from self._pad_and_batch(centers, contexts, rng)

    # Short-path class bound: centers whose Huffman code fits in this
    # many levels run through a kernel sliced to [:, :L] — under a zipf
    # corpus most pairs take this class, nearly halving the [B, L, D]
    # gather/scatter volume of the padded-to-max path.
    _HS_SHORT_LEN = 8

    def _pad_and_batch(self, centers, contexts, rng):
        """Pad the tail to a full batch by resampling existing pairs, so
        every jitted step sees one static shape (no tail recompiles).
        Yields (centers, contexts, l_max, pair_offset): l_max is the
        Huffman-path slice the HS kernel needs (0 when HS is off — the
        NS kernel ignores it) and pair_offset is the batch's position in
        the PRE-SPLIT shuffled pair order, which the lr anneal is
        computed from — so splitting by code-length class changes
        execution order (each class runs contiguously, avoiding
        per-chunk executable alternation, which measures slow on the
        tunnel transport) without skewing rare-word pairs onto the
        low-lr tail of the schedule."""
        total = len(centers)
        if self.use_hs:
            short = self._code_len_np[centers] <= self._HS_SHORT_LEN
            splits = [
                (centers[short], contexts[short],
                 min(self._HS_SHORT_LEN, self._code_lmax)),
                (centers[~short], contexts[~short], self._code_lmax),
            ]
        else:
            splits = [(centers, contexts, 0)]
        for cen, ctx, lmax in splits:
            n = len(cen)
            if n == 0:
                continue
            rem = n % self.batch_size
            if rem and n > self.batch_size:
                extra = rng.integers(0, n, size=self.batch_size - rem)
                cen = np.concatenate([cen, cen[extra]])
                ctx = np.concatenate([ctx, ctx[extra]])
            n_batches = max(1, len(cen) // self.batch_size)
            for j, s in enumerate(range(0, len(cen), self.batch_size)):
                # pre-split position: batch j of this class sits at
                # fraction (j+0.5)/n_batches of the full shuffled pass
                offset = int((j + 0.5) / n_batches * total)
                yield (
                    cen[s:s + self.batch_size],
                    ctx[s:s + self.batch_size],
                    lmax,
                    offset,
                )

    # ------------------------------------------------------------------
    # Jitted batched skip-gram updates
    # ------------------------------------------------------------------
    def _hs_step(self, l_max: Optional[int] = None):
        """Scanned multi-batch HS update: one dispatch trains S batches
        (centers/contexts [S, B], lrs [S]) via lax.scan — amortizes the
        host->device dispatch latency that would otherwise dominate
        words/sec. ``l_max`` slices the Huffman path tables to the
        batch's code-length class (see _pad_and_batch) — the compiled
        step is cached per class."""
        cache = self.__dict__.setdefault("_hs_step_cache", {})
        if l_max not in cache:
            inner = self._hs_inner(l_max)

            # donate: the embedding tables are dead after each dispatch;
            # without donation every chunk copies [V, D] x2 out.
            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def steps(syn0, syn1, centers, contexts, lrs):
                def body(carry, inp):
                    s0, s1 = carry
                    c, x, lr = inp
                    s0, s1, loss = inner(s0, s1, c, x, lr)
                    return (s0, s1), loss

                (syn0, syn1), losses = jax.lax.scan(
                    body, (syn0, syn1), (centers, contexts, lrs)
                )
                return syn0, syn1, jnp.mean(losses)

            cache[l_max] = steps
        return cache[l_max]

    def _hs_inner(self, l_max: Optional[int] = None):
        codes, points, cmask = self._codes, self._points, self._code_mask
        if l_max is not None and l_max < codes.shape[1]:
            codes = codes[:, :l_max]
            points = points[:, :l_max]
            cmask = cmask[:, :l_max]

        def step(syn0, syn1, centers, contexts, lr):
            # Skip-gram HS: input vector = context word (word2vec trains
            # the *context* against the center's Huffman path).
            h = syn0[contexts]  # [B, D]
            pts = points[centers]  # [B, L]
            cds = codes[centers].astype(jnp.float32)  # [B, L]
            msk = cmask[centers]  # [B, L]
            w = syn1[pts]  # [B, L, D]
            dot = jnp.einsum("bld,bd->bl", w, h)
            # p(code) via sigmoid; gradient of -log-likelihood. The
            # MAX_EXP=6 clamp mirrors the reference's exp-table range
            # (InMemoryLookupTable.iterateSample skips HS updates whose
            # logit falls outside the table): besides fidelity it is
            # the stability brake for BATCHED scatter-adds — without
            # it, hot Huffman roots accumulate thousands of same-sign
            # stale-value updates per batch on real-text frequency
            # distributions and the tables diverge to NaN (measured on
            # the bundled raw_sentences corpus; zipf-synthetic runs
            # were too short to develop it).
            g = (1.0 - cds - _sigmoid(dot)) * msk  # [B, L]
            g = g * (jnp.abs(dot) < 6.0)
            dh = jnp.einsum("bl,bld->bd", g, w)  # accumulate into syn0
            dw = jnp.einsum("bl,bd->bld", g, h)  # into syn1 rows
            syn0 = syn0.at[contexts].add(lr * dh)
            syn1 = syn1.at[pts.reshape(-1)].add(
                lr * dw.reshape(-1, dw.shape[-1])
            )
            loss = -jnp.sum(
                jnp.log(
                    _sigmoid(jnp.where(cds > 0, -dot, dot)) + 1e-10
                )
                * msk
            ) / jnp.maximum(1, centers.shape[0])
            return syn0, syn1, loss

        return step

    @functools.cached_property
    def _ns_step(self):
        """Scanned multi-batch negative-sampling update (see _hs_step)."""
        inner = self._ns_inner

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def steps(syn0, syn1neg, centers, contexts, lrs, rng):
            def body(carry, inp):
                s0, s1, key = carry
                c, x, lr = inp
                key, sub = jax.random.split(key)
                s0, s1, loss = inner(s0, s1, c, x, lr, sub)
                return (s0, s1, key), loss

            (syn0, syn1neg, _), losses = jax.lax.scan(
                body, (syn0, syn1neg, rng), (centers, contexts, lrs)
            )
            return syn0, syn1neg, jnp.mean(losses)

        return steps

    @functools.cached_property
    def _ns_inner(self):
        neg_table = self._neg_table
        k = self.negative

        def step(syn0, syn1neg, centers, contexts, lr, rng):
            h = syn0[contexts]  # [B, D]
            pos = syn1neg[centers]  # [B, D]
            draws = jax.random.randint(
                rng, (centers.shape[0], k), 0, neg_table.shape[0])
            negs = neg_table[draws]  # [B, K]
            wneg = syn1neg[negs]  # [B, K, D]
            dot_pos = jnp.sum(pos * h, axis=-1)  # [B]
            dot_neg = jnp.einsum("bkd,bd->bk", wneg, h)
            # The reference saturates NS gradients outside the
            # exp-table range (iterateSample: g = (label-1)*alpha /
            # (label-0)*alpha at |f| > MAX_EXP) rather than skipping.
            # Under BATCHED scatter-adds saturation is not a brake —
            # sustained +/-1 gradients on hot rows (high-frequency
            # negatives) accumulate stale-value updates until the
            # tables overflow (measured NaN on the bundled
            # raw_sentences corpus). We therefore zero updates outside
            # the table range for NS as well — a documented deviation
            # with the same fixed-range rationale as the table itself.
            in_rng_pos = jnp.abs(dot_pos) < 6.0
            in_rng_neg = jnp.abs(dot_neg) < 6.0
            g_pos = (1.0 - _sigmoid(dot_pos)) * in_rng_pos  # label 1
            g_neg = -_sigmoid(dot_neg) * in_rng_neg  # label 0
            # Exclude accidental positives: the reference's iterateSample
            # skips sampled negatives equal to the target word.
            g_neg = g_neg * (negs != centers[:, None]).astype(g_neg.dtype)
            dh = g_pos[:, None] * pos + jnp.einsum("bk,bkd->bd", g_neg, wneg)
            syn0 = syn0.at[contexts].add(lr * dh)
            syn1neg = syn1neg.at[centers].add(lr * g_pos[:, None] * h)
            syn1neg = syn1neg.at[negs.reshape(-1)].add(
                lr * (g_neg[..., None] * h[:, None, :]).reshape(-1, h.shape[-1])
            )
            loss = -(
                jnp.sum(jnp.log(_sigmoid(dot_pos) + 1e-10))
                + jnp.sum(jnp.log(_sigmoid(-dot_neg) + 1e-10))
            ) / jnp.maximum(1, centers.shape[0])
            return syn0, syn1neg, loss

        return step

    # ------------------------------------------------------------------
    def fit(self, sequences_factory) -> None:
        """Train. ``sequences_factory`` is a zero-arg callable returning a
        fresh iterable of token sequences (one pass per epoch), or a list.
        """
        if not self.use_hs and self.negative <= 0:
            raise ValueError(
                "No training objective: enable hierarchical softmax "
                "(use_hierarchic_softmax=True) and/or negative sampling "
                "(negative > 0)"
            )
        if self.vocab is None:
            seqs = (
                sequences_factory()
                if callable(sequences_factory)
                else sequences_factory
            )
            self.build_vocab_from(seqs)
        total_pairs_est = None
        rng = np.random.default_rng(self.seed)
        key = jax.random.key(self.seed + 1)
        pairs_done = 0
        # Rough anneal denominator: total occurrences * window * epochs.
        denom = max(
            1,
            self.vocab.total_word_occurrences() * self.window * self.epochs,
        )
        def annealed_lrs(pair_offsets):
            fracs = np.asarray(pair_offsets, np.float64) / denom
            return np.maximum(
                self.min_learning_rate,
                self.learning_rate * (1.0 - np.minimum(1.0, fracs)),
            ).astype(np.float32)

        key_box = [key]
        # Fast path: tokenize ONCE and reuse the id-corpus across
        # epochs — the ids (8 B/token) are far smaller than the token
        # strings, and epochs differ only in subsampling/window draws,
        # which happen in the miner. Only taken when BOTH hold:
        # - the corpus is a materialized iterable (a CALLABLE factory
        #   may stream fresh/augmented sequences per epoch — the
        #   documented contract — so it is re-invoked and re-tokenized
        #   each epoch), and
        # - _mine_pairs is not overridden (ParagraphVectors mines
        #   label-word pairs from the sequences themselves and must see
        #   them, not the id arrays).
        plain_miner = type(self)._mine_pairs is SequenceVectors._mine_pairs
        id_corpus = None
        for epoch in range(self.epochs):
            if id_corpus is not None:
                batches = self._mine_pairs_from_ids(*id_corpus, rng)
            else:
                seqs = (
                    sequences_factory()
                    if callable(sequences_factory)
                    else sequences_factory
                )
                if plain_miner and not callable(sequences_factory):
                    id_corpus = self._tokenize_corpus(seqs)
                    batches = self._mine_pairs_from_ids(*id_corpus, rng)
                else:
                    batches = self._mine_pairs(seqs, rng)
            pairs_done = self._dispatch_chunks(
                batches, annealed_lrs, key_box, pairs_done)
        self._pairs_trained = pairs_done

    # batches per device dispatch (see _hs_step docstring)
    _DISPATCH_CHUNK = 64
    # chunks staged on device before their compute is dispatched. On the
    # remote-tunnel PJRT transport a host->device copy BLOCKS until all
    # queued compute drains (measured: 1.8 ms idle vs ~90 ms behind a
    # queued scan), so interleaving upload/compute per chunk serializes
    # the link. Uploading a whole window back-to-back while the device
    # is idle, then dispatching the window's compute, keeps the copies
    # at idle-latency and amortizes the one drain-wait per window.
    # 128 chunks x 64 batches x 8192 pairs x 8 B = ~0.5 GB ceiling.
    _STAGE_WINDOW = 128

    def _dispatch_chunks(self, batches, lr_fn, key_box, pairs_done=0) -> int:
        """Stack mined (centers, contexts) batches into scan chunks,
        upload them window-at-a-time, then run the scanned jitted
        updates per window (see _STAGE_WINDOW for why staging is
        windowed rather than interleaved per chunk — VERDICT round-1
        weak #5). ``lr_fn(pair_offsets)`` maps each batch's global pair
        offset (pre-split epoch position + prior passes) to its
        learning rate; ``key_box`` is a 1-element list holding the RNG
        key (advanced in place). Returns the updated pair count. Shared
        by fit() and train_sequences(). Chunk order is deterministic
        (mining order), so same-seed runs stay reproducible.
        """
        CHUNK = self._DISPATCH_CHUNK
        # lrs are computed at STAGE time from each batch's PRE-SPLIT
        # pair offset (pairs_done at entry = the base of this pass), so
        # every device input — indices AND learning rates — uploads in
        # the idle window and the compute phase dispatches back-to-back
        # with no host->device copy in between to drain the pipeline.
        pass_base = pairs_done
        # The scan dispatches DONATE the embedding tables; an exception
        # mid-dispatch (tunnel error, Ctrl-C) would otherwise leave
        # self.syn0/... bound to deleted buffers. Snapshot to host once
        # per pass (~15 MB, device idle here) and restore on failure so
        # the model stays readable at its pass-entry state.
        backup = (np.asarray(self.syn0), np.asarray(self.syn1),
                  np.asarray(self.syn1neg))
        try:
            return self._dispatch_chunks_inner(
                batches, lr_fn, key_box, pairs_done)
        except BaseException:
            self.syn0 = jnp.asarray(backup[0])
            self.syn1 = jnp.asarray(backup[1])
            self.syn1neg = jnp.asarray(backup[2])
            raise

    def _dispatch_chunks_inner(self, batches, lr_fn, key_box,
                               pairs_done=0) -> int:
        CHUNK = self._DISPATCH_CHUNK
        pass_base = pairs_done

        def stage(group, lmax):
            s, bsize = len(group), len(group[0][0])
            offsets = pass_base + np.asarray(
                [off for _, _, off in group], np.float64)
            entry = (jnp.asarray(np.stack([c for c, _, _ in group])),
                     jnp.asarray(np.stack([x for _, x, _ in group])),
                     jnp.asarray(lr_fn(offsets)),
                     s, bsize, lmax)
            return entry

        def run(staged, pairs_done):
            for cen_d, ctx_d, lrs_d, s, bsize, lmax in staged:
                if self.use_hs:
                    self.syn0, self.syn1, _ = self._hs_step(lmax)(
                        self.syn0, self.syn1, cen_d, ctx_d, lrs_d
                    )
                if self.negative > 0:
                    key_box[0], sub = jax.random.split(key_box[0])
                    self.syn0, self.syn1neg, _ = self._ns_step(
                        self.syn0, self.syn1neg, cen_d, ctx_d, lrs_d, sub
                    )
                pairs_done += s * bsize
            return pairs_done

        staged = []
        pending: dict = {}
        for c, x, lmax, offset in batches:
            buf = pending.setdefault((len(c), lmax), [])
            buf.append((c, x, offset))
            if len(buf) >= CHUNK:
                staged.append(stage(buf, lmax))
                pending[(len(c), lmax)] = []
                if len(staged) >= self._STAGE_WINDOW:
                    pairs_done = run(staged, pairs_done)
                    staged = []
        for (_, lmax), buf in pending.items():
            if buf:
                staged.append(stage(buf, lmax))
        return run(staged, pairs_done)

    def train_sequences(self, sequences, learning_rate=None) -> int:
        """One incremental pass over the given token sequences at a fixed
        learning rate — the ``trainSentence`` granularity the param-server
        performers dispatch at (reference scaleout/perform/.../
        Word2VecPerformer.java:232), vs ``fit``'s full annealed epochs.
        Returns the number of (center, context) pairs trained."""
        if self.vocab is None:
            raise ValueError("build_vocab_from must run before training")
        lr = float(learning_rate if learning_rate is not None
                   else self.learning_rate)
        if not hasattr(self, "_stream_rng"):
            self._stream_rng = np.random.default_rng(self.seed + 7)
            self._stream_key = jax.random.key(self.seed + 11)
        key_box = [self._stream_key]
        done = self._dispatch_chunks(
            self._mine_pairs(sequences, self._stream_rng),
            lambda offsets: np.full((len(offsets),), lr, np.float32),
            key_box,
        )
        self._stream_key = key_box[0]
        return done

    # ------------------------------------------------------------------
    # WordVectors API (reference wordvectors/WordVectors.java)
    # ------------------------------------------------------------------
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        if i < 0:
            return None
        return np.asarray(self.syn0[i])

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        if denom == 0:
            return 0.0
        return float(np.dot(va, vb) / denom)

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            v = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
            if v is None:
                return []
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        m = np.asarray(self.syn0)
        norms = np.linalg.norm(m, axis=1) * (np.linalg.norm(v) + 1e-12)
        sims = m @ v / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            if w in exclude:
                continue
            out.append(w)
            if len(out) >= top_n:
                break
        return out
