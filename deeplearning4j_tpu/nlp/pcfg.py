"""Trainable PCFG constituency parser (CKY decode).

The reference's parse-tree pipeline runs a trained constituency parser
behind UIMA (reference text/corpora + TreeParser / TreeVectorizer,
models/rntn consuming its trees); round 1 stood that in with the
deterministic chunker in nlp/tree_parser.py. This module supplies the
trainable statistical counterpart: a PCFG induced from example
``ParseTree``s (rules counted off collapsed-unary, binarized trees —
CNF via the same transformers the RNTN pipeline uses) and decoded with
CKY over log probabilities. Out-of-vocabulary words back off to a
uniform preterminal distribution; sentences with no full-span parse
fall back to the chunker so downstream consumers (TreeVectorizer →
RNTN) always receive a tree.
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nlp.tree_parser import (
    BinarizeTreeTransformer,
    CollapseUnaries,
    ParseTree,
    TreeParser,
)


class PcfgParser:
    _pretrained_singleton = None

    def __init__(self, fallback: Optional[TreeParser] = None):
        self.fallback = fallback or TreeParser()
        self._fitted = False

    @classmethod
    def pretrained(cls) -> "PcfgParser":
        """Out-of-the-box parser induced from the bundled treebank
        (deeplearning4j_tpu/nlp/data) — the analogue of the reference's
        shipped ClearTK/OpenNLP parsing models
        (text/corpora/treeparser/TreeParser.java), which make parsing
        work with zero user setup. Induces in milliseconds on first
        call, then cached for the process."""
        if cls._pretrained_singleton is None:
            from deeplearning4j_tpu.nlp.data import load_treebank

            cls._pretrained_singleton = cls().fit(load_treebank())
        return cls._pretrained_singleton

    # -- grammar induction --------------------------------------------
    def fit(self, trees: Iterable[ParseTree]) -> "PcfgParser":
        binarize = BinarizeTreeTransformer()
        collapse = CollapseUnaries()
        binary: Dict[str, Counter] = defaultdict(Counter)  # A -> (B, C)
        lexicon: Dict[str, Counter] = defaultdict(Counter)  # T -> word
        roots: Counter = Counter()
        n_trees = 0
        for tree in trees:
            t = binarize.transform(collapse.transform(tree))
            roots[t.label] += 1
            n_trees += 1
            self._count(t, binary, lexicon)
        if not n_trees:
            raise ValueError("no training trees")

        # Freeze plain dicts first: defaultdict lookups below would
        # otherwise insert empty entries (every binary nonterminal would
        # leak into the preterminal set and every preterminal into the
        # binary table).
        binary = dict(binary)
        lexicon = dict(lexicon)
        empty: Counter = Counter()
        self._preterminals: List[str] = sorted(lexicon)
        self._log_binary: Dict[Tuple[str, str], List[Tuple[str, float]]]
        self._log_binary = defaultdict(list)
        for a, rhs in binary.items():
            total = sum(rhs.values()) + sum(lexicon.get(a, empty).values())
            for (b, c), n in rhs.items():
                self._log_binary[(b, c)].append((a, math.log(n / total)))
        self._log_lex: Dict[str, List[Tuple[str, float]]] = defaultdict(list)
        for t, words in lexicon.items():
            total = sum(words.values()) + sum(binary.get(t, empty).values())
            for w, n in words.items():
                self._log_lex[w].append((t, math.log(n / total)))
        total_roots = sum(roots.values())
        self._log_root = {a: math.log(n / total_roots)
                          for a, n in roots.items()}
        self._fitted = True
        return self

    def _count(self, node: ParseTree, binary, lexicon) -> None:
        if node.is_leaf():
            return
        kids = node.children
        if len(kids) == 1 and kids[0].is_leaf():
            lexicon[node.label][kids[0].word.lower()] += 1
            return
        if len(kids) == 2:
            binary[node.label][(kids[0].label, kids[1].label)] += 1
        elif len(kids) == 1:
            # residual unary over a non-leaf: treat as X -> (Y, Y) is
            # wrong; instead skip through (collapse should have removed
            # these, but be robust)
            self._count(kids[0], binary, lexicon)
            return
        for k in kids:
            self._count(k, binary, lexicon)

    # -- CKY decode ----------------------------------------------------
    def parse_tokens(self, tokens: Sequence[str]) -> Optional[ParseTree]:
        """Best full-span tree for the token list, or None if the
        grammar cannot cover it."""
        if not self._fitted:
            raise ValueError("fit() must run first")
        n = len(tokens)
        if n == 0:
            return None
        # chart[(i, j)]: label -> (logp, back) where back is either
        # ("lex", word) or (k, left_label, right_label)
        chart: List[Dict[str, Tuple[float, tuple]]] = [
            {} for _ in range(n * (n + 1))]

        def cell(i, j):
            return chart[i * (n + 1) + j]

        oov_logp = math.log(1.0 / max(1, len(self._preterminals)))
        for i, w in enumerate(tokens):
            entries = self._log_lex.get(w.lower())
            c = cell(i, i + 1)
            if entries:
                for t, lp in entries:
                    if lp > c.get(t, (-math.inf,))[0]:
                        c[t] = (lp, ("lex", w))
            else:
                for t in self._preterminals:
                    c[t] = (oov_logp, ("lex", w))
        for span in range(2, n + 1):
            for i in range(0, n - span + 1):
                j = i + span
                c = cell(i, j)
                for k in range(i + 1, j):
                    left, right = cell(i, k), cell(k, j)
                    if not left or not right:
                        continue
                    for bl, (lpb, _) in left.items():
                        for cl, (lpc, _) in right.items():
                            for a, lpr in self._log_binary.get(
                                    (bl, cl), ()):
                                score = lpr + lpb + lpc
                                if score > c.get(a, (-math.inf,))[0]:
                                    c[a] = (score, (k, bl, cl))
        top = cell(0, n)
        best, best_score = None, -math.inf
        for a, (lp, _) in top.items():
            if a not in self._log_root:
                continue  # only labels observed as tree roots qualify
            score = lp + self._log_root[a]
            if score > best_score:
                best, best_score = a, score
        if best is None:
            return None
        return self._debinarize(self._build(0, n, best, cell))

    def _debinarize(self, tree: ParseTree) -> ParseTree:
        """Inline the left-factored ``@label`` intermediates CKY decodes
        in (grammar space) back into n-ary constituents (surface
        space) — the reference's TreeParser hands consumers n-ary
        trees; RNTN's TreeVectorizer re-binarizes on its own."""
        if tree.word is not None:
            return tree
        kids = []
        for c in tree.children:
            c = self._debinarize(c)
            if c.label.startswith("@"):
                kids.extend(c.children)
            else:
                kids.append(c)
        return ParseTree(label=tree.label, children=kids)

    def _build(self, i, j, label, cell) -> ParseTree:
        _, back = cell(i, j)[label]
        if back[0] == "lex":
            return ParseTree(
                label=label,
                children=[ParseTree(label=label, word=back[1])])
        k, bl, cl = back
        return ParseTree(label=label, children=[
            self._build(i, k, bl, cell),
            self._build(k, j, cl, cell),
        ])

    # -- TreeParser-compatible surface --------------------------------
    def parse(self, sentence: str) -> ParseTree:
        tokens = [t for t in sentence.split() if t]
        tree = self.parse_tokens(tokens) if self._fitted else None
        if tree is None:
            return self.fallback.parse(sentence)
        return tree

    def get_trees(self, text: str) -> List[ParseTree]:
        return [self.parse(s) for s in text.split(".") if s.strip()]
