"""Tokenizers + factories + preprocessors.

Mirror of reference nlp text/tokenization/** (DefaultTokenizer,
NGramTokenizer, PosUimaTokenizer, factories, CommonPreprocessor/
EndingPreProcessor). The reference's UIMA-backed tokenizers ride a
ClearTK POS-tagger pipeline; UIMA is a JVM-only stack, so the
POS-filtered tokenizer here uses a self-contained rule tagger with the
same observable contract: tokens whose POS is outside the allowed set
collapse to a placeholder.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional


class TokenPreProcess:
    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation (reference CommonPreprocessor)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer from the reference (strips common English endings)."""

    def pre_process(self, token: str) -> str:
        for ending in ("ing", "ed", "es", "s", "ly"):
            if token.endswith(ending) and len(token) > len(ending) + 2:
                return token[: -len(ending)]
        return token


class Tokenizer:
    def __init__(self, tokens: List[str],
                 preprocessor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = preprocessor

    def get_tokens(self) -> List[str]:
        if self._pre is None:
            return list(self._tokens)
        out = []
        for t in self._tokens:
            p = self._pre.pre_process(t)
            if p:
                out.append(p)
        return out

    def count_tokens(self) -> int:
        return len(self._tokens)


class TokenizerFactory:
    def __init__(self):
        self.preprocessor: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self.preprocessor = pre

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenization (reference DefaultTokenizer wraps
    StringTokenizer)."""

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text.split(), self.preprocessor)


class RuleBasedPosTagger:
    """Tiny deterministic POS tagger (closed-class lexicon + suffix
    rules). Stands in for the reference's UIMA/ClearTK tagger behind
    PosUimaTokenizer (text/tokenization/tokenizer/PosUimaTokenizer.java);
    intentionally coarse — callers only branch on the tag class. For a
    TRAINABLE statistical tagger with the same ``tag`` interface plus
    contextual ``tag_sequence``, use nlp/pos.py HmmPosTagger."""

    _DETERMINERS = {"the", "a", "an", "this", "that", "these", "those"}
    _PRONOUNS = {"i", "you", "he", "she", "it", "we", "they", "me",
                 "him", "her", "us", "them", "its", "his", "their", "my",
                 "your", "our"}
    _PREPOSITIONS = {"in", "on", "at", "by", "for", "with", "about",
                     "against", "between", "into", "through", "during",
                     "of", "to", "from", "up", "down", "over", "under"}
    _CONJUNCTIONS = {"and", "or", "but", "nor", "so", "yet", "because",
                     "although", "while", "if"}
    _MODALS = {"can", "could", "will", "would", "shall", "should", "may",
               "might", "must"}
    _BE_VERBS = {"is", "am", "are", "was", "were", "be", "been", "being",
                 "has", "have", "had", "do", "does", "did"}
    _COMMON_VERBS = {"run", "runs", "ran", "go", "goes", "went", "sleep",
                     "sleeps", "sit", "sits", "sat", "eat", "eats", "ate",
                     "jump", "jumps", "bark", "barks", "say", "says",
                     "said", "make", "makes", "made", "take", "takes",
                     "took", "see", "sees", "saw", "come", "comes",
                     "came", "get", "gets", "got", "know", "knows",
                     "knew", "think", "thinks", "look", "looks", "want",
                     "wants", "give", "gives", "gave", "find", "finds",
                     "found", "tell", "tells", "told", "work", "works",
                     "seem", "seems", "feel", "feels", "felt", "leave",
                     "leaves", "left", "keep", "keeps", "kept", "let",
                     "lets", "begin", "begins", "began", "show", "shows",
                     "hear", "hears", "heard", "play", "plays", "move",
                     "moves", "like", "likes", "live", "lives", "hold",
                     "holds", "held", "write", "writes", "wrote", "read",
                     "reads", "speak", "speaks", "spoke", "grow", "grows",
                     "grew", "walk", "walks", "win", "wins", "won",
                     "love", "loves", "hate", "hates", "buy", "buys",
                     "bought", "build", "builds", "built", "fall",
                     "falls", "fell"}
    _COMMON_ADVERBS = {"fast", "very", "quite", "too", "also", "now",
                       "then", "here", "there", "well", "often", "never",
                       "always", "soon", "again", "still", "just", "not"}

    def tag(self, token: str) -> str:
        w = token.lower()
        if not w:
            return "NONE"
        if w in self._DETERMINERS:
            return "DT"
        if w in self._PRONOUNS:
            return "PRP"
        if w in self._PREPOSITIONS:
            return "IN"
        if w in self._CONJUNCTIONS:
            return "CC"
        if w in self._MODALS:
            return "MD"
        if w in self._BE_VERBS or w in self._COMMON_VERBS:
            return "VB"
        if w[0].isdigit():
            return "CD"
        if w.endswith("ly") or w in self._COMMON_ADVERBS:
            return "RB"
        if w.endswith(("ing", "ed")) and len(w) > 4:
            return "VB"
        if w.endswith(("ous", "ful", "ive", "able", "ible", "al", "ic")):
            return "JJ"
        return "NN"


class PosTokenizerFactory(TokenizerFactory):
    """Keeps tokens whose POS tag is in ``allowed_pos``; others become
    a placeholder so window offsets are preserved — the reference
    PosUimaTokenizer's behavior for its moving-window features."""

    PLACEHOLDER = "NONE"

    def __init__(self, allowed_pos: List[str],
                 tagger: Optional[RuleBasedPosTagger] = None):
        super().__init__()
        self.allowed_pos = set(allowed_pos)
        self.tagger = tagger or RuleBasedPosTagger()

    def create(self, text: str) -> Tokenizer:
        # Preprocess/tag first; placeholders are exempt from further
        # preprocessing so window offsets survive intact (a preprocessed
        # token that becomes empty also collapses to the placeholder
        # rather than being dropped).
        kept = []
        for w in text.split():
            token = (self.preprocessor.pre_process(w)
                     if self.preprocessor else w)
            if token and self.tagger.tag(token) in self.allowed_pos:
                kept.append(token)
            else:
                kept.append(self.PLACEHOLDER)
        return Tokenizer(kept, None)


class NGramTokenizerFactory(TokenizerFactory):
    """Word n-grams over the base tokenization (reference NGramTokenizer)."""

    def __init__(self, n_min: int = 1, n_max: int = 2):
        super().__init__()
        self.n_min = n_min
        self.n_max = n_max

    def create(self, text: str) -> Tokenizer:
        words = text.split()
        grams: List[str] = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(words) - n + 1):
                grams.append(" ".join(words[i : i + n]))
        return Tokenizer(grams, self.preprocessor)
