"""Tokenizers + factories + preprocessors.

Mirror of reference nlp text/tokenization/** (DefaultTokenizer,
NGramTokenizer, factories, CommonPreprocessor/EndingPreProcessor).
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional


class TokenPreProcess:
    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation (reference CommonPreprocessor)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer from the reference (strips common English endings)."""

    def pre_process(self, token: str) -> str:
        for ending in ("ing", "ed", "es", "s", "ly"):
            if token.endswith(ending) and len(token) > len(ending) + 2:
                return token[: -len(ending)]
        return token


class Tokenizer:
    def __init__(self, tokens: List[str],
                 preprocessor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = preprocessor

    def get_tokens(self) -> List[str]:
        if self._pre is None:
            return list(self._tokens)
        out = []
        for t in self._tokens:
            p = self._pre.pre_process(t)
            if p:
                out.append(p)
        return out

    def count_tokens(self) -> int:
        return len(self._tokens)


class TokenizerFactory:
    def __init__(self):
        self.preprocessor: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self.preprocessor = pre

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenization (reference DefaultTokenizer wraps
    StringTokenizer)."""

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text.split(), self.preprocessor)


class NGramTokenizerFactory(TokenizerFactory):
    """Word n-grams over the base tokenization (reference NGramTokenizer)."""

    def __init__(self, n_min: int = 1, n_max: int = 2):
        super().__init__()
        self.n_min = n_min
        self.n_max = n_max

    def create(self, text: str) -> Tokenizer:
        words = text.split()
        grams: List[str] = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(words) - n + 1):
                grams.append(" ".join(words[i : i + n]))
        return Tokenizer(grams, self.preprocessor)
