"""Sentence/document iterators.

Mirror of reference nlp text/sentenceiterator/** (BasicLineIterator,
CollectionSentenceIterator, FileSentenceIterator, LineSentenceIterator,
preprocessors, label-aware variants).
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, Iterator, List, Optional


class SentencePreProcessor:
    def pre_process(self, sentence: str) -> str:
        raise NotImplementedError


class SentenceIterator:
    def __init__(self):
        self.preprocessor: Optional[Callable[[str], str]] = None

    def _apply(self, s: str) -> str:
        return self.preprocessor(s) if self.preprocessor else s

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            yield self.next_sentence()

    def next_sentence(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        super().__init__()
        self._list: List[str] = list(sentences)
        self._i = 0

    def next_sentence(self) -> str:
        s = self._list[self._i]
        self._i += 1
        return self._apply(s)

    def has_next(self) -> bool:
        return self._i < len(self._list)

    def reset(self) -> None:
        self._i = 0


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (reference LineSentenceIterator /
    BasicLineIterator)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._f = None
        self._next: Optional[str] = None
        self.reset()

    def _advance(self) -> None:
        line = self._f.readline()
        while line and not line.strip():
            line = self._f.readline()
        self._next = line.strip() if line else None

    def next_sentence(self) -> str:
        s = self._next
        self._advance()
        return self._apply(s)

    def has_next(self) -> bool:
        return self._next is not None

    def reset(self) -> None:
        if self._f:
            self._f.close()
        self._f = open(self.path, "r", encoding="utf-8", errors="replace")
        self._advance()


class FileSentenceIterator(SentenceIterator):
    """Every line of every file under a directory (reference
    FileSentenceIterator)."""

    def __init__(self, directory: str):
        super().__init__()
        self.directory = directory
        self.reset()

    def _files(self) -> List[str]:
        out = []
        for root, _, files in os.walk(self.directory):
            for f in sorted(files):
                out.append(os.path.join(root, f))
        return out

    def reset(self) -> None:
        self._lines: List[str] = []
        for path in self._files():
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                self._lines.extend(
                    line.strip() for line in f if line.strip()
                )
        self._i = 0

    def next_sentence(self) -> str:
        s = self._lines[self._i]
        self._i += 1
        return self._apply(s)

    def has_next(self) -> bool:
        return self._i < len(self._lines)


class LabelAwareSentenceIterator(SentenceIterator):
    """Sentence + current label, for ParagraphVectors (reference
    labelaware variants)."""

    def current_label(self) -> str:
        raise NotImplementedError


class LabelledCollectionSentenceIterator(LabelAwareSentenceIterator):
    def __init__(self, sentences: List[str], labels: List[str]):
        super().__init__()
        assert len(sentences) == len(labels)
        self._sentences = sentences
        self._labels = labels
        self._i = 0

    def next_sentence(self) -> str:
        s = self._sentences[self._i]
        self._i += 1
        return self._apply(s)

    def current_label(self) -> str:
        return self._labels[max(0, self._i - 1)]

    def has_next(self) -> bool:
        return self._i < len(self._sentences)

    def reset(self) -> None:
        self._i = 0
