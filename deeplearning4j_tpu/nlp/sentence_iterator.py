"""Sentence/document iterators.

Mirror of reference nlp text/sentenceiterator/** (BasicLineIterator,
CollectionSentenceIterator, FileSentenceIterator, LineSentenceIterator,
preprocessors, label-aware variants).
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, Iterator, List, Optional


class SentencePreProcessor:
    def pre_process(self, sentence: str) -> str:
        raise NotImplementedError


class SentenceIterator:
    def __init__(self):
        self.preprocessor: Optional[Callable[[str], str]] = None

    def _apply(self, s: str) -> str:
        return self.preprocessor(s) if self.preprocessor else s

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            yield self.next_sentence()

    def next_sentence(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        super().__init__()
        self._list: List[str] = list(sentences)
        self._i = 0

    def next_sentence(self) -> str:
        s = self._list[self._i]
        self._i += 1
        return self._apply(s)

    def has_next(self) -> bool:
        return self._i < len(self._list)

    def reset(self) -> None:
        self._i = 0


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (reference LineSentenceIterator /
    BasicLineIterator)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._f = None
        self._next: Optional[str] = None
        self.reset()

    def _advance(self) -> None:
        line = self._f.readline()
        while line and not line.strip():
            line = self._f.readline()
        self._next = line.strip() if line else None

    def next_sentence(self) -> str:
        s = self._next
        self._advance()
        return self._apply(s)

    def has_next(self) -> bool:
        return self._next is not None

    def reset(self) -> None:
        if self._f:
            self._f.close()
        self._f = open(self.path, "r", encoding="utf-8", errors="replace")
        self._advance()


class FileSentenceIterator(SentenceIterator):
    """Every line of every file under a directory (reference
    FileSentenceIterator)."""

    def __init__(self, directory: str):
        super().__init__()
        self.directory = directory
        self.reset()

    def _files(self) -> List[str]:
        out = []
        for root, _, files in os.walk(self.directory):
            for f in sorted(files):
                out.append(os.path.join(root, f))
        return out

    def reset(self) -> None:
        self._lines: List[str] = []
        for path in self._files():
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                self._lines.extend(
                    line.strip() for line in f if line.strip()
                )
        self._i = 0

    def next_sentence(self) -> str:
        s = self._lines[self._i]
        self._i += 1
        return self._apply(s)

    def has_next(self) -> bool:
        return self._i < len(self._lines)


class LabelAwareSentenceIterator(SentenceIterator):
    """Sentence + current label, for ParagraphVectors (reference
    labelaware variants)."""

    def current_label(self) -> str:
        raise NotImplementedError


class LabelledCollectionSentenceIterator(LabelAwareSentenceIterator):
    def __init__(self, sentences: List[str], labels: List[str]):
        super().__init__()
        assert len(sentences) == len(labels)
        self._sentences = sentences
        self._labels = labels
        self._i = 0

    def next_sentence(self) -> str:
        s = self._sentences[self._i]
        self._i += 1
        return self._apply(s)

    def current_label(self) -> str:
        return self._labels[max(0, self._i - 1)]

    def has_next(self) -> bool:
        return self._i < len(self._sentences)

    def reset(self) -> None:
        self._i = 0


class AggregatingSentenceIterator(SentenceIterator):
    """Chain several sentence iterators into one stream (reference
    sentenceiterator/AggregatingSentenceIterator.java)."""

    def __init__(self, *iterators: SentenceIterator):
        super().__init__()
        self._iterators = list(iterators)
        self._cur = 0

    def _advance(self) -> None:
        while (self._cur < len(self._iterators)
               and not self._iterators[self._cur].has_next()):
            self._cur += 1

    def has_next(self) -> bool:
        self._advance()
        return self._cur < len(self._iterators)

    def next_sentence(self) -> str:
        self._advance()
        return self._apply(self._iterators[self._cur].next_sentence())

    def reset(self) -> None:
        for it in self._iterators:
            it.reset()
        self._cur = 0


class StreamLineIterator(SentenceIterator):
    """Sentences from a text stream/file-like object, ``batch_of`` lines
    joined per sentence (reference sentenceiterator/StreamLineIterator.java
    over a DocumentIterator's InputStream)."""

    def __init__(self, stream, batch_of: int = 1):
        super().__init__()
        self._stream = stream
        self.batch_of = max(1, batch_of)
        self._head: Optional[str] = None
        self._advance()

    def _advance(self) -> None:
        """Lazily read the next non-blank line (no full materialization)."""
        for line in self._stream:
            if line.strip():
                self._head = line.rstrip("\n")
                return
        self._head = None

    def has_next(self) -> bool:
        return self._head is not None

    def next_sentence(self) -> str:
        chunk = []
        for _ in range(self.batch_of):
            if self._head is None:
                break
            chunk.append(self._head)
            self._advance()
        return self._apply(" ".join(chunk))

    def reset(self) -> None:
        self._stream.seek(0)
        self._advance()


class PrefetchingSentenceIterator(SentenceIterator):
    """Background-thread prefetch into a bounded queue (reference
    sentenceiterator/PrefetchingSentenceIterator.java): hides tokenizer/IO
    latency from the training loop the way AsyncDataSetIterator hides
    host->device feed latency."""

    def __init__(self, base: SentenceIterator, fetch_size: int = 100):
        super().__init__()
        self.base = base
        self.fetch_size = fetch_size
        self._queue = None
        self._thread = None
        self._stop = None
        self._done = False
        self._start()

    def _start(self) -> None:
        import queue
        import threading

        q = self._queue = queue.Queue(maxsize=self.fetch_size)
        stop = self._stop = threading.Event()
        self._done = False
        sentinel = self._sentinel = object()
        base = self.base

        # The worker binds q/stop/sentinel locally: after reset() swaps in
        # a new queue, a lingering old thread can only touch its own.
        def worker():
            try:
                while base.has_next() and not stop.is_set():
                    s = base.next_sentence()
                    while not stop.is_set():
                        try:
                            q.put(s, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            finally:
                # normal end: block until the consumer makes room; stopped
                # end: best effort only (reset() is draining, nobody waits)
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                else:
                    try:
                        q.put_nowait(sentinel)
                    except queue.Full:
                        pass

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()
        self._head = None
        self._pull()

    def _pull(self) -> None:
        item = self._queue.get()
        if item is self._sentinel:
            self._head = None
            self._done = True
        else:
            self._head = item

    def has_next(self) -> bool:
        return not self._done

    def next_sentence(self) -> str:
        s = self._head
        self._pull()
        return self._apply(s)

    def reset(self) -> None:
        import queue

        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            # drain so a put()-blocked worker can observe the stop flag
            while self._thread.is_alive():
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
                self._thread.join(timeout=0.05)
        self.base.reset()
        self._start()


class SynchronizedSentenceIterator(SentenceIterator):
    """Lock-guarded wrapper for sharing one iterator across threads
    (reference sentenceiterator/SynchronizedSentenceIterator.java)."""

    def __init__(self, base: SentenceIterator):
        super().__init__()
        import threading

        self.base = base
        self._lock = threading.Lock()

    def has_next(self) -> bool:
        with self._lock:
            return self.base.has_next()

    def next_sentence(self) -> str:
        with self._lock:
            return self._apply(self.base.next_sentence())

    def next_sentence_if_any(self):
        """Atomic has_next+next, the race-free form threads should use."""
        with self._lock:
            if not self.base.has_next():
                return None
            return self._apply(self.base.next_sentence())

    def reset(self) -> None:
        with self._lock:
            self.base.reset()
