"""Nearest-neighbor / similarity utilities over trained word vectors.

TPU-native equivalent of the reference ModelUtils SPI (reference
deeplearning4j-nlp/.../models/embeddings/reader/impl/
{BasicModelUtils,FlatModelUtils,TreeModelUtils}.java): pluggable
``words_nearest``/``similarity`` strategies over a fitted embedding model
(anything with ``vocab``, ``syn0`` and ``get_word_vector`` — SequenceVectors,
Word2Vec, GloVe, ParagraphVectors).

- BasicModelUtils: cosine similarity with mean-subtraction for multi-word
  positive/negative queries (the king-queen analogy form).
- FlatModelUtils: brute-force over a pre-normalized matrix — one [V,D]@[D]
  matvec, exact.
- TreeModelUtils: VPTree-indexed search — sublinear queries, the structure
  the reference borrows from the UI's nearest-neighbors view.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

Query = Union[str, Sequence[str], np.ndarray]


class ModelUtils:
    """``init(model)`` then ``words_nearest``/``similarity``."""

    def __init__(self):
        self.model = None

    def init(self, model) -> "ModelUtils":
        self.model = model
        return self

    # -- shared helpers --------------------------------------------------
    def _vector_of(self, query: Query, exclude: set) -> Optional[np.ndarray]:
        if isinstance(query, str):
            exclude.add(query)
            v = self.model.get_word_vector(query)
            return None if v is None else np.asarray(v, np.float64)
        if isinstance(query, np.ndarray):
            return query.astype(np.float64)
        vecs = []
        for w in query:
            exclude.add(w)
            v = self.model.get_word_vector(w)
            if v is not None:
                vecs.append(np.asarray(v, np.float64))
        return np.mean(vecs, axis=0) if vecs else None

    def similarity(self, a: str, b: str) -> float:
        va = self.model.get_word_vector(a)
        vb = self.model.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(np.dot(va, vb) / denom) if denom else 0.0

    def words_nearest(self, query: Query, top_n: int = 10) -> List[str]:
        raise NotImplementedError


class BasicModelUtils(ModelUtils):
    """Cosine brute force; supports positive/negative word-algebra via
    ``words_nearest(positive, negative, top_n)`` (reference
    BasicModelUtils.wordsNearest)."""

    def words_nearest(self, query: Query, top_n: int = 10,
                      negative: Sequence[str] = ()) -> List[str]:
        exclude: set = set()
        v = self._vector_of(query, exclude)
        if v is None:
            return []
        for w in negative:
            exclude.add(w)
            nv = self.model.get_word_vector(w)
            if nv is not None:
                v = v - np.asarray(nv, np.float64)
        m = np.asarray(self.model.syn0, np.float64)
        sims = (m @ v) / (
            np.linalg.norm(m, axis=1) * (np.linalg.norm(v) + 1e-12) + 1e-12)
        out = []
        for i in np.argsort(-sims):
            w = self.model.vocab.word_at_index(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out


class FlatModelUtils(ModelUtils):
    """Pre-normalized flat matrix: query = one matvec (reference
    FlatModelUtils — "the fastest exact" variant)."""

    def init(self, model) -> "FlatModelUtils":
        super().init(model)
        m = np.asarray(model.syn0, np.float64)
        self._norm = m / (np.linalg.norm(m, axis=1, keepdims=True) + 1e-12)
        return self

    def words_nearest(self, query: Query, top_n: int = 10) -> List[str]:
        exclude: set = set()
        v = self._vector_of(query, exclude)
        if v is None:
            return []
        v = v / (np.linalg.norm(v) + 1e-12)
        sims = self._norm @ v
        out = []
        for i in np.argsort(-sims):
            w = self.model.vocab.word_at_index(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out


class TreeModelUtils(ModelUtils):
    """VPTree-indexed nearest neighbors (reference TreeModelUtils over the
    same VPTree the nearest-neighbors UI uses)."""

    def init(self, model) -> "TreeModelUtils":
        from deeplearning4j_tpu.clustering.vptree import VPTree

        super().init(model)
        self._words = model.vocab.words()
        self._tree = VPTree(np.asarray(model.syn0, np.float64),
                            similarity="cosine")
        return self

    def words_nearest(self, query: Query, top_n: int = 10) -> List[str]:
        exclude: set = set()
        v = self._vector_of(query, exclude)
        if v is None:
            return []
        # over-fetch to survive excluded query words
        hits = self._tree.knn(v, min(top_n + len(exclude),
                                     len(self._words)))
        out = [self._words[i] for _, i in hits
               if self._words[i] not in exclude]
        return out[:top_n]
