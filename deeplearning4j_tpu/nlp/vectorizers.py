"""Text vectorizers: bag-of-words + TF-IDF.

Mirror of reference nlp bagofwords/vectorizer/{BagOfWordsVectorizer,
TfidfVectorizer}.java (which back the text-classification pipeline and the
reference's Lucene inverted index statistics).
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab


class BaseTextVectorizer:
    def __init__(
        self,
        tokenizer_factory: Optional[TokenizerFactory] = None,
        min_word_frequency: int = 1,
    ):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.vocab: Optional[VocabCache] = None
        self._doc_freq: Optional[np.ndarray] = None
        self._n_docs = 0

    def _tokenize(self, text: str) -> List[str]:
        return self.tokenizer_factory.create(text).get_tokens()

    def fit(self, texts: Iterable[str]) -> "BaseTextVectorizer":
        token_docs = [self._tokenize(t) for t in texts]
        self.vocab = build_vocab(token_docs, self.min_word_frequency)
        v = self.vocab.num_words()
        df = np.zeros((v,), np.float64)
        for toks in token_docs:
            for i in {self.vocab.index_of(t) for t in toks if self.vocab.contains_word(t)}:
                df[i] += 1
        self._doc_freq = df
        self._n_docs = len(token_docs)
        return self

    def transform(self, texts: Sequence[str]) -> np.ndarray:
        raise NotImplementedError

    def fit_transform(
        self, texts: Sequence[str], labels: Optional[np.ndarray] = None
    ):
        self.fit(texts)
        x = self.transform(texts)
        if labels is None:
            return x
        return DataSet(x, labels)

    def _counts(self, texts: Sequence[str]) -> np.ndarray:
        v = self.vocab.num_words()
        out = np.zeros((len(texts), v), np.float32)
        for r, t in enumerate(texts):
            for tok in self._tokenize(t):
                i = self.vocab.index_of(tok)
                if i >= 0:
                    out[r, i] += 1.0
        return out


class BagOfWordsVectorizer(BaseTextVectorizer):
    def transform(self, texts: Sequence[str]) -> np.ndarray:
        return self._counts(texts)


class TfidfVectorizer(BaseTextVectorizer):
    def transform(self, texts: Sequence[str]) -> np.ndarray:
        tf = self._counts(texts)
        idf = np.log(
            (1.0 + self._n_docs) / (1.0 + self._doc_freq)
        ).astype(np.float32) + 1.0
        return tf * idf[None, :]
