"""Recursive Neural Tensor Network (Socher sentiment) — TPU-native.

Capability mirror of reference nlp/.../models/rntn/RNTN.java:84 (1,489
LoC, implements Layer; own AdaGrad) + RNTNEval + the Tree type
(nn/layers/feedforward/autoencoder/recursive/Tree.java). Same math:
for children (a, b), x = [a; b],
    p = tanh(W x + b + x^T V x)        (V: d tensor slices over [2d, 2d])
    y = softmax(W_s p)  at every node; loss = Σ node cross-entropy.

TPU re-design: the reference recurses over Java tree objects, an XLA
anti-pattern (dynamic control flow). Here each tree is LINEARIZED into a
post-order array program — leaves load word vectors, internal nodes
combine two earlier slots — executed with ``lax.scan`` over a fixed-size
node buffer (dynamic_update_slice writes), padded/masked to a static
``max_nodes`` so one jitted computation serves every tree in a batch via
``vmap``. Training uses per-parameter AdaGrad like the reference.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# trees
# ---------------------------------------------------------------------------

@dataclass
class Tree:
    """Binary parse tree with an integer label per node (sentiment
    treebank convention)."""

    label: int
    word: Optional[str] = None
    left: Optional["Tree"] = None
    right: Optional["Tree"] = None

    def is_leaf(self) -> bool:
        return self.word is not None

    @staticmethod
    def parse(s: str) -> "Tree":
        """Parse '(2 (1 bad) (0 movie))'-style s-expressions."""
        tokens = re.findall(r"\(|\)|[^\s()]+", s)
        pos = [0]

        def rec() -> "Tree":
            if tokens[pos[0]] != "(":
                raise ValueError(f"expected '(' at {pos[0]}")
            pos[0] += 1
            label = int(tokens[pos[0]])
            pos[0] += 1
            if tokens[pos[0]] == "(":
                left = rec()
                right = rec()
                node = Tree(label=label, left=left, right=right)
            else:
                node = Tree(label=label, word=tokens[pos[0]])
                pos[0] += 1
            if tokens[pos[0]] != ")":
                raise ValueError(f"expected ')' at {pos[0]}")
            pos[0] += 1
            return node

        out = rec()
        if pos[0] != len(tokens):
            raise ValueError("trailing tokens in tree string")
        return out

    def nodes(self) -> List["Tree"]:
        """Post-order traversal (children before parents)."""
        out: List[Tree] = []

        def walk(t: "Tree"):
            if t.left is not None:
                walk(t.left)
                walk(t.right)
            out.append(t)

        walk(self)
        return out

    def leaves(self) -> List["Tree"]:
        return [n for n in self.nodes() if n.is_leaf()]


# ---------------------------------------------------------------------------
# linearization: tree -> fixed arrays
# ---------------------------------------------------------------------------

@dataclass
class _Program:
    """One tree as a static array program of length max_nodes."""

    word_ids: np.ndarray    # [max_nodes] leaf word index (0 if internal)
    left: np.ndarray        # [max_nodes] child slot (0 if leaf)
    right: np.ndarray       # [max_nodes]
    is_leaf: np.ndarray     # [max_nodes] 1.0/0.0
    labels: np.ndarray      # [max_nodes] int
    mask: np.ndarray        # [max_nodes] 1.0 for real nodes
    root: int               # slot index of the root


def linearize(tree: Tree, vocab: dict, max_nodes: int) -> _Program:
    nodes = tree.nodes()
    if len(nodes) > max_nodes:
        raise ValueError(
            f"tree has {len(nodes)} nodes > max_nodes={max_nodes}")
    slot = {id(n): i for i, n in enumerate(nodes)}
    p = _Program(
        word_ids=np.zeros(max_nodes, np.int32),
        left=np.zeros(max_nodes, np.int32),
        right=np.zeros(max_nodes, np.int32),
        is_leaf=np.zeros(max_nodes, np.float32),
        labels=np.zeros(max_nodes, np.int32),
        mask=np.zeros(max_nodes, np.float32),
        root=len(nodes) - 1,
    )
    for i, n in enumerate(nodes):
        p.labels[i] = n.label
        p.mask[i] = 1.0
        if n.is_leaf():
            p.is_leaf[i] = 1.0
            p.word_ids[i] = vocab.get(n.word, 0)  # 0 = UNK
        else:
            p.left[i] = slot[id(n.left)]
            p.right[i] = slot[id(n.right)]
    return p


def _stack(programs: Sequence[_Program]):
    import jax.numpy as jnp

    return {
        "word_ids": jnp.asarray(np.stack([p.word_ids for p in programs])),
        "left": jnp.asarray(np.stack([p.left for p in programs])),
        "right": jnp.asarray(np.stack([p.right for p in programs])),
        "is_leaf": jnp.asarray(np.stack([p.is_leaf for p in programs])),
        "labels": jnp.asarray(np.stack([p.labels for p in programs])),
        "mask": jnp.asarray(np.stack([p.mask for p in programs])),
        "root": jnp.asarray(np.asarray([p.root for p in programs],
                                       np.int32)),
    }


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------

class RNTN:
    """Train/predict over labeled binary trees.

    Parameters follow the reference defaults (RNTN.java Builder):
    ``num_hidden`` = d (reference numHidden=25), AdaGrad learning rate,
    parameter init ~ U(-1/sqrt(2d), 1/sqrt(2d)).
    """

    def __init__(self, vocab: Sequence[str], num_hidden: int = 25,
                 num_classes: int = 5, max_nodes: int = 64,
                 learning_rate: float = 0.1, seed: int = 123,
                 param_smoothing: float = 1e-8):
        import jax

        self.vocab = {w: i + 1 for i, w in enumerate(vocab)}  # 0 = UNK
        self.num_hidden = int(num_hidden)
        self.num_classes = int(num_classes)
        self.max_nodes = int(max_nodes)
        self.learning_rate = float(learning_rate)
        self.param_smoothing = float(param_smoothing)
        d = self.num_hidden
        v = len(self.vocab) + 1
        key = jax.random.key(seed)
        ks = jax.random.split(key, 5)
        scale = 1.0 / np.sqrt(2.0 * d)

        def unif(k, shape, s=scale):
            return jax.random.uniform(k, shape, minval=-s, maxval=s,
                                      dtype=np.float32)

        self.params = {
            "E": unif(ks[0], (v, d), 0.1),            # word embeddings
            "W": unif(ks[1], (2 * d, d)),             # composition matrix
            "b": np.zeros((d,), np.float32),
            "V": unif(ks[2], (d, 2 * d, 2 * d)),      # tensor slices
            "Ws": unif(ks[3], (d, self.num_classes)),  # classifier
            "bs": np.zeros((self.num_classes,), np.float32),
        }
        import jax.numpy as jnp

        self.params = {k: jnp.asarray(val) for k, val in
                       self.params.items()}
        self._adagrad = {k: jnp.zeros_like(val) for k, val in
                        self.params.items()}
        self._loss_grad = None
        self._forward = None

    # -- core computation ----------------------------------------------
    def _build_fns(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        d = self.num_hidden

        def run_tree(params, prog):
            """Returns (node_vectors [max_nodes, d], logits, loss)."""
            buf0 = jnp.zeros((self.max_nodes, d), jnp.float32)

            def step(buf, idx):
                leaf_vec = params["E"][prog["word_ids"][idx]]
                a = buf[prog["left"][idx]]
                bvec = buf[prog["right"][idx]]
                x = jnp.concatenate([a, bvec])                 # [2d]
                tensor = jnp.einsum("i,dij,j->d", x, params["V"], x)
                comp = jnp.tanh(x @ params["W"] + params["b"] + tensor)
                vec = jnp.where(prog["is_leaf"][idx] > 0, leaf_vec, comp)
                buf = lax.dynamic_update_slice(buf, vec[None, :],
                                               (idx, 0))
                return buf, None

            buf, _ = lax.scan(step, buf0,
                              jnp.arange(self.max_nodes, dtype=jnp.int32))
            logits = buf @ params["Ws"] + params["bs"]   # [max_nodes, C]
            logp = jax.nn.log_softmax(logits)
            node_nll = -logp[jnp.arange(self.max_nodes), prog["labels"]]
            loss = jnp.sum(node_nll * prog["mask"])
            return buf, logits, loss

        def batch_loss(params, batch):
            def one(word_ids, left, right, is_leaf, labels, mask, root):
                prog = {"word_ids": word_ids, "left": left, "right": right,
                        "is_leaf": is_leaf, "labels": labels, "mask": mask}
                _, _, loss = run_tree(params, prog)
                return loss

            losses = jax.vmap(one)(
                batch["word_ids"], batch["left"], batch["right"],
                batch["is_leaf"], batch["labels"], batch["mask"],
                batch["root"])
            return jnp.sum(losses) / jnp.maximum(
                jnp.sum(batch["mask"]), 1.0)

        self._loss_grad = jax.jit(jax.value_and_grad(batch_loss))

        def forward(params, batch):
            def one(word_ids, left, right, is_leaf, labels, mask, root):
                prog = {"word_ids": word_ids, "left": left, "right": right,
                        "is_leaf": is_leaf, "labels": labels, "mask": mask}
                _, logits, _ = run_tree(params, prog)
                return logits

            return jax.vmap(one)(
                batch["word_ids"], batch["left"], batch["right"],
                batch["is_leaf"], batch["labels"], batch["mask"],
                batch["root"])

        self._forward = jax.jit(forward)

    # -- training -------------------------------------------------------
    def fit(self, trees: Sequence[Tree], num_epochs: int = 1,
            batch_size: int = 32) -> List[float]:
        """AdaGrad over tree batches (the reference's own AdaGrad update,
        RNTN.java getValueGradient/updateAdaGrad). Returns epoch losses."""
        import jax
        import jax.numpy as jnp

        if self._loss_grad is None:
            self._build_fns()
        programs = [linearize(t, self.vocab, self.max_nodes)
                    for t in trees]
        # stack + upload each batch ONCE, not once per epoch
        batches = [_stack(programs[i:i + batch_size])
                   for i in range(0, len(programs), batch_size)]
        losses = []
        for _ in range(num_epochs):
            total = 0.0
            for batch in batches:
                loss, grads = self._loss_grad(self.params, batch)
                total += float(loss)
                # AdaGrad: g2 += g²; p -= lr * g / (sqrt(g2) + eps)
                for k in self.params:
                    self._adagrad[k] = self._adagrad[k] + grads[k] ** 2
                    self.params[k] = self.params[k] - (
                        self.learning_rate * grads[k]
                        / (jnp.sqrt(self._adagrad[k])
                           + self.param_smoothing))
            losses.append(total)
        return losses

    # -- inference ------------------------------------------------------
    def predict(self, tree: Tree) -> np.ndarray:
        """Per-node predicted class, post-order (root last)."""
        if self._forward is None:
            self._build_fns()
        prog = linearize(tree, self.vocab, self.max_nodes)
        logits = np.asarray(self._forward(self.params, _stack([prog]))[0])
        n = len(tree.nodes())
        return logits[:n].argmax(axis=-1)

    def predict_root(self, tree: Tree) -> int:
        return int(self.predict(tree)[-1])


class RNTNEval:
    """Node-level and root-level accuracy (reference RNTNEval.java)."""

    def __init__(self) -> None:
        self.node_correct = 0
        self.node_total = 0
        self.root_correct = 0
        self.root_total = 0

    def eval(self, model: RNTN, trees: Sequence[Tree]) -> None:
        for t in trees:
            preds = model.predict(t)
            labels = np.asarray([n.label for n in t.nodes()])
            self.node_correct += int((preds == labels).sum())
            self.node_total += len(labels)
            self.root_correct += int(preds[-1] == labels[-1])
            self.root_total += 1

    def node_accuracy(self) -> float:
        return self.node_correct / max(1, self.node_total)

    def root_accuracy(self) -> float:
        return self.root_correct / max(1, self.root_total)

    def stats(self) -> str:
        return (f"RNTN eval: node acc {self.node_accuracy():.4f} "
                f"({self.node_correct}/{self.node_total}), root acc "
                f"{self.root_accuracy():.4f} "
                f"({self.root_correct}/{self.root_total})")
