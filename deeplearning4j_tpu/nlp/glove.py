"""GloVe: co-occurrence counting + weighted least-squares factorization.

Mirror of reference nlp models/glove/{Glove.java:31, AbstractCoOccurrences,
GloveWeightLookupTable}. The reference counts co-occurrences with an actor
pipeline spilling to binary files and trains with per-element AdaGrad
(Hogwild); here counting is a host-side dict pass (1/distance weighting,
symmetric window) and training is a jitted batched AdaGrad scatter update.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import build_vocab


class Glove(SequenceVectors):
    def __init__(
        self,
        layer_size: int = 100,
        window: int = 15,
        learning_rate: float = 0.05,
        min_word_frequency: int = 5,
        epochs: int = 25,
        x_max: float = 100.0,
        alpha: float = 0.75,
        batch_size: int = 65536,
        symmetric: bool = True,
        seed: int = 12345,
    ):
        super().__init__(
            layer_size=layer_size,
            window=window,
            learning_rate=learning_rate,
            min_word_frequency=min_word_frequency,
            epochs=epochs,
            batch_size=batch_size,
            seed=seed,
            use_hierarchic_softmax=False,
        )
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric

    # ------------------------------------------------------------------
    def _count_cooccurrences(
        self, sequences: Iterable[Sequence[str]]
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        counts: Dict[Tuple[int, int], float] = {}
        for tokens in sequences:
            idxs = [
                self.vocab.index_of(t)
                for t in tokens
                if self.vocab.contains_word(t)
            ]
            for pos, center in enumerate(idxs):
                for off in range(1, self.window + 1):
                    j = pos + off
                    if j >= len(idxs):
                        break
                    w = 1.0 / off
                    a, b = center, idxs[j]
                    counts[(a, b)] = counts.get((a, b), 0.0) + w
                    if self.symmetric:
                        counts[(b, a)] = counts.get((b, a), 0.0) + w
        if not counts:
            raise ValueError("Empty co-occurrence matrix")
        ij = np.asarray(list(counts.keys()), np.int32)
        x = np.asarray(list(counts.values()), np.float32)
        return ij[:, 0], ij[:, 1], x

    # ------------------------------------------------------------------
    @functools.cached_property
    def _glove_step(self):
        x_max, alpha = self.x_max, self.alpha

        @jax.jit
        def step(w, wt, b, bt, gw, gwt, gb, gbt, rows, cols, xij, lr):
            wi = w[rows]
            wj = wt[cols]
            diff = (
                jnp.sum(wi * wj, axis=-1) + b[rows] + bt[cols] - jnp.log(xij)
            )
            fx = jnp.minimum(1.0, (xij / x_max) ** alpha)
            g = fx * diff  # [B]
            loss = 0.5 * jnp.mean(fx * diff * diff)
            dwi = g[:, None] * wj
            dwj = g[:, None] * wi
            # AdaGrad accumulators (reference GloveWeightLookupTable's
            # per-element historical gradient).
            gw = gw.at[rows].add(dwi * dwi)
            gwt = gwt.at[cols].add(dwj * dwj)
            gb = gb.at[rows].add(g * g)
            gbt = gbt.at[cols].add(g * g)
            w = w.at[rows].add(-lr * dwi / jnp.sqrt(gw[rows] + 1e-8))
            wt = wt.at[cols].add(-lr * dwj / jnp.sqrt(gwt[cols] + 1e-8))
            b = b.at[rows].add(-lr * g / jnp.sqrt(gb[rows] + 1e-8))
            bt = bt.at[cols].add(-lr * g / jnp.sqrt(gbt[cols] + 1e-8))
            return w, wt, b, bt, gw, gwt, gb, gbt, loss

        return step

    # ------------------------------------------------------------------
    def fit(self, sequences_factory) -> None:
        seqs = (
            sequences_factory()
            if callable(sequences_factory)
            else sequences_factory
        )
        seqs = list(seqs)
        if self.vocab is None:
            self.vocab = build_vocab(seqs, self.min_word_frequency)
        v, d = self.vocab.num_words(), self.layer_size
        key = jax.random.key(self.seed)
        k1, k2 = jax.random.split(key)
        w = (jax.random.uniform(k1, (v, d)) - 0.5) / d
        wt = (jax.random.uniform(k2, (v, d)) - 0.5) / d
        b = jnp.zeros((v,))
        bt = jnp.zeros((v,))
        gw = jnp.zeros((v, d))
        gwt = jnp.zeros((v, d))
        gb = jnp.zeros((v,))
        gbt = jnp.zeros((v,))

        rows, cols, xij = self._count_cooccurrences(seqs)
        rng = np.random.default_rng(self.seed)
        n = len(rows)
        self.losses: List[float] = []
        for _ in range(self.epochs):
            order = rng.permutation(n)
            for start in range(0, n, self.batch_size):
                sel = order[start : start + self.batch_size]
                (w, wt, b, bt, gw, gwt, gb, gbt, loss) = self._glove_step(
                    w, wt, b, bt, gw, gwt, gb, gbt,
                    jnp.asarray(rows[sel]), jnp.asarray(cols[sel]),
                    jnp.asarray(xij[sel]), self.learning_rate,
                )
            self.losses.append(float(loss))
        # Final embedding = w + wt (standard GloVe practice).
        self.syn0 = w + wt
