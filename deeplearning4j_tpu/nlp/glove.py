"""GloVe: co-occurrence counting + weighted least-squares factorization.

Mirror of reference nlp models/glove/{Glove.java:31, AbstractCoOccurrences,
GloveWeightLookupTable}. The reference counts co-occurrences with an actor
pipeline spilling to binary files and trains with per-element AdaGrad
(Hogwild); here counting is a host-side dict pass (1/distance weighting,
symmetric window) for in-RAM corpora, or the disk-spill counter
(nlp/cooccurrence.py DiskBackedCoOccurrences, the AbstractCoOccurrences
bounded-memory design) when ``max_pairs_in_memory`` is set; training is
a jitted batched AdaGrad scatter update either way.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import build_vocab


class Glove(SequenceVectors):
    def __init__(
        self,
        layer_size: int = 100,
        window: int = 15,
        learning_rate: float = 0.05,
        min_word_frequency: int = 5,
        epochs: int = 25,
        x_max: float = 100.0,
        alpha: float = 0.75,
        batch_size: int = 65536,
        symmetric: bool = True,
        seed: int = 12345,
    ):
        super().__init__(
            layer_size=layer_size,
            window=window,
            learning_rate=learning_rate,
            min_word_frequency=min_word_frequency,
            epochs=epochs,
            batch_size=batch_size,
            seed=seed,
            use_hierarchic_softmax=False,
        )
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric

    # ------------------------------------------------------------------
    def _count_cooccurrences(
        self, sequences: Iterable[Sequence[str]]
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        counts: Dict[Tuple[int, int], float] = {}
        for tokens in sequences:
            idxs = [
                self.vocab.index_of(t)
                for t in tokens
                if self.vocab.contains_word(t)
            ]
            for pos, center in enumerate(idxs):
                for off in range(1, self.window + 1):
                    j = pos + off
                    if j >= len(idxs):
                        break
                    w = 1.0 / off
                    a, b = center, idxs[j]
                    counts[(a, b)] = counts.get((a, b), 0.0) + w
                    if self.symmetric:
                        counts[(b, a)] = counts.get((b, a), 0.0) + w
        if not counts:
            raise ValueError("Empty co-occurrence matrix")
        ij = np.asarray(list(counts.keys()), np.int32)
        x = np.asarray(list(counts.values()), np.float32)
        return ij[:, 0], ij[:, 1], x

    # ------------------------------------------------------------------
    @functools.cached_property
    def _glove_step(self):
        x_max, alpha = self.x_max, self.alpha

        @jax.jit
        def step(w, wt, b, bt, gw, gwt, gb, gbt, rows, cols, xij, lr):
            wi = w[rows]
            wj = wt[cols]
            diff = (
                jnp.sum(wi * wj, axis=-1) + b[rows] + bt[cols] - jnp.log(xij)
            )
            fx = jnp.minimum(1.0, (xij / x_max) ** alpha)
            g = fx * diff  # [B]
            loss = 0.5 * jnp.mean(fx * diff * diff)
            dwi = g[:, None] * wj
            dwj = g[:, None] * wi
            # AdaGrad accumulators (reference GloveWeightLookupTable's
            # per-element historical gradient).
            gw = gw.at[rows].add(dwi * dwi)
            gwt = gwt.at[cols].add(dwj * dwj)
            gb = gb.at[rows].add(g * g)
            gbt = gbt.at[cols].add(g * g)
            w = w.at[rows].add(-lr * dwi / jnp.sqrt(gw[rows] + 1e-8))
            wt = wt.at[cols].add(-lr * dwj / jnp.sqrt(gwt[cols] + 1e-8))
            b = b.at[rows].add(-lr * g / jnp.sqrt(gb[rows] + 1e-8))
            bt = bt.at[cols].add(-lr * g / jnp.sqrt(gbt[cols] + 1e-8))
            return w, wt, b, bt, gw, gwt, gb, gbt, loss

        return step

    # ------------------------------------------------------------------
    TABLE_NAMES = ("w", "wt", "b", "bt", "gw", "gwt", "gb", "gbt")

    def init_tables(self) -> None:
        """Allocate factorization tables + AdaGrad accumulators on the
        model so training can proceed incrementally (the distributed
        performer trains co-occurrence shards between table averages)."""
        v, d = self.vocab.num_words(), self.layer_size
        key = jax.random.key(self.seed)
        k1, k2 = jax.random.split(key)
        self.w = (jax.random.uniform(k1, (v, d)) - 0.5) / d
        self.wt = (jax.random.uniform(k2, (v, d)) - 0.5) / d
        self.b = jnp.zeros((v,))
        self.bt = jnp.zeros((v,))
        self.gw = jnp.zeros((v, d))
        self.gwt = jnp.zeros((v, d))
        self.gb = jnp.zeros((v,))
        self.gbt = jnp.zeros((v,))
        self.losses: List[float] = []
        # fresh shuffle stream: repeated fit() runs stay seed-reproducible
        self._glove_rng = np.random.default_rng(self.seed)

    def train_cooccurrences(self, rows, cols, xij,
                            learning_rate=None) -> float:
        """One shuffled pass over the given co-occurrence triples at a
        fixed lr; returns the pair-weighted mean batch loss over the
        pass — the incremental granularity the distributed
        GlovePerformer dispatches at
        (reference scaleout/perform/models/glove/GlovePerformer.java)."""
        if not hasattr(self, "w"):
            raise ValueError("init_tables() (or fit) must run first")
        lr = float(learning_rate if learning_rate is not None
                   else self.learning_rate)
        rows = np.asarray(rows, np.int32)
        cols = np.asarray(cols, np.int32)
        xij = np.asarray(xij, np.float32)
        if len(rows) == 0:
            return 0.0  # empty shard: no work, a real (non-NaN) loss
        if not hasattr(self, "_glove_rng"):
            self._glove_rng = np.random.default_rng(self.seed)
        order = self._glove_rng.permutation(len(rows))
        # Device-scalar accumulation: one host sync per PASS, not per
        # batch (a per-batch float() would serialize dispatch on the
        # TPU tunnel, where transfers block behind queued compute).
        loss_sum = jnp.zeros((), jnp.float32)
        for start in range(0, len(rows), self.batch_size):
            sel = order[start : start + self.batch_size]
            (self.w, self.wt, self.b, self.bt, self.gw, self.gwt,
             self.gb, self.gbt, loss) = self._glove_step(
                self.w, self.wt, self.b, self.bt,
                self.gw, self.gwt, self.gb, self.gbt,
                jnp.asarray(rows[sel]), jnp.asarray(cols[sel]),
                jnp.asarray(xij[sel]), lr,
            )
            loss_sum = loss_sum + loss * len(sel)
        # Final embedding = w + wt (standard GloVe practice).
        self.syn0 = self.w + self.wt
        return float(loss_sum) / len(rows)

    def train_cooccurrence_batches(self, batches, learning_rate=None,
                                   shuffle_window: int = 8) -> float:
        """One pass over an iterable of (rows, cols, xij) batches at a
        fixed lr — the disk-streaming counterpart of
        ``train_cooccurrences``. The merged spill stream arrives in
        sorted key order, so ``shuffle_window`` consecutive batches are
        buffered and shuffled TOGETHER (train_cooccurrences permutes the
        concatenation) before their scatter steps — bounded-memory SGD
        mixing, vs the in-memory path's full-pair-set permutation (a
        global shuffle would need O(pairs) memory, the thing this path
        exists to avoid). Peak memory: shuffle_window batches + tables."""
        if not hasattr(self, "w"):
            raise ValueError("init_tables() (or fit) must run first")
        # Pair-count-weighted mean across flushes so the returned epoch
        # loss is comparable to the in-memory path's full-pass loss (a
        # bare last-flush loss would reflect only the final window).
        loss_weighted_sum = 0.0
        total_pairs = 0
        window: list = []

        def flush():
            nonlocal loss_weighted_sum, total_pairs
            if not window:
                return
            rows = np.concatenate([b[0] for b in window])
            cols = np.concatenate([b[1] for b in window])
            xij = np.concatenate([b[2] for b in window])
            flush_loss = self.train_cooccurrences(
                rows, cols, xij, learning_rate)
            loss_weighted_sum += flush_loss * len(rows)
            total_pairs += len(rows)
            window.clear()

        for batch in batches:
            window.append(batch)
            if len(window) >= shuffle_window:
                flush()
        flush()
        self.syn0 = self.w + self.wt
        return loss_weighted_sum / total_pairs if total_pairs else 0.0

    def fit(
        self,
        sequences_factory,
        max_pairs_in_memory: int | None = None,
        spill_dir: str | None = None,
    ) -> None:
        """``max_pairs_in_memory`` bounds counting memory: co-occurrence
        counts spill to sorted disk shards past that many distinct pairs
        and training streams the k-way merge per epoch (reference
        AbstractCoOccurrences maxMemory knob)."""
        from deeplearning4j_tpu.nlp.cooccurrence import (
            DiskBackedCoOccurrences,
        )

        seqs = (
            sequences_factory()
            if callable(sequences_factory)
            else sequences_factory
        )
        seqs = list(seqs)
        if self.vocab is None:
            self.vocab = build_vocab(seqs, self.min_word_frequency)
        self.init_tables()
        if max_pairs_in_memory is None:
            rows, cols, xij = self._count_cooccurrences(seqs)
            for _ in range(self.epochs):
                self.losses.append(
                    self.train_cooccurrences(rows, cols, xij))
            return
        counter = DiskBackedCoOccurrences(
            self.vocab, window=self.window, symmetric=self.symmetric,
            max_pairs_in_memory=max_pairs_in_memory, spill_dir=spill_dir,
        )
        try:
            counter.count_sequences(seqs)
            if counter.n_shards() == 0:
                raise ValueError("Empty co-occurrence matrix")
            for _ in range(self.epochs):
                self.losses.append(self.train_cooccurrence_batches(
                    counter.iter_batches(self.batch_size)))
        finally:
            counter.cleanup()
