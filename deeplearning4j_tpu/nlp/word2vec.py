"""Word2Vec: skip-gram over sentences.

Mirror of reference nlp models/word2vec/Word2Vec.java:30 (+Builder :68) on
top of the SequenceVectors engine, fed by a SentenceIterator + Tokenizer
(reference SentenceTransformer pipeline).
"""

from __future__ import annotations

from typing import List, Optional

from deeplearning4j_tpu.nlp.sentence_iterator import SentenceIterator
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)


class Word2Vec(SequenceVectors):
    def __init__(
        self,
        sentence_iterator: Optional[SentenceIterator] = None,
        tokenizer_factory: Optional[TokenizerFactory] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.sentence_iterator = sentence_iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()

    class Builder:
        """Fluent builder (reference Word2Vec.Builder)."""

        def __init__(self):
            self._kw = {}
            self._iter = None
            self._tok = None

        def iterate(self, sentence_iterator) -> "Word2Vec.Builder":
            self._iter = sentence_iterator
            return self

        def tokenizer_factory(self, tf) -> "Word2Vec.Builder":
            self._tok = tf
            return self

        def layer_size(self, n: int) -> "Word2Vec.Builder":
            self._kw["layer_size"] = n
            return self

        def window_size(self, n: int) -> "Word2Vec.Builder":
            self._kw["window"] = n
            return self

        def learning_rate(self, lr: float) -> "Word2Vec.Builder":
            self._kw["learning_rate"] = lr
            return self

        def min_learning_rate(self, lr: float) -> "Word2Vec.Builder":
            self._kw["min_learning_rate"] = lr
            return self

        def min_word_frequency(self, n: int) -> "Word2Vec.Builder":
            self._kw["min_word_frequency"] = n
            return self

        def negative_sample(self, n: int) -> "Word2Vec.Builder":
            self._kw["negative"] = n
            if n > 0:
                self._kw.setdefault("use_hierarchic_softmax", False)
            return self

        def use_hierarchic_softmax(self, flag: bool) -> "Word2Vec.Builder":
            self._kw["use_hierarchic_softmax"] = flag
            return self

        def sampling(self, s: float) -> "Word2Vec.Builder":
            self._kw["subsampling"] = s
            return self

        def epochs(self, n: int) -> "Word2Vec.Builder":
            self._kw["epochs"] = n
            return self

        def batch_size(self, n: int) -> "Word2Vec.Builder":
            self._kw["batch_size"] = n
            return self

        def seed(self, n: int) -> "Word2Vec.Builder":
            self._kw["seed"] = n
            return self

        def build(self) -> "Word2Vec":
            return Word2Vec(self._iter, self._tok, **self._kw)

    # ------------------------------------------------------------------
    def _sentences(self) -> List[List[str]]:
        out = []
        self.sentence_iterator.reset()
        for sentence in self.sentence_iterator:
            tokens = self.tokenizer_factory.create(sentence).get_tokens()
            if tokens:
                out.append(tokens)
        return out

    def fit(self, sequences=None) -> None:
        if sequences is not None:
            super().fit(sequences)
        else:
            if self.sentence_iterator is None:
                raise ValueError("No sentence iterator configured")
            super().fit(self._sentences)
