"""Distributed-style text → vocab pipeline and partitioned cumulative sums.

TPU-native equivalent of the Spark NLP driver pipeline (reference
dl4j-spark-nlp/.../text/functions/TextPipeline.java and CountCumSum.java):
the corpus is a list of partitions (the RDD analogue), tokenization and
word-frequency counting run per-partition on a thread pool (the
accumulator analogue is a merged Counter), low-frequency words collapse to
UNK, and the resulting VocabCache gets Huffman codes assigned before any
worker consumes it — the same order the reference enforces ("huffman tree
should be built BEFORE vocab broadcast").

``CountCumSum`` mirrors the reference's two-phase partition scan: fold
within each partition, broadcast per-partition maxima, then offset between
partitions — the shape an XLA ``associative_scan`` would take over a mesh
axis; here partitions are host shards so the fold runs on host threads.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ..util.collections import Counter, run_in_parallel
from .tokenization import DefaultTokenizerFactory, NGramTokenizerFactory
from .vocab import VocabCache, assign_huffman_codes

UNK = "UNK"


def _as_partitions(corpus) -> List[List[str]]:
    """Accept either a flat list of sentences or a list of partitions."""
    if not corpus:
        return []
    if isinstance(corpus[0], (list, tuple)):
        return [list(p) for p in corpus]
    return [list(corpus)]


class TextPipeline:
    """Corpus partitions → tokenized sentences, word frequencies, VocabCache
    with Huffman codes, vocab-word-index lists and per-sentence counts.

    Config knobs mirror the reference's broadcast tokenizer var map
    (TextPipeline.java setRDDVarMap): ``num_words`` (min frequency),
    ``n_grams``, ``use_unk``, ``stop_words``. Stop words count under (and
    index to) the shared STOP marker, as in the reference accumulator.
    """

    def __init__(
        self,
        corpus,
        num_words: int = 1,
        n_grams: int = 1,
        tokenizer_factory=None,
        stop_words: Optional[Sequence[str]] = None,
        use_unk: bool = True,
        max_workers: Optional[int] = None,
    ):
        self.partitions = _as_partitions(corpus)
        self.num_words = num_words
        self.use_unk = use_unk
        self.stop_words = set(stop_words or [])
        self.max_workers = max_workers
        if tokenizer_factory is None:
            tokenizer_factory = (
                NGramTokenizerFactory(n_min=1, n_max=n_grams) if n_grams > 1
                else DefaultTokenizerFactory()
            )
        self.tokenizer_factory = tokenizer_factory

        self.word_freq: Counter[str] = Counter()
        self.vocab_cache = VocabCache()
        self._tokenized: Optional[List[List[List[str]]]] = None
        self._sentence_word_counts: Optional[List[List[int]]] = None
        self.total_word_count = 0

    # -- stage 1: tokenize (per partition, in parallel) ------------------
    def tokenize(self) -> List[List[List[str]]]:
        if self._tokenized is None:
            def run(part: List[str]) -> List[List[str]]:
                tf = self.tokenizer_factory
                return [tf.create(s).get_tokens() for s in part]

            self._tokenized = run_in_parallel(
                [lambda p=p: run(p) for p in self.partitions],
                max_workers=self.max_workers,
            )
        return self._tokenized

    # -- stage 2: word-frequency "accumulator" ---------------------------
    def update_word_freq_accumulator(self) -> Counter:
        """Per-partition counts merged into one Counter; stop words count
        as the STOP marker like the reference accumulator function."""
        tokenized = self.tokenize()

        def count(part: List[List[str]]) -> Counter:
            c: Counter[str] = Counter()
            for tokens in part:
                for tok in tokens:
                    c.increment_count("STOP" if tok in self.stop_words else tok)
            return c

        partials = run_in_parallel(
            [lambda p=p: count(p) for p in tokenized],
            max_workers=self.max_workers,
        )
        self.word_freq = Counter()
        for c in partials:
            self.word_freq.increment_all(c)
        self._sentence_word_counts = [
            [len(tokens) for tokens in part] for part in tokenized
        ]
        return self.word_freq

    def filter_min_word_add_vocab(self, word_freq: Counter) -> None:
        if word_freq.is_empty():
            raise ValueError(
                "word frequency counter is empty — run "
                "update_word_freq_accumulator() on a non-empty corpus first"
            )
        for word in word_freq.key_set():
            count = int(word_freq.get_count(word))
            token = UNK if count < self.num_words else word
            if token == UNK and not self.use_unk:
                continue
            self.vocab_cache.add_token(token, count)
        self.vocab_cache.finalize_indices()

    # -- stage 3: vocab + Huffman ----------------------------------------
    def build_vocab_cache(self) -> VocabCache:
        self.filter_min_word_add_vocab(self.update_word_freq_accumulator())
        assign_huffman_codes(self.vocab_cache)
        return self.vocab_cache

    # -- stage 4: sentence → vocab-index lists ---------------------------
    def build_vocab_word_list(self) -> List[List[List[int]]]:
        """Per partition, per sentence: vocab indices (OOV → UNK index when
        available, else dropped) — the vocabWordListRDD analogue."""
        if self.vocab_cache.num_words() == 0:
            self.build_vocab_cache()
        unk_idx = self.vocab_cache.index_of(UNK)
        stop_idx = self.vocab_cache.index_of("STOP")
        out = []
        for part in self.tokenize():
            rows = []
            for tokens in part:
                idxs = []
                for tok in tokens:
                    if tok in self.stop_words:
                        i = stop_idx
                    else:
                        i = self.vocab_cache.index_of(tok)
                    if i < 0:
                        i = unk_idx
                    if i >= 0:
                        idxs.append(i)
                rows.append(idxs)
            out.append(rows)
        self.total_word_count = sum(
            sum(counts) for counts in (self._sentence_word_counts or [])
        )
        return out

    def sentence_count_partitions(self) -> List[List[int]]:
        if self._sentence_word_counts is None:
            self.update_word_freq_accumulator()
        return list(self._sentence_word_counts or [])


class CountCumSum:
    """Exclusive-prefix offsets of per-sentence word counts across
    partitions (reference CountCumSum.java): the cumulative word count at
    each sentence is what anneals the skip-gram learning rate.

    Phase 1 folds within each partition (parallel); phase 2 adds the
    broadcast per-partition totals as offsets. Returns inclusive sums per
    sentence, flattened in partition order like the reference's cumSumRDD.
    """

    def __init__(self, sentence_count_partitions: Sequence[Sequence[int]],
                 max_workers: Optional[int] = None):
        self.partitions = [list(p) for p in sentence_count_partitions]
        self.max_workers = max_workers
        self._within: Optional[List[List[int]]] = None
        self._max_per_partition: Dict[int, int] = {}

    def cum_sum_within_partition(self) -> List[List[int]]:
        def fold(part: List[int]) -> List[int]:
            acc, out = 0, []
            for c in part:
                acc += c
                out.append(acc)
            return out

        self._within = run_in_parallel(
            [lambda p=p: fold(p) for p in self.partitions],
            max_workers=self.max_workers,
        )
        self._max_per_partition = {
            i: (folded[-1] if folded else 0)
            for i, folded in enumerate(self._within)
        }
        return self._within

    def cum_sum_between_partition(self) -> List[int]:
        if self._within is None:
            self.cum_sum_within_partition()
        out: List[int] = []
        offset = 0
        for i, folded in enumerate(self._within or []):
            out.extend(v + offset for v in folded)
            offset += self._max_per_partition.get(i, 0)
        return out

    def build_cum_sum(self) -> List[int]:
        self.cum_sum_within_partition()
        return self.cum_sum_between_partition()
