"""DataSet iterator over word windows vectorized with a trained Word2Vec.

TPU-native equivalent of the reference
models/word2vec/iterator/Word2VecDataSetIterator.java: a label-aware
sentence iterator feeds a moving window over each sentence; every window
becomes one example whose features are the concatenated word vectors of
the window (WindowConverter) and whose label is the one-hot of the
sentence's label. Homogenization and label tagging mirror the reference's
sentence pre-processors. Windows spill across sentence boundaries into a
cache so every batch except the final remainder has the full static
``batch`` rows.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..datasets.dataset import DataSet
from ..datasets.iterator import DataSetIterator
from .moving_window import Window, WindowConverter, input_homogenization, windows
from .sentence_iterator import LabelAwareSentenceIterator


class Word2VecDataSetIterator(DataSetIterator):
    def __init__(
        self,
        vec,
        iterator: LabelAwareSentenceIterator,
        labels: List[str],
        batch: int = 10,
        homogenization: bool = True,
        add_labels: bool = True,
        normalize: bool = False,
    ):
        super().__init__(batch_size=batch)
        self.vec = vec
        self.iter = iterator
        self.labels = list(labels)
        self.batch = batch
        self.homogenization = homogenization
        self.add_labels = add_labels
        self.normalize = normalize
        self._cached: List[Window] = []

    def _sentence_windows(self) -> List[Window]:
        sentence = self.iter.next_sentence()
        label = self.iter.current_label() if self.add_labels else None
        if self.homogenization:
            sentence = input_homogenization(sentence)
        if not sentence.strip():
            return []
        ws = windows(sentence, window_size=self.vec.window)
        if label is not None:
            for w in ws:
                w.label = label
        return ws

    def _fill_cache(self, num: int) -> None:
        while len(self._cached) < num and self.iter.has_next():
            self._cached.extend(self._sentence_windows())

    def _to_dataset(self, ws: List[Window]) -> DataSet:
        feats = WindowConverter.as_example_matrix(ws, self.vec, self.normalize)
        n_out = max(len(self.labels), 1)
        labels = np.zeros((len(ws), n_out), dtype=np.float32)
        for i, w in enumerate(ws):
            if w.label in self.labels:
                labels[i, self.labels.index(w.label)] = 1.0
        return DataSet(feats, labels)

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        num = num or self.batch
        self._fill_cache(num)
        if not self._cached:
            return None
        take, self._cached = self._cached[:num], self._cached[num:]
        return self._post(self._to_dataset(take))

    def reset(self) -> None:
        self.iter.reset()
        self._cached = []

    def input_columns(self) -> int:
        return self.vec.layer_size * self.vec.window

    def total_outcomes(self) -> int:
        return max(len(self.labels), 1)
