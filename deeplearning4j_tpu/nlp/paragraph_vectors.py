"""ParagraphVectors: document embeddings (PV-DBOW).

Mirror of reference nlp models/paragraphvectors/ParagraphVectors.java
(666 LoC): document labels are added to the vocabulary and trained like
words — the DBOW sequence-learning algorithm (learning/impl/sequence/
DBOW.java) trains the label vector to predict each word in the document
via the same HS/NS objective. Inference for unseen docs trains a fresh
vector against the frozen word tables.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import (
    VocabCache,
    assign_huffman_codes,
    build_vocab,
)


class ParagraphVectors(SequenceVectors):
    LABEL_PREFIX = "DOC_"

    def __init__(self, tokenizer_factory: Optional[TokenizerFactory] = None,
                 **kwargs):
        kwargs.setdefault("min_word_frequency", 1)
        super().__init__(**kwargs)
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.labels: List[str] = []

    # ------------------------------------------------------------------
    def fit_documents(
        self, docs: Sequence[str], labels: Optional[Sequence[str]] = None
    ) -> None:
        if labels is None:
            labels = [f"{self.LABEL_PREFIX}{i}" for i in range(len(docs))]
        self.labels = list(labels)
        token_docs = [
            self.tokenizer_factory.create(d).get_tokens() for d in docs
        ]
        # Vocab over words only; labels appended after (reference adds
        # labels to the vocab with count ~ document length).
        self.vocab = build_vocab(token_docs, self.min_word_frequency)
        for lbl, toks in zip(labels, token_docs):
            vw = self.vocab.add_token(lbl, max(1, len(toks)))
        self.vocab.finalize_indices()
        if self.use_hs:
            assign_huffman_codes(self.vocab)
        self._reset_weights()

        # DBOW pairs: (center=word, context=label) — the label vector
        # learns to predict every word of its document.
        def factory():
            return self._label_sequences(token_docs, labels)

        super().fit(factory)

    def _label_sequences(self, token_docs, labels):
        """Each 'sequence' = [label, w1, w2, ...]; the engine's window pair
        mining would mix word-word pairs too (that is PV + W2V combined,
        which the reference also trains); to keep the DBOW objective we
        mine label-word pairs explicitly instead."""
        out = []
        for lbl, toks in zip(labels, token_docs):
            kept = [t for t in toks if self.vocab.contains_word(t)]
            out.append((lbl, kept))
        return out

    # Override pair mining: every (word, label) pair of each doc.
    def _mine_pairs(self, sequences, rng):
        # Mixed code lengths per batch -> always the full padded
        # Huffman-path slice (the code-length class split in
        # SequenceVectors._pad_and_batch is a skip-gram mining concern).
        lmax = self._code_lmax if self.use_hs else 0
        centers: List[int] = []
        contexts: List[int] = []
        emitted = 0
        for lbl, toks in sequences:
            li = self.vocab.index_of(lbl)
            if li < 0:
                continue
            for t in toks:
                centers.append(self.vocab.index_of(t))
                contexts.append(li)
                if len(centers) >= self.batch_size:
                    yield (
                        np.asarray(centers, np.int32),
                        np.asarray(contexts, np.int32),
                        lmax,
                        emitted,
                    )
                    emitted += len(centers)
                    centers, contexts = [], []
        if centers:
            yield (
                np.asarray(centers, np.int32),
                np.asarray(contexts, np.int32),
                lmax,
                emitted,
            )

    # ------------------------------------------------------------------
    def doc_vector(self, label: str) -> Optional[np.ndarray]:
        return self.get_word_vector(label)

    @functools.cached_property
    def _infer_fn(self):
        """Compiled inference: all ``steps`` updates in one lax.fori_loop
        dispatch; compiled once per (token-count) shape, reused across
        calls. Supports both HS and negative-sampling models."""
        use_hs = self.use_hs
        negative = self.negative

        @functools.partial(jax.jit, static_argnames=("steps",))
        def infer(vec, idxs, syn1, syn1neg, codes, points, cmask,
                  neg_table, key, lr0, steps):
            def body(s, carry):
                vec, key = carry
                lr = lr0 * (1.0 - s / steps)
                dvec = jnp.zeros_like(vec)
                if use_hs:
                    w = syn1[points]  # [T, L, D]
                    dot = jnp.einsum("tld,d->tl", w, vec)
                    g = (1.0 - codes - jax.nn.sigmoid(dot)) * cmask
                    dvec = dvec + jnp.einsum("tl,tld->d", g, w)
                if negative > 0:
                    key, sub = jax.random.split(key)
                    pos = syn1neg[idxs]  # [T, D]
                    # unigram-TABLE draws (sequence_vectors.py: the
                    # categorical-over-[V] path materializes [T, K, V]
                    # gumbel noise; the table is O(1) per draw)
                    draws = jax.random.randint(
                        sub, (idxs.shape[0], negative), 0,
                        neg_table.shape[0])
                    negs = neg_table[draws]
                    wneg = syn1neg[negs]  # [T, K, D]
                    g_pos = 1.0 - jax.nn.sigmoid(pos @ vec)  # [T]
                    g_neg = -jax.nn.sigmoid(
                        jnp.einsum("tkd,d->tk", wneg, vec)
                    )
                    g_neg = g_neg * (negs != idxs[:, None]).astype(
                        g_neg.dtype
                    )
                    dvec = dvec + g_pos @ pos + jnp.einsum(
                        "tk,tkd->d", g_neg, wneg
                    )
                return vec + lr * dvec, key

            vec, _ = jax.lax.fori_loop(0, steps, body, (vec, key))
            return vec

        return infer

    def infer_vector(self, text: str, steps: int = 50,
                     lr: float = 0.025) -> np.ndarray:
        """Train a fresh vector for unseen text against frozen tables
        (reference inferVector)."""
        toks = [
            t
            for t in self.tokenizer_factory.create(text).get_tokens()
            if self.vocab.contains_word(t)
        ]
        d = self.layer_size
        key = jax.random.key(abs(hash(text)) % (2**31))
        vec = (jax.random.uniform(key, (d,)) - 0.5) / d
        if not toks:
            return np.asarray(vec)
        idxs = jnp.asarray(
            [self.vocab.index_of(t) for t in toks], jnp.int32
        )
        if self.use_hs:
            codes = self._codes[idxs].astype(jnp.float32)
            points = self._points[idxs]
            cmask = self._code_mask[idxs]
        else:
            t = idxs.shape[0]
            codes = jnp.zeros((t, 1), jnp.float32)
            points = jnp.zeros((t, 1), jnp.int32)
            cmask = jnp.zeros((t, 1), jnp.float32)
        vec = self._infer_fn(
            vec, idxs, self.syn1, self.syn1neg, codes, points, cmask,
            self._neg_table, key, lr, steps,
        )
        return np.asarray(vec)

    def similarity_to_label(self, text: str, label: str) -> float:
        v = self.infer_vector(text)
        u = self.doc_vector(label)
        if u is None:
            return float("nan")
        return float(
            np.dot(v, u)
            / (np.linalg.norm(v) * np.linalg.norm(u) + 1e-12)
        )
