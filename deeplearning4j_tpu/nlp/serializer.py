"""Word-vector serialization: text + Google word2vec binary formats.

Mirror of reference nlp models/embeddings/loader/WordVectorSerializer.java
(writeWordVectors text format; loadGoogleModel binary compat).
"""

from __future__ import annotations

import struct
from typing import TextIO

import numpy as np

from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import VocabCache


def write_word_vectors(vectors: SequenceVectors, path: str) -> None:
    """Text format: one `word v1 v2 ... vD` line per word (reference
    writeWordVectors)."""
    syn0 = np.asarray(vectors.syn0)
    with open(path, "w", encoding="utf-8") as f:
        for vw in vectors.vocab.vocab_words():
            vec = " ".join(f"{x:.6g}" for x in syn0[vw.index])
            f.write(f"{vw.word} {vec}\n")


def load_txt_vectors(path: str) -> SequenceVectors:
    words = []
    rows = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < 2:
                continue
            words.append(parts[0])
            rows.append([float(x) for x in parts[1:]])
    return _assemble(words, np.asarray(rows, np.float32))


def write_google_binary(vectors: SequenceVectors, path: str) -> None:
    """Google word2vec binary format: header `V D\\n`, then per word:
    `word `, D float32s (reference loadGoogleModel's inverse)."""
    syn0 = np.asarray(vectors.syn0, np.float32)
    v, d = syn0.shape
    with open(path, "wb") as f:
        f.write(f"{v} {d}\n".encode())
        for vw in vectors.vocab.vocab_words():
            f.write(vw.word.encode("utf-8") + b" ")
            f.write(syn0[vw.index].astype("<f4").tobytes())
            f.write(b"\n")


def load_google_binary(path: str) -> SequenceVectors:
    with open(path, "rb") as f:
        header = f.readline().decode("utf-8").strip().split()
        v, d = int(header[0]), int(header[1])
        words = []
        rows = np.empty((v, d), np.float32)
        for i in range(v):
            chars = []
            while True:
                ch = f.read(1)
                if ch == b" " or ch == b"":
                    break
                if ch != b"\n":
                    chars.append(ch)
            words.append(b"".join(chars).decode("utf-8"))
            rows[i] = np.frombuffer(f.read(4 * d), dtype="<f4")
            nl = f.read(1)
            if nl not in (b"\n", b""):
                f.seek(-1, 1)
    return _assemble(words, rows)


def _assemble(words, syn0: np.ndarray) -> SequenceVectors:
    import jax.numpy as jnp

    sv = SequenceVectors(layer_size=syn0.shape[1], min_word_frequency=1)
    cache = VocabCache()
    for w in words:
        cache.add_token(w, 1)
    # Preserve file order as index order.
    cache._by_index = [cache._words[w] for w in words]
    for i, vw in enumerate(cache._by_index):
        vw.index = i
    sv.vocab = cache
    sv.syn0 = jnp.asarray(syn0)
    return sv
