"""Document iterators + moving-window context.

Mirror of reference text/documentiterator (DocumentIterator,
FileDocumentIterator, label-aware variants) and text/movingwindow
(Window/Windows — fixed-size context windows with edge padding, the
input representation for windowed classifiers like the MNER example).
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence

PAD = "<PAD>"


class DocumentIterator:
    """Stream of documents (raw strings); resettable."""

    def next_document(self) -> Optional[str]:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            doc = self.next_document()
            if doc is None:
                return
            yield doc


class CollectionDocumentIterator(DocumentIterator):
    def __init__(self, docs: Sequence[str]):
        self._docs = list(docs)
        self._pos = 0

    def next_document(self) -> Optional[str]:
        if self._pos >= len(self._docs):
            return None
        doc = self._docs[self._pos]
        self._pos += 1
        return doc

    def has_next(self) -> bool:
        return self._pos < len(self._docs)

    def reset(self) -> None:
        self._pos = 0


class FileDocumentIterator(DocumentIterator):
    """One document per file under a directory tree (reference
    FileDocumentIterator)."""

    def __init__(self, root: str, extensions: Sequence[str] = (".txt",)):
        self.paths: List[str] = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                if os.path.splitext(fn)[1].lower() in extensions:
                    self.paths.append(os.path.join(dirpath, fn))
        self._pos = 0

    def next_document(self) -> Optional[str]:
        if self._pos >= len(self.paths):
            return None
        path = self.paths[self._pos]
        self._pos += 1
        with open(path, encoding="utf-8", errors="replace") as f:
            return f.read()

    def has_next(self) -> bool:
        return self._pos < len(self.paths)

    def reset(self) -> None:
        self._pos = 0


class LabelAwareDocumentIterator(CollectionDocumentIterator):
    """Documents with labels (reference LabelAwareDocumentIterator —
    feeds ParagraphVectors supervised training)."""

    def __init__(self, docs: Sequence[str], labels: Sequence[str]):
        if len(docs) != len(labels):
            raise ValueError("docs/labels length mismatch")
        super().__init__(docs)
        self.labels = list(labels)

    def current_label(self) -> str:
        """Label of the most recently returned document."""
        if self._pos == 0:
            raise RuntimeError("no document returned yet")
        return self.labels[self._pos - 1]


# ---------------------------------------------------------------------------
# moving-window context (reference text/movingwindow/Window(s).java)
# ---------------------------------------------------------------------------

class Window:
    """A fixed-size token window with a focus position."""

    def __init__(self, tokens: Sequence[str], focus: int,
                 label: Optional[str] = None):
        self.tokens = list(tokens)
        self.focus = focus
        self.label = label

    def focus_word(self) -> str:
        return self.tokens[self.focus]

    def __repr__(self) -> str:
        marked = [f"[{t}]" if i == self.focus else t
                  for i, t in enumerate(self.tokens)]
        return "Window(" + " ".join(marked) + ")"


def windows(tokens: Sequence[str], window_size: int = 5,
            label: Optional[str] = None) -> List[Window]:
    """One window per token, PAD-extended at the edges (reference
    Windows.windows: every word becomes the focus of a size-k window)."""
    if window_size % 2 == 0 or window_size < 1:
        raise ValueError("window_size must be odd and positive")
    half = window_size // 2
    padded = [PAD] * half + list(tokens) + [PAD] * half
    return [
        Window(padded[i:i + window_size], half, label)
        for i in range(len(tokens))
    ]
