"""Trainable HMM part-of-speech tagger.

The reference tags tokens through trained UIMA/ClearTK annotators behind
``PosUimaTokenizer`` (reference text/tokenization/tokenizer/
PosUimaTokenizer.java) — a statistical model shipped as a binary. Round
1 stood that in with the closed-lexicon ``RuleBasedPosTagger``
(nlp/tokenization.py); this module supplies the trainable statistical
counterpart: a supervised bigram HMM (add-k smoothed transition and
emission counts) decoded with the framework's Viterbi
(util/viterbi.py — the reference carries the same algorithm in
util/Viterbi.java). Unknown words back off to orthographic-class
emissions (suffix/capitalization/digit shape) estimated from rare
training words, the classic HMM-tagger unknown-word model.

Interface-compatible with RuleBasedPosTagger (``tag(token)``), plus the
context-aware ``tag_sequence(tokens)`` that single-token rules cannot
express.
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.util.viterbi import viterbi_decode


def _shape_class(word: str) -> str:
    w = word.lower()
    feats = [w[-3:] if len(w) >= 3 else w]
    if word[:1].isupper():
        feats.append("CAP")
    if any(ch.isdigit() for ch in word):
        feats.append("DIG")
    return "|".join(feats)


class HmmPosTagger:
    """Supervised bigram HMM: fit on tagged sentences, Viterbi decode."""

    _pretrained_singleton = None

    def __init__(self, smoothing: float = 0.1, rare_threshold: int = 1):
        self.smoothing = smoothing
        self.rare_threshold = rare_threshold
        self._fitted = False

    @classmethod
    def pretrained(cls) -> "HmmPosTagger":
        """Out-of-the-box tagger trained from the bundled corpus
        (deeplearning4j_tpu/nlp/data) — the analogue of the reference's
        shipped UIMA PoS models (PosUimaTokenizer.java:35-50), which
        make tagging work with zero user setup. Trains in milliseconds
        on first call, then cached for the process."""
        if cls._pretrained_singleton is None:
            from deeplearning4j_tpu.nlp.data import load_tagged_corpus

            cls._pretrained_singleton = cls().fit(load_tagged_corpus())
        return cls._pretrained_singleton

    def fit(
        self, tagged_sentences: Iterable[Sequence[Tuple[str, str]]]
    ) -> "HmmPosTagger":
        trans: Dict[str, Counter] = defaultdict(Counter)
        emit: Dict[str, Counter] = defaultdict(Counter)
        init: Counter = Counter()
        word_counts: Counter = Counter()
        sentences = [list(s) for s in tagged_sentences if s]
        if not sentences:
            raise ValueError("no tagged sentences")
        for sent in sentences:
            for w, _ in sent:
                word_counts[w.lower()] += 1
        shape_emit: Dict[str, Counter] = defaultdict(Counter)
        for sent in sentences:
            prev = None
            for w, t in sent:
                lw = w.lower()
                emit[t][lw] += 1
                if word_counts[lw] <= self.rare_threshold:
                    shape_emit[t][_shape_class(w)] += 1
                if prev is None:
                    init[t] += 1
                else:
                    trans[prev][t] += 1
                prev = t

        self.tags: List[str] = sorted(emit)
        tag_idx = {t: i for i, t in enumerate(self.tags)}
        S = len(self.tags)
        k = self.smoothing
        self._log_init = np.full(S, -math.inf)
        total_init = sum(init.values())
        for t, c in init.items():
            self._log_init[tag_idx[t]] = math.log(c / total_init)
        self._log_init = np.maximum(self._log_init, math.log(k / (S * 10)))
        self._log_trans = np.zeros((S, S))
        for i, t in enumerate(self.tags):
            row = trans[t]
            total = sum(row.values()) + k * S
            for j, t2 in enumerate(self.tags):
                self._log_trans[i, j] = math.log(
                    (row.get(t2, 0) + k) / total)
        # word -> per-tag log emission (smoothed within each tag)
        self._vocab = set(word_counts)
        self._log_emit_word: Dict[str, np.ndarray] = {}
        tag_totals = {t: sum(emit[t].values()) for t in self.tags}
        for w in self._vocab:
            col = np.empty(S)
            for i, t in enumerate(self.tags):
                col[i] = math.log(
                    (emit[t].get(w, 0) + k)
                    / (tag_totals[t] + k * max(1, len(self._vocab))))
            self._log_emit_word[w] = col
        # orthographic-class backoff for OOV words
        self._log_emit_shape: Dict[str, np.ndarray] = {}
        shapes = {s for c in shape_emit.values() for s in c}
        for s in shapes:
            col = np.empty(S)
            for i, t in enumerate(self.tags):
                col[i] = math.log(
                    (shape_emit[t].get(s, 0) + k)
                    / (sum(shape_emit[t].values()) + k * max(1, len(shapes))))
            self._log_emit_shape[s] = col
        self._log_emit_unk = np.full(S, math.log(1.0 / S))
        self._fitted = True
        return self

    # -- decoding ------------------------------------------------------
    def _emission(self, word: str) -> np.ndarray:
        lw = word.lower()
        if lw in self._log_emit_word:
            return self._log_emit_word[lw]
        col = self._log_emit_shape.get(_shape_class(word))
        return col if col is not None else self._log_emit_unk

    def tag_sequence(self, tokens: Sequence[str]) -> List[str]:
        if not self._fitted:
            raise ValueError("fit() must run first")
        if not tokens:
            return []
        log_emit = np.stack([self._emission(w) for w in tokens])
        _, path = viterbi_decode(self._log_init, self._log_trans, log_emit)
        return [self.tags[i] for i in path]

    def tag(self, token: str) -> str:
        """Single-token compatibility with RuleBasedPosTagger (no
        context: the HMM reduces to argmax init+emission)."""
        if not token:
            return "NONE"
        return self.tag_sequence([token])[0]
