"""HTTP observability server + client.

Mirror of reference deeplearning4j-ui UiServer.java:63 (Dropwizard app,
run :83) on the shared JSON-HTTP scaffolding (util/httpjson.py — also
used by the scaleout coordinator). Listeners POST JSON records; browsers
(or tests) GET them back; ``/`` serves a small self-contained HTML
dashboard polling the JSON endpoints — replacing the reference's
Dropwizard views + JS assets.

Endpoints:
  POST /update             {key, iteration, payload}     → {ok}
  GET  /series?key=…&since=…                             → {points}
  GET  /keys                                             → {keys}
  POST /vectors            {labels, vectors}             → {ok}
      (the Word2Vec nearest-neighbors upload; VPTree-indexed)
  GET  /nearest?word=…&k=…                               → {neighbors}
  GET  /train/metrics      Prometheus exposition text    (ISSUE 8)
  GET  /train/trace        Chrome trace-event JSON       (ISSUE 8)
  GET  /                                                 → HTML dashboard

The two ``/train/*`` endpoints render an attached
:class:`~deeplearning4j_tpu.profiler.tracer.Tracer` (``UiServer(...,
tracer=)`` or :meth:`UiServer.attach_tracer`) with the SAME renderers
the serving gateway uses — ``Tracer.prometheus_text`` for a scrape
target and the Chrome trace-event event list for Perfetto — so a
training run is observable with the exact tooling the serving stack
already taught (scripts/latency_report.py reads either).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.ui.storage import HistoryStorage
from deeplearning4j_tpu.util.httpjson import HttpService, JsonHandler

_DASHBOARD = """<!doctype html>
<html><head><meta charset="utf-8"><title>deeplearning4j_tpu</title>
<style>
body{font-family:monospace;margin:2em;background:#fafafa}
.card{background:#fff;border:1px solid #ddd;border-radius:6px;
      padding:12px;margin:10px 0}
canvas{display:block}
h1{font-size:18px} h2{font-size:13px;margin:0 0 6px 0;color:#333}
pre{background:#f4f4f4;padding:8px;max-height:120px;overflow:auto}
</style></head>
<body><h1>deeplearning4j_tpu training dashboard</h1>
<div id="charts"></div>
<script>
// Per-series renderers: numeric payloads -> line chart; histogram
// payloads ({bins:[...], counts:[...]} or {name:[...counts]}) -> bars;
// anything else -> latest-value text (the reference's
// histogram/score/activations views, vanilla canvas instead of
// Dropwizard+JS assets).
const cards = {};  // key -> element (keys may contain arbitrary text)
function card(key){
  let el = cards[key];
  if (!el){
    el = document.createElement('div'); el.className='card';
    const h2 = document.createElement('h2');
    h2.textContent = key;  // textContent: never inject keys as HTML
    const cv = document.createElement('canvas');
    cv.width = 640; cv.height = 160;
    const pre = document.createElement('pre');
    pre.style.display = 'none';
    el.append(h2, cv, pre);
    document.getElementById('charts').appendChild(el);
    cards[key] = el;
  }
  return el;
}
function line(ctx, pts, W, H){
  // loop, not Math.min(...spread): spread throws on very long series
  let x0=Infinity, x1=-Infinity, y0=Infinity, y1=-Infinity;
  for (const p of pts){
    const x = p[0], y = Number(p[1]);
    if (x < x0) x0 = x; if (x > x1) x1 = x;
    if (y < y0) y0 = y; if (y > y1) y1 = y;
  }
  const sx = i => 40 + (W-50) * (x1>x0 ? (i-x0)/(x1-x0) : 0.5);
  const sy = v => H-18 - (H-30) * (y1>y0 ? (v-y0)/(y1-y0) : 0.5);
  ctx.strokeStyle='#888'; ctx.strokeRect(40, 12, W-50, H-30);
  ctx.fillStyle='#333'; ctx.font='10px monospace';
  ctx.fillText(y1.toPrecision(4), 2, 18);
  ctx.fillText(y0.toPrecision(4), 2, H-18);
  ctx.fillText('iter '+x0, 40, H-4); ctx.fillText(''+x1, W-60, H-4);
  ctx.strokeStyle='#0a62c9'; ctx.beginPath();
  pts.forEach((p,i)=>{const X=sx(p[0]),Y=sy(Number(p[1]));
                      i?ctx.lineTo(X,Y):ctx.moveTo(X,Y);});
  ctx.stroke();
}
function bars(ctx, counts, W, H){
  let m = 1;
  for (const c of counts) if (c > m) m = c;
  const bw = (W-50)/counts.length;
  ctx.fillStyle='#0a62c9';
  counts.forEach((c,i)=>{
    const h = (H-30)*c/m;
    ctx.fillRect(40+i*bw, H-18-h, Math.max(1,bw-1), h);
  });
  ctx.strokeStyle='#888'; ctx.strokeRect(40, 12, W-50, H-30);
}
function imageGrid(ctx, v, W, H){
  // per-channel activation maps / per-filter kernels as a grey grid
  // (the reference's convolutional activation/filter render view)
  const n = v.images.length;
  const cols = Math.ceil(Math.sqrt(n)), rows = Math.ceil(n/cols);
  const cell = Math.max(8, Math.min(Math.floor((W-20)/cols),
                                    Math.floor((H-20)/rows)));
  ctx.imageSmoothingEnabled = false;
  v.images.forEach((img, i) => {
    const oc = document.createElement('canvas');
    oc.width = v.w; oc.height = v.h;
    const id = oc.getContext('2d').createImageData(v.w, v.h);
    img.forEach((px, j) => {
      id.data[4*j] = px; id.data[4*j+1] = px; id.data[4*j+2] = px;
      id.data[4*j+3] = 255;
    });
    oc.getContext('2d').putImageData(id, 0, 0);
    ctx.drawImage(oc, 10+(i%cols)*cell, 10+Math.floor(i/cols)*cell,
                  cell-2, cell-2);
  });
}
function scatter(ctx, v, W, H){
  // 2-D embedding scatter (the reference's t-SNE render view)
  let x0=Infinity,x1=-Infinity,y0=Infinity,y1=-Infinity;
  for (const p of v.points){
    if (p[0]<x0)x0=p[0]; if (p[0]>x1)x1=p[0];
    if (p[1]<y0)y0=p[1]; if (p[1]>y1)y1=p[1];
  }
  const sx = x => 12 + (W-24)*(x1>x0 ? (x-x0)/(x1-x0) : 0.5);
  const sy = y => H-12 - (H-24)*(y1>y0 ? (y-y0)/(y1-y0) : 0.5);
  ctx.strokeStyle='#888'; ctx.strokeRect(8, 8, W-16, H-16);
  ctx.fillStyle='#0a62c9'; ctx.font='9px monospace';
  v.points.forEach((p, i) => {
    const X = sx(p[0]), Y = sy(p[1]);
    ctx.beginPath(); ctx.arc(X, Y, 2, 0, 6.3); ctx.fill();
    if (v.labels && v.points.length <= 200){
      ctx.fillStyle='#555'; ctx.fillText(v.labels[i], X+3, Y-2);
      ctx.fillStyle='#0a62c9';
    }
  });
}
function flow(ctx, v, W, H, cv){
  // network structure boxes + connections; hover highlights a layer
  // and click pins its detail panel (the reference's interactive
  // FlowIterationListener view with per-layer ModelInfo)
  const L = v.layers, n = L.length;
  const bw = Math.min(110, Math.floor((W-30)/n)-8), bh = 52;
  const y = Math.floor(H/2) - bh/2;
  ctx.font='9px monospace';
  const boxes = [];
  const hov = cv._flowHover, pin = cv._flowPin;
  L.forEach((l, i) => {
    const x = 15 + i*(bw+8);
    boxes.push({x:x, y:y, w:bw, h:bh, layer:l});
    const hot = (i === hov) || (i === pin);
    ctx.fillStyle = hot ? '#cfe3fa' : '#eaf2fc';
    ctx.fillRect(x, y, bw, bh);
    ctx.strokeStyle='#0a62c9'; ctx.lineWidth = hot ? 2 : 1;
    ctx.strokeRect(x, y, bw, bh); ctx.lineWidth = 1;
    ctx.fillStyle='#222';
    ctx.fillText(String(l.type).slice(0, 14), x+3, y+12);
    ctx.fillText((l.n_in==null?'?':l.n_in)+' -> '+
                 (l.n_out==null?'?':l.n_out), x+3, y+26);
    if (l.activation) ctx.fillText(String(l.activation), x+3, y+40);
    if (i){
      ctx.strokeStyle='#888'; ctx.beginPath();
      ctx.moveTo(x-8, y+bh/2); ctx.lineTo(x, y+bh/2); ctx.stroke();
      ctx.beginPath(); ctx.moveTo(x-4, y+bh/2-3); ctx.lineTo(x, y+bh/2);
      ctx.lineTo(x-4, y+bh/2+3); ctx.stroke();
    }
  });
  ctx.fillStyle='#555';
  ctx.fillText('params: '+v.num_params+
               '   (hover a layer; click to pin)', 15, y+bh+14);
  cv._flowBoxes = boxes;
  cv._flowLast = v;
  const detail = () => {
    const idx = (cv._flowPin != null) ? cv._flowPin : cv._flowHover;
    const pre = cv.parentElement.querySelector('pre');
    if (idx == null || !cv._flowBoxes[idx]){
      pre.style.display='none'; return;
    }
    const l = cv._flowBoxes[idx].layer;
    pre.style.display='block';
    pre.textContent =
      'layer '+l.index+': '+l.type+'\\n'+
      'in/out: '+l.n_in+' -> '+l.n_out+
      (l.activation ? '   activation: '+l.activation : '')+'\\n'+
      'params: '+(l.n_params==null?'?':l.n_params)+
      '   shapes: '+JSON.stringify(l.param_shapes||{})+'\\n'+
      (l.preprocessor ? 'preprocessor: '+l.preprocessor+'\\n' : '')+
      (l.updater ? 'updater: '+l.updater : '');
  };
  detail();  // keep a pinned/hovered panel alive across poll redraws
  wireFlowCanvas(cv, ctx);
}
function wireFlowCanvas(cv, ctx){
  // shared hover/click wiring for both flow renderers; redraw
  // dispatches on the LAST payload's shape because the same 'flow'
  // key can switch between chain and DAG payloads across runs
  if (cv._flowWired) return;
  cv._flowWired = true;
  const hit = ev => {
    const r = cv.getBoundingClientRect();
    const mx = ev.clientX - r.left, my = ev.clientY - r.top;
    const bs = cv._flowBoxes || [];
    for (let i = 0; i < bs.length; i++){
      const b = bs[i];
      if (mx>=b.x && mx<=b.x+b.w && my>=b.y && my<=b.y+b.h) return i;
    }
    return null;
  };
  const redraw = () => {
    ctx.clearRect(0, 0, cv.width, cv.height);
    const f = (cv._flowLast && cv._flowLast.vertices) ? dagflow : flow;
    f(ctx, cv._flowLast, cv.width, cv.height, cv);
  };
  cv.addEventListener('mousemove', ev => {
    const i = hit(ev);
    if (i !== cv._flowHover){ cv._flowHover = i; redraw(); }
  });
  cv.addEventListener('click', ev => {
    const i = hit(ev);
    cv._flowPin = (cv._flowPin === i) ? null : i;
    redraw();
  });
  cv.addEventListener('mouseleave', () => {
    if (cv._flowHover != null){ cv._flowHover = null; redraw(); }
  });
}
function dagDepths(v){
  // longest path from the network inputs -> column per vertex; also
  // the widest column (for canvas sizing). Shared by dagflow() and
  // render() so layout and height cannot diverge.
  const depth = {}, count = {};
  (v.inputs||[]).forEach(n => depth[n] = 0);
  count[0] = (v.inputs||[]).length;
  v.vertices.forEach(vert => {
    let d = 1;
    vert.inputs.forEach(inp => {
      const di = (depth[inp] == null ? 0 : depth[inp]) + 1;
      if (di > d) d = di;
    });
    depth[vert.name] = d;
    count[d] = (count[d]||0)+1;
  });
  let maxCol = 1, ncols = 1;
  for (const k in count){
    if (count[k] > maxCol) maxCol = count[k];
    if (Number(k)+1 > ncols) ncols = Number(k)+1;
  }
  return {depth: depth, maxCol: maxCol, ncols: ncols};
}
function dagflow(ctx, v, W, H, cv){
  // ComputationGraph conf DAG: vertices in topological columns
  // (longest path from the network inputs), edges drawn between
  // boxes, hover/click detail like the chain flow view (the
  // reference's graph flow render, flow/FlowIterationListener.java)
  const depth = dagDepths(v).depth;
  const nodes = v.inputs.map(n => ({name:n, type:'INPUT', inputs:[]}))
                 .concat(v.vertices);
  const cols = {};
  let ncols = 1;
  nodes.forEach(n => {
    const d = depth[n.name] || 0;
    (cols[d] = cols[d] || []).push(n);
    if (d+1 > ncols) ncols = d+1;
  });
  // deep chains: boxes never shrink below readable width — the
  // canvas grows instead (render() sizes it from ncols)
  const bw = Math.max(24, Math.min(104, Math.floor((W-30)/ncols)-12));
  const bh = 40;
  const pos = {}, boxes = [];
  const hov = cv._flowHover, pin = cv._flowPin;
  Object.keys(cols).map(Number).sort((a,b)=>a-b).forEach(d => {
    cols[d].forEach((n, r) => {
      const rowH = Math.max(bh+10, Math.floor((H-30)/cols[d].length));
      const x = 15 + d*(bw+14);
      const y = 10 + r*rowH + Math.max(0, (rowH-bh-10)/2);
      pos[n.name] = {x:x, y:y};
    });
  });
  ctx.strokeStyle='#999';
  v.vertices.forEach(vert => {
    const t = pos[vert.name];
    vert.inputs.forEach(inp => {
      const s = pos[inp];
      if (!s) return;
      ctx.beginPath();
      ctx.moveTo(s.x+bw, s.y+bh/2);
      ctx.bezierCurveTo(s.x+bw+8, s.y+bh/2, t.x-8, t.y+bh/2,
                        t.x, t.y+bh/2);
      ctx.stroke();
      ctx.beginPath(); ctx.moveTo(t.x-5, t.y+bh/2-3);
      ctx.lineTo(t.x, t.y+bh/2); ctx.lineTo(t.x-5, t.y+bh/2+3);
      ctx.stroke();
    });
  });
  ctx.font='9px monospace';
  nodes.forEach((n, i) => {
    const p = pos[n.name];
    boxes.push({x:p.x, y:p.y, w:bw, h:bh, layer:n});
    const hot = (i === hov) || (i === pin);
    const isOut = v.outputs.indexOf(n.name) >= 0;
    ctx.fillStyle = hot ? '#cfe3fa'
                  : (n.type === 'INPUT' ? '#f2f2f2'
                  : (isOut ? '#e4f3e4' : '#eaf2fc'));
    ctx.fillRect(p.x, p.y, bw, bh);
    ctx.strokeStyle = isOut ? '#2d8a2d' : '#0a62c9';
    ctx.lineWidth = hot ? 2 : 1;
    ctx.strokeRect(p.x, p.y, bw, bh); ctx.lineWidth = 1;
    ctx.fillStyle='#222';
    ctx.fillText(String(n.name).slice(0, 14), p.x+3, p.y+12);
    ctx.fillText(String(n.type).slice(0, 14), p.x+3, p.y+24);
    if (n.activation_mean != null)
      ctx.fillText('|a|='+Number(n.activation_mean).toPrecision(3),
                   p.x+3, p.y+36);
  });
  ctx.fillStyle='#555';
  ctx.fillText('params: '+v.num_params+
               '   (hover a vertex; click to pin)', 15, H-6);
  cv._flowBoxes = boxes;
  cv._flowLast = v;
  const detail = () => {
    const idx = (cv._flowPin != null) ? cv._flowPin : cv._flowHover;
    const pre = cv.parentElement.querySelector('pre');
    if (idx == null || !cv._flowBoxes[idx]){
      pre.style.display='none'; return;
    }
    const l = cv._flowBoxes[idx].layer;
    pre.style.display='block';
    pre.textContent =
      l.name+': '+l.type+'\\n'+
      'inputs: '+JSON.stringify(l.inputs||[])+'\\n'+
      'in/out: '+l.n_in+' -> '+l.n_out+
      (l.activation ? '   activation: '+l.activation : '')+'\\n'+
      'params: '+(l.n_params==null?'?':l.n_params)+
      '   shapes: '+JSON.stringify(l.param_shapes||{})+'\\n'+
      (l.activation_mean != null ?
        'act mean|.|: '+l.activation_mean+'  std: '+l.activation_std
        : '');
  };
  detail();
  wireFlowCanvas(cv, ctx);
}
function wireScrub(el, cv, pts, draw){
  // iteration scrubber for per-iteration payload drops (the reference
  // t-SNE tab re-renders each drop; dragging replays the history,
  // releasing at the right edge returns to live)
  cv._scrubPts = pts;
  let s = el.querySelector('input[type=range]');
  if (!s){
    s = document.createElement('input');
    s.type = 'range'; s.min = 0; s.style.width = '620px';
    el.appendChild(s);
    const lab = document.createElement('span');
    lab.style.cssText = 'font-size:10px;color:#555;margin-left:6px';
    el.appendChild(lab);
    cv._scrubLab = lab;
    s.addEventListener('input', () => {
      const P = cv._scrubPts;
      const i = Number(s.value);
      // Pin the ITERATION, not the index: the KEEP trim shifts indices
      // as new points arrive, which would silently advance a "frozen"
      // view at live rate.
      cv._scrubIter = (i >= P.length - 1) ? null : P[i][0];
      draw();
    });
  }
  const atLive = cv._scrubIter == null;
  s.max = Math.max(0, pts.length - 1);
  let shown = pts.length - 1;
  if (!atLive){
    shown = 0;
    for (let i = pts.length - 1; i >= 0; i--)
      if (pts[i][0] <= cv._scrubIter){ shown = i; break; }
  }
  s.value = shown;
  cv._scrubLab.textContent = 'iter '+pts[shown][0]+
    (atLive ? ' (live)' : ' (scrubbed — drag right for live)');
  return shown;
}
function render(key, pts){
  const el = card(key);
  const cv = el.querySelector('canvas'), pre = el.querySelector('pre');
  const showChart = on => {
    cv.style.display = on ? 'block' : 'none';
    pre.style.display = on ? 'none' : 'block';
  };
  const setH = h => { if (cv.height !== h) cv.height = h; };
  const ctx = cv.getContext('2d');
  const last = pts[pts.length-1];
  const numeric = pts.every(p=>typeof p[1] === 'number');
  const v = last[1];
  if (numeric){ setH(160); }
  else if (v && v.type === 'image_grid'){ setH(280); }
  else if (v && v.type === 'scatter'){ setH(280); }
  ctx.clearRect(0,0,cv.width,cv.height);
  if (numeric){ showChart(true); line(ctx, pts, cv.width, cv.height);
                return; }
  if (v && v.type === 'image_grid'){
    showChart(true); imageGrid(ctx, v, cv.width, cv.height); return;
  }
  if (v && v.type === 'scatter'){
    showChart(true);
    const draw = () => {
      const P = cv._scrubPts;
      let i = P.length - 1;
      if (cv._scrubIter != null){
        i = 0;
        for (let j = P.length - 1; j >= 0; j--)
          if (P[j][0] <= cv._scrubIter){ i = j; break; }
      }
      ctx.clearRect(0, 0, cv.width, cv.height);
      scatter(ctx, P[i][1], cv.width, cv.height);
    };
    wireScrub(el, cv, pts, draw);
    draw();
    return;
  }
  if (v && Array.isArray(v.layers)){
    setH(120); ctx.clearRect(0,0,cv.width,cv.height);
    showChart(true); flow(ctx, v, cv.width, cv.height, cv); return;
  }
  if (v && Array.isArray(v.vertices)){
    const dd = dagDepths(v);
    setH(Math.max(150, 56*dd.maxCol + 30));
    // grow the canvas sideways for deep graphs so columns past the
    // default width are drawn, not clipped
    const needW = 30 + dd.ncols*(24+14);
    if (cv.width < needW) cv.width = needW;
    ctx.clearRect(0,0,cv.width,cv.height);
    showChart(true); dagflow(ctx, v, cv.width, cv.height, cv); return;
  }
  let counts = null;
  if (v && Array.isArray(v.counts)) counts = v.counts;
  else if (v && typeof v === 'object'){
    const first = Object.values(v)[0];
    if (Array.isArray(first) && first.every(n=>typeof n==='number'))
      counts = first;
  }
  if (counts){ setH(160); ctx.clearRect(0,0,cv.width,cv.height);
               showChart(true); bars(ctx, counts, cv.width, cv.height);
               return; }
  showChart(false);
  pre.textContent = '@'+last[0]+': '+JSON.stringify(v).slice(0,800);
}
const history = {};   // key -> accumulated points
const fetched = {};   // key -> server-side append count already pulled
const KEEP = 5000;    // client-side retention bound
async function poll(k, serverCount){
  try {
    const have = fetched[k] || 0;
    if (serverCount < have){          // server restarted/reset: refetch
      history[k] = []; fetched[k] = 0;
    } else if (serverCount === have){ // nothing new: skip the request
      return;
    }
    const off = fetched[k]||0;
    const s = await (await fetch('/series?key='+encodeURIComponent(k)+
                                 '&offset='+off)).json();
    // count from the server-reported start, not our requested offset:
    // a server that trimmed past our offset returns start > off, and
    // assuming points began at off would re-fetch and duplicate the
    // retained series next tick
    fetched[k] = (typeof s.start === 'number' ? s.start : off) +
                 s.points.length;
    let pts = (history[k]||[]).concat(s.points);
    if (pts.length > KEEP) pts = pts.slice(-KEEP);
    history[k] = pts;
    if (pts.length) render(k, pts);
  } catch (e) { /* per-key failure must not break other charts */ }
}
async function tick(){
  const ks = await (await fetch('/keys')).json();
  await Promise.all(ks.keys.map(k => poll(k, ks.counts[k]||0)));
}
// chained loop (not setInterval): no overlapping ticks on slow servers
async function loop(){
  try { await tick(); } catch (e) {}
  setTimeout(loop, 2000);
}
loop();
</script></body></html>"""


class _Handler(JsonHandler):
    storage: HistoryStorage
    server_ref: "UiServer"

    def do_GET(self) -> None:
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        if parsed.path == "/":
            self.send_bytes(_DASHBOARD.encode(), "text/html")
        elif parsed.path == "/keys":
            self.send_json({"keys": self.storage.keys(),
                            "counts": self.storage.counts()})
        elif parsed.path == "/series":
            key = qs.get("key", [""])[0]
            if "offset" in qs:
                start, points = self.storage.get_window(
                    key, int(qs["offset"][0]))
                self.send_json({"points": points, "start": start})
            else:
                since = int(qs.get("since", ["-1"])[0])
                self.send_json({"points": self.storage.get(key, since)})
        elif parsed.path == "/nearest":
            word = qs.get("word", [""])[0]
            k = int(qs.get("k", ["5"])[0])
            try:
                self.send_json(
                    {"neighbors": self.server_ref.nearest(word, k)})
            except KeyError:
                self.send_json({"error": f"unknown word {word!r}"}, 404)
        elif parsed.path == "/train/metrics":
            tracer = self.server_ref.tracer
            if tracer is None:
                self.send_json(
                    {"error": "no tracer attached (UiServer(tracer=) "
                              "or attach_tracer)"}, 404)
            else:
                self.send_bytes(
                    tracer.prometheus_text().encode(),
                    "text/plain; version=0.0.4")
        elif parsed.path == "/train/trace":
            tracer = self.server_ref.tracer
            if tracer is None:
                self.send_json({"error": "no tracer attached"}, 404)
            else:
                self.send_json({"traceEvents": tracer.events()})
        else:
            self.send_json({"error": "not found"}, 404)

    def do_POST(self) -> None:
        body = self.read_json()
        if self.path == "/update":
            self.storage.put(body["key"], body["iteration"], body["payload"])
            self.send_json({"ok": True})
        elif self.path == "/vectors":
            self.server_ref.set_vectors(body["labels"], body["vectors"])
            self.send_json({"ok": True})
        else:
            self.send_json({"error": "not found"}, 404)


class UiServer(HttpService):
    """Threaded observability server over a HistoryStorage."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 storage: Optional[HistoryStorage] = None,
                 tracer=None):
        self.storage = storage or HistoryStorage()
        self.tracer = tracer
        super().__init__(_Handler, host, port,
                         storage=self.storage, server_ref=self)
        self._vec_lock = threading.Lock()
        self._labels: List[str] = []
        self._tree = None

    def attach_tracer(self, tracer) -> None:
        """Expose a (training) Tracer at ``/train/metrics`` +
        ``/train/trace`` — attach the same tracer the
        TracingIterationListener feeds."""
        self.tracer = tracer

    # -- word2vec nearest neighbors (reference nearestneighbors/word2vec) --
    def set_vectors(self, labels: List[str], vectors) -> None:
        from deeplearning4j_tpu.clustering.vptree import VPTree

        tree = VPTree(np.asarray(vectors, np.float64),
                      labels=list(labels), similarity="cosine")
        with self._vec_lock:
            self._labels = list(labels)
            self._tree = tree

    def nearest(self, word: str, k: int = 5) -> List[str]:
        with self._vec_lock:
            if self._tree is None or word not in self._labels:
                raise KeyError(word)
            q = self._tree.items[self._labels.index(word)]
            # k+1 then drop the word itself
            out = [w for w in self._tree.words_nearest(q, k + 1)
                   if w != word]
            return out[:k]


class UiClient:
    """POSTs records to a remote UiServer — what a listener uses when the
    server runs in another process (the reference's listener→REST path)."""

    def __init__(self, address: str, timeout: float = 5.0):
        self.address = address.rstrip("/")
        self.timeout = timeout

    def _post(self, path: str, payload: Dict[str, Any]) -> None:
        req = urllib.request.Request(
            self.address + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=self.timeout).read()

    def put(self, key: str, iteration: int, payload: Any) -> None:
        self._post("/update", {"key": key, "iteration": iteration,
                               "payload": payload})

    def set_vectors(self, labels: List[str], vectors) -> None:
        self._post("/vectors", {"labels": list(labels),
                                "vectors": np.asarray(vectors).tolist()})

    def get_series(self, key: str, since: int = -1) -> List[tuple]:
        url = (f"{self.address}/series?"
               + urllib.parse.urlencode({"key": key, "since": since}))
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return [tuple(p) for p in json.loads(resp.read())["points"]]

    def nearest(self, word: str, k: int = 5) -> List[str]:
        url = (f"{self.address}/nearest?"
               + urllib.parse.urlencode({"word": word, "k": k}))
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return json.loads(resp.read())["neighbors"]

    def get_train_metrics(self) -> str:
        """Prometheus exposition text from ``/train/metrics``."""
        with urllib.request.urlopen(self.address + "/train/metrics",
                                    timeout=self.timeout) as resp:
            return resp.read().decode("utf-8", "replace")

    def get_train_trace(self) -> Dict[str, Any]:
        """Chrome trace-event document from ``/train/trace``."""
        with urllib.request.urlopen(self.address + "/train/trace",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())
