"""IterationListeners that feed the observability UI.

Mirror of reference deeplearning4j-ui listeners (SURVEY.md §5.5):
``HistogramIterationListener`` (weights/HistogramIterationListener.java —
score + per-param/per-gradient histograms), ``FlowIterationListener``
(flow/FlowIterationListener.java — model structure snapshot), and
``ActivationIterationListener`` (activation render feed). Each writes to a
``sink``: a HistoryStorage (in-process) or a UiClient (HTTP POST to a
UiServer in another process) — both expose ``put(key, iteration, payload)``.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.ui.storage import histogram


class HistogramIterationListener(IterationListener):
    """Score series + parameter histograms every N iterations."""

    def __init__(self, sink: Any, frequency: int = 1, bins: int = 20):
        self.sink = sink
        self.invoked_every = frequency
        self.bins = bins

    def iteration_done(self, model, iteration: int) -> None:
        self.sink.put("score", iteration, float(model.score_value))
        for key, p in model.param_table().items():
            self.sink.put(f"histogram/{key}", iteration,
                          histogram(np.asarray(p), bins=self.bins))


def _act_stats(act) -> dict:
    a = np.asarray(act)
    return {"activation_mean": round(float(np.mean(np.abs(a))), 6),
            "activation_std": round(float(np.std(a)), 6)}


class FlowIterationListener(IterationListener):
    """Model-structure snapshot: MultiLayerNetwork chains render as the
    linear flow view; ComputationGraphs ship their conf DAG (vertices +
    input edges in topological order) so the dashboard draws the graph
    the reference's flow view draws (flow/FlowIterationListener.java:1).
    With ``probe_features`` set, every layer/vertex also carries
    activation mean/std on that probe batch (the per-vertex ModelInfo
    stats)."""

    def __init__(self, sink: Any, frequency: int = 1,
                 probe_features=None):
        self.sink = sink
        self.invoked_every = frequency
        self.probe = probe_features

    def iteration_done(self, model, iteration: int) -> None:
        if hasattr(model.conf, "vertices"):
            self._graph_flow(model, iteration)
        else:
            self._chain_flow(model, iteration)

    def _chain_flow(self, model, iteration: int) -> None:
        acts = None
        if self.probe is not None:
            acts = model.feed_forward(self.probe, train=False)
        layers = []
        for i, conf in enumerate(model.conf.confs):
            bean = conf.layer
            si = str(i)
            shapes = {
                name: list(np.asarray(p).shape)
                for name, p in model.params.get(si, {}).items()
            }
            n_par = int(sum(int(np.prod(s)) for s in shapes.values()))
            pp = model.conf.preprocessor_for(i)
            entry = {
                "index": i,
                "type": type(bean).__name__,
                "n_in": getattr(bean, "n_in", None),
                "n_out": getattr(bean, "n_out", None),
                "activation": getattr(bean, "activation", None),
                # per-layer detail for the flow view's hover/click panel
                # (reference FlowIterationListener's per-layer ModelInfo,
                # FlowIterationListener.java:120-200)
                "n_params": n_par,
                "param_shapes": shapes,
                "preprocessor": type(pp).__name__ if pp else None,
                "updater": str(conf.resolved("updater") or ""),
            }
            if acts is not None and i + 1 < len(acts):
                entry.update(_act_stats(acts[i + 1]))  # acts[0] = input
            layers.append(entry)
        n_params = int(sum(np.asarray(p).size
                           for p in model.param_table().values()))
        self.sink.put("flow", iteration,
                      {"layers": layers, "num_params": n_params})

    def _graph_flow(self, model, iteration: int) -> None:
        conf = model.conf
        acts = None
        if self.probe is not None:
            probe = self.probe
            if not isinstance(probe, dict):
                probe = (probe,)
                acts = model.feed_forward(*probe)
            else:
                acts = model.feed_forward(
                    *[probe[k] for k in conf.network_inputs])
        vertices = []
        for name in conf.topological_order():
            bean = conf.vertices[name]
            shapes = {
                pname: list(np.asarray(p).shape)
                for pname, p in model.params.get(name, {}).items()
            }
            layer_conf = getattr(bean, "conf", None)
            layer_bean = layer_conf.layer if layer_conf else None
            entry = {
                "name": name,
                "type": (type(layer_bean).__name__ if layer_bean
                         else type(bean).__name__),
                "inputs": list(conf.vertex_inputs.get(name, [])),
                "n_in": getattr(layer_bean, "n_in", None),
                "n_out": getattr(layer_bean, "n_out", None),
                "activation": getattr(layer_bean, "activation", None),
                "n_params": int(sum(int(np.prod(s))
                                    for s in shapes.values())),
                "param_shapes": shapes,
            }
            if acts is not None and name in acts:
                entry.update(_act_stats(acts[name]))
            vertices.append(entry)
        n_params = int(sum(
            np.asarray(p).size
            for group in model.params.values()
            for p in group.values()))
        self.sink.put("flow", iteration, {
            "vertices": vertices,
            "inputs": list(conf.network_inputs),
            "outputs": list(conf.network_outputs),
            "num_params": n_params,
        })


class ActivationIterationListener(IterationListener):
    """Mean |activation| per layer on a probe batch — the activations
    render feed (reference UpdateActivationIterationListener)."""

    def __init__(self, sink: Any, probe_features, frequency: int = 1):
        self.sink = sink
        self.probe = np.asarray(probe_features)
        self.invoked_every = frequency

    def iteration_done(self, model, iteration: int) -> None:
        acts = model.feed_forward(self.probe, train=False)
        self.sink.put(
            "activations", iteration,
            [float(np.mean(np.abs(np.asarray(a)))) for a in acts])


class ActivationImageListener(IterationListener):
    """Convolutional activation maps + filter kernels rendered as image
    grids (reference deeplearning4j-ui activation render path): for each
    4-D layer activation on the probe batch, ship the first example's
    channel maps; for each 4-D weight, ship the per-output-filter
    kernels."""

    def __init__(self, sink: Any, probe_features, frequency: int = 1,
                 max_images: int = 16):
        from deeplearning4j_tpu.ui.render import (
            filter_grid_payload,
            image_grid_payload,
        )

        self.sink = sink
        self.probe = np.asarray(probe_features)
        self.invoked_every = frequency
        self.max_images = max_images
        self._act_grid = image_grid_payload
        self._filter_grid = filter_grid_payload

    def iteration_done(self, model, iteration: int) -> None:
        acts = model.feed_forward(self.probe, train=False)  # input first
        for i, a in enumerate(acts):
            a = np.asarray(a)
            if a.ndim == 4:
                name = "input" if i == 0 else f"layer{i - 1}"
                self.sink.put(f"activation_images/{name}", iteration,
                              self._act_grid(a, self.max_images))
        for key, p in model.param_table().items():
            p = np.asarray(p)
            if p.ndim == 4:
                self.sink.put(f"filters/{key}", iteration,
                              self._filter_grid(p, self.max_images))
