"""In-memory time-series store for training observability.

Mirror of reference ui/storage/HistoryStorage.java (SURVEY.md §5.5): keyed
series of per-iteration records (scores, histograms, activations, t-SNE
coordinates, model structure), thread-safe, with bounded retention so a
long run cannot exhaust host memory (the reference keeps everything —
bounding is an improvement, tunable via ``max_points``).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Any, Dict, List, Optional

import numpy as np


def histogram(values, bins: int = 20) -> Dict[str, List[float]]:
    """np.histogram → JSON-friendly {counts, edges}."""
    arr = np.asarray(values).ravel()
    counts, edges = np.histogram(arr, bins=bins)
    return {"counts": counts.tolist(), "edges": edges.tolist()}


class HistoryStorage:
    """Keyed append-only series: key → [(iteration, payload), ...]."""

    def __init__(self, max_points: int = 10_000):
        self._lock = threading.RLock()
        self._series: Dict[str, List[tuple]] = defaultdict(list)
        self._appended: Dict[str, int] = defaultdict(int)  # incl. trimmed
        self.max_points = max_points

    def put(self, key: str, iteration: int, payload: Any) -> None:
        with self._lock:
            series = self._series[key]
            series.append((int(iteration), payload))
            self._appended[key] += 1
            if len(series) > self.max_points:
                del series[: len(series) - self.max_points]

    def get(self, key: str, since: int = -1) -> List[tuple]:
        with self._lock:
            return [(i, p) for i, p in self._series.get(key, [])
                    if i > since]

    def get_from(self, key: str, offset: int = 0) -> List[tuple]:
        """Points appended at global position >= offset — count-based
        incremental polling that stays correct across iteration resets
        and duplicate iteration numbers (offsets account for trimming)."""
        return self.get_window(key, offset)[1]

    def get_window(self, key: str, offset: int = 0):
        """(start, points) where ``start`` is the actual global append
        position of points[0]. When the requested offset has been trimmed
        away, start > offset is returned so clients can resynchronise
        their counters instead of double-counting the retained series."""
        with self._lock:
            series = self._series.get(key, [])
            dropped = self._appended.get(key, 0) - len(series)
            local = max(0, offset - dropped)
            return dropped + local, list(series[local:])

    def counts(self) -> Dict[str, int]:
        """Total points appended per key (monotone unless the storage is
        replaced — clients reset on decrease)."""
        with self._lock:
            return dict(self._appended)

    def latest(self, key: str) -> Optional[tuple]:
        with self._lock:
            series = self._series.get(key)
            return series[-1] if series else None

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
