"""Training observability UI: history storage, HTTP server, listeners.

Mirror of the reference deeplearning4j-ui module (SURVEY.md §2.8, §5.5):
Dropwizard REST resources + views become a stdlib HTTP/JSON server with a
minimal HTML dashboard; the listeners that POST model snapshots into it
(HistogramIterationListener, FlowIterationListener,
UpdateActivationIterationListener) become IterationListeners that write to
a HistoryStorage either directly (in-process) or over HTTP (remote server),
and the Word2Vec nearest-neighbors view (VPTree-backed) is the /nearest
endpoint.
"""

from deeplearning4j_tpu.ui.storage import HistoryStorage
from deeplearning4j_tpu.ui.server import UiServer, UiClient
from deeplearning4j_tpu.ui.listeners import (
    HistogramIterationListener,
    FlowIterationListener,
    ActivationIterationListener,
)

__all__ = [
    "HistoryStorage",
    "UiServer",
    "UiClient",
    "HistogramIterationListener",
    "FlowIterationListener",
    "ActivationIterationListener",
]
