"""Payload builders for the dashboard's image/scatter/flow views.

Mirror of the reference's renderers the round-1 dashboard lacked
(VERDICT missing #5): convolutional filter/activation image grids
(deeplearning4j-ui activation/ + plot/iterationlistener/
ActivationMeanIterationListener render path), the t-SNE scatter view
(plot renderers), and the interactive network flow view
(flow/FlowIterationListener.java). The builders are pure functions
producing JSON-serializable payloads tagged with ``type``; the
dashboard (ui/server.py) dispatches renderers on that tag.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def _to_uint8(img: np.ndarray) -> List[int]:
    """Normalize one 2-D map to 0..255 (per-image min/max, the
    reference's per-filter normalization in its image render path)."""
    img = np.asarray(img, np.float64)
    lo, hi = float(img.min()), float(img.max())
    if hi > lo:
        img = (img - lo) / (hi - lo)
    else:
        img = np.zeros_like(img)
    return np.round(img * 255).astype(np.uint8).reshape(-1).tolist()


def image_grid_payload(maps, max_images: int = 16) -> dict:
    """[C, H, W] (or [N, C, H, W]: first example) activation maps -> an
    image-grid payload {type, h, w, images: [per-image row-major 0-255]}.
    """
    arr = np.asarray(maps)
    if arr.ndim == 4:
        arr = arr[0]
    if arr.ndim == 2:
        arr = arr[None]
    if arr.ndim != 3:
        raise ValueError(f"expected [C,H,W]-like maps, got {arr.shape}")
    arr = arr[:max_images]
    return {
        "type": "image_grid",
        "h": int(arr.shape[1]),
        "w": int(arr.shape[2]),
        "images": [_to_uint8(m) for m in arr],
    }


def filter_grid_payload(w_oihw, max_images: int = 16) -> dict:
    """Conv kernels [O, I, kH, kW] -> grid of the first-input-channel
    slice of each output filter (the reference's filter render)."""
    w = np.asarray(w_oihw)
    if w.ndim != 4:
        raise ValueError(f"expected [O,I,kH,kW] kernels, got {w.shape}")
    return image_grid_payload(w[:, 0], max_images=max_images)


def scatter_payload(coords, labels: Optional[Sequence[str]] = None) -> dict:
    """2-D embedding coords [N, 2] (t-SNE output) -> scatter payload."""
    c = np.asarray(coords, np.float64)
    if c.ndim != 2 or c.shape[1] != 2:
        raise ValueError(f"expected [N,2] coords, got {c.shape}")
    payload = {"type": "scatter", "points": c.round(4).tolist()}
    if labels is not None:
        if len(labels) != len(c):
            raise ValueError("labels/coords length mismatch")
        payload["labels"] = [str(s) for s in labels]
    return payload


def publish_tsne(sink, coords, labels=None, iteration: int = 0,
                 key: str = "tsne") -> None:
    """Ship a fitted t-SNE embedding (plot/tsne.py output) to the
    dashboard's scatter view."""
    sink.put(key, iteration, scatter_payload(coords, labels))
