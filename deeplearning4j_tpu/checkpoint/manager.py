"""Async step-numbered checkpoint manager (orbax-style).

TPU-native replacement for the reference's scattered checkpoint writers —
ModelSavingActor (save every N updates), LocalFileModelSaver,
HdfsModelSaver/S3ModelSaver (SURVEY.md §5.4). Design goals the reference
lacks and a gang-scheduled TPU job needs (§5.3 checkpoint-restart
elasticity):

- **Async save**: params are snapshotted to host (cheap device→host copy)
  on the training thread, then compressed/written on a background thread so
  the accelerator never idles on disk IO. The pending-save queue is
  BOUNDED (default 2): if the writer falls behind, ``save()`` blocks —
  backpressure instead of accumulating full model copies until OOM.
- **Atomic commits**: write to ``step_N.tmp`` dirs, ``os.replace`` rename —
  a crash mid-save can never leave a torn "latest" checkpoint.
- **Retention**: keep the last ``keep_last_n`` steps plus the best-scoring
  one (early-stopping "best + latest" semantics, reference
  BaseEarlyStoppingTrainer).
- **Iterator state**: dataset-iterator position is saved alongside the
  model (the reference restarts the epoch on resume; we don't).

Model payload serde delegates to util/model_serializer (one format, one
implementation); each step directory holds ``model.zip`` + ``meta.json``
(+ ``iterator.pkl``).
"""

from __future__ import annotations

import json
import os
import pickle
import queue
import re
import shutil
import threading
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu.util.model_serializer import (
    restore_model,
    snapshot,
    write_snapshot,
)

_STEP_RE = re.compile(r"^step_(\d+)$")


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        keep_last_n: int = 3,
        keep_best: bool = True,
        async_save: bool = True,
        max_pending: int = 2,
    ):
        self.directory = directory
        self.keep_last_n = keep_last_n
        self.keep_best = keep_best
        self.async_save = async_save
        os.makedirs(directory, exist_ok=True)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_pending)
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    # Save
    # ------------------------------------------------------------------
    def save(
        self,
        step: int,
        net,
        iterator=None,
        score: Optional[float] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Snapshot on the caller's thread, write on the background one.
        Blocks if ``max_pending`` saves are already in flight."""
        self._check_error()
        payload = {
            "snap": snapshot(net),
            "iterator_state": (
                iterator.state_dict() if iterator is not None else None
            ),
            "score": score,
            "metadata": metadata or {},
        }
        if self.async_save:
            self._ensure_worker()
            self._queue.put((step, payload))
        else:
            self._write(step, payload)

    def wait_until_finished(self) -> None:
        self._queue.join()
        self._check_error()

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(target=self._drain, daemon=True)
            self._worker.start()

    def _drain(self) -> None:
        while True:
            step, payload = self._queue.get()
            try:
                self._write(step, payload)
            except BaseException as e:
                self._error = e
            finally:
                self._queue.task_done()

    def _check_error(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _write(self, step: int, payload: Dict[str, Any]) -> None:
        with self._lock:
            final = os.path.join(self.directory, f"step_{step}")
            tmp = final + ".tmp"
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            write_snapshot(payload["snap"], os.path.join(tmp, "model.zip"))
            meta = {
                "step": step,
                "iteration": payload["snap"]["iteration"],
                "kind": payload["snap"]["kind"],
                "score": payload["score"],
                "metadata": payload["metadata"],
            }
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f)
            if payload["iterator_state"] is not None:
                with open(os.path.join(tmp, "iterator.pkl"), "wb") as f:
                    pickle.dump(payload["iterator_state"], f)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
            self._gc()

    # ------------------------------------------------------------------
    # Retention
    # ------------------------------------------------------------------
    def _all_steps_locked(self):
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def _score_of(self, step: int) -> Optional[float]:
        try:
            with open(
                os.path.join(self.directory, f"step_{step}", "meta.json")
            ) as f:
                return json.load(f).get("score")
        except OSError:
            return None

    def _gc(self) -> None:
        steps = self._all_steps_locked()
        keep = set(steps[-self.keep_last_n:]) if self.keep_last_n else set(
            steps
        )
        if self.keep_best:
            scored = [(s, self._score_of(s)) for s in steps]
            scored = [(s, sc) for s, sc in scored if sc is not None]
            if scored:
                best = min(scored, key=lambda t: t[1])[0]
                keep.add(best)
        for s in steps:
            if s not in keep:
                shutil.rmtree(
                    os.path.join(self.directory, f"step_{s}"),
                    ignore_errors=True,
                )

    # ------------------------------------------------------------------
    # Restore
    # ------------------------------------------------------------------
    def all_steps(self):
        with self._lock:
            return self._all_steps_locked()

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def best_step(self) -> Optional[int]:
        with self._lock:
            scored = [
                (s, self._score_of(s)) for s in self._all_steps_locked()
            ]
        scored = [(s, sc) for s, sc in scored if sc is not None]
        return min(scored, key=lambda t: t[1])[0] if scored else None

    def restore(
        self, step: Optional[int] = None, iterator=None
    ) -> Tuple[Any, Dict[str, Any]]:
        """Returns (net, meta). If ``iterator`` is given, its position is
        restored in place."""
        self.wait_until_finished()
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        path = os.path.join(self.directory, f"step_{step}")
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        net = restore_model(os.path.join(path, "model.zip"))
        ipath = os.path.join(path, "iterator.pkl")
        if iterator is not None and os.path.exists(ipath):
            with open(ipath, "rb") as f:
                iterator.load_state_dict(pickle.load(f))
        return net, meta
