"""Orbax-backed sharded checkpointing.

The multi-host/sharded-array complement to the zip-based
`util/model_serializer.py` and the async `checkpoint/manager.py`
(SURVEY.md §5.4: "orbax-style sharded async checkpoint of (config, param
pytree, opt-state pytree)"): each host writes only its shards, restore
re-shards onto the current mesh. The checkpoint triple matches the
reference's (conf JSON, params, updater) LocalFileModelSaver format
(reference earlystopping/saver/LocalFileModelSaver.java:76-86) so the
same resume semantics hold at pod scale.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional


def _require_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except Exception as e:  # pragma: no cover
        raise ImportError(
            "orbax-checkpoint is required for OrbaxCheckpointer; "
            "use checkpoint.CheckpointManager or util.model_serializer "
            "for single-host checkpoints"
        ) from e


class OrbaxCheckpointer:
    """Save/restore the (conf JSON, params, updater state, iteration)
    triple through orbax's async, shard-aware writers."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        ocp = _require_orbax()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )
        self._ocp = ocp

    # -- save -----------------------------------------------------------
    def save(self, step: int, net, wait: bool = False) -> None:
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(net, ComputationGraph):
            kind = "graph"
        elif isinstance(net, MultiLayerNetwork):
            kind = "multilayer"
        else:
            raise TypeError(
                f"unsupported model type {type(net).__name__}; expected "
                "MultiLayerNetwork or ComputationGraph")
        payload = {
            "params": net.params,
            "updater_state": net.updater_state,
            "state": net.state or {},
        }
        meta = {
            "kind": kind,
            "conf_json": net.conf.to_json(),
            "iteration": int(net.iteration),
            "step": int(step),
        }
        args = self._ocp.args.Composite(
            arrays=self._ocp.args.StandardSave(payload),
            meta=self._ocp.args.JsonSave(meta),
        )
        self._mgr.save(step, args=args)
        if wait:
            self._mgr.wait_until_finished()

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    # -- inspect --------------------------------------------------------
    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    # -- restore --------------------------------------------------------
    def restore(self, step: Optional[int] = None):
        """Rebuild the checkpointed model (MultiLayerNetwork or
        ComputationGraph) at the given (default: latest) step."""
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.multi_layer import (
            MultiLayerConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no orbax checkpoints under {self.directory}")
        # two-phase: meta first, build the target net, then restore the
        # arrays against its pytree so dtypes/shardings are honored
        meta: Dict[str, Any] = self._mgr.restore(
            step, args=self._ocp.args.Composite(
                meta=self._ocp.args.JsonRestore()),
        )["meta"]
        if meta.get("kind", "multilayer") == "graph":
            net = ComputationGraph(
                ComputationGraphConfiguration.from_json(
                    meta["conf_json"])).init()
        else:
            net = MultiLayerNetwork(
                MultiLayerConfiguration.from_json(meta["conf_json"])).init()
        target = {
            "params": net.params,
            "updater_state": net.updater_state,
            "state": net.state or {},
        }
        arrays: Dict[str, Any] = self._mgr.restore(
            step, args=self._ocp.args.Composite(
                arrays=self._ocp.args.StandardRestore(target)),
        )["arrays"]
        net.params = arrays["params"]
        net.updater_state = arrays["updater_state"]
        if arrays.get("state"):
            net.state = arrays["state"]
        net.iteration = int(meta["iteration"])
        return net

    def close(self) -> None:
        self._mgr.close()
