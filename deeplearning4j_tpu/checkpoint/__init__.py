from deeplearning4j_tpu.checkpoint.manager import CheckpointManager

__all__ = ["CheckpointManager"]
