from deeplearning4j_tpu.graph.api import Edge, Graph, NoEdgeHandling, Vertex
from deeplearning4j_tpu.graph.deepwalk import DeepWalk

__all__ = ["Edge", "Graph", "NoEdgeHandling", "Vertex", "DeepWalk"]
