"""Graph API: vertices, edges, adjacency graph.

Capability mirror of reference deeplearning4j-graph api/{IGraph,Vertex,
Edge,NoEdgeHandling}.java + graph/Graph.java (adjacency-list store).
The adjacency is ALSO materialized as padded numpy arrays
(``neighbor_table``) so random-walk generation can run vectorized over
all walkers at once instead of the reference's per-vertex object walk.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

import numpy as np

V = TypeVar("V")


class NoEdgeHandling(enum.Enum):
    SELF_LOOP_ON_DISCONNECTED = "SELF_LOOP_ON_DISCONNECTED"
    EXCEPTION_ON_DISCONNECTED = "EXCEPTION_ON_DISCONNECTED"


class NoEdgesException(Exception):
    pass


@dataclass
class Vertex(Generic[V]):
    idx: int
    value: Optional[V] = None


@dataclass
class Edge:
    frm: int
    to: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    """Adjacency-list graph over integer-indexed vertices (reference
    graph/Graph.java)."""

    def __init__(
        self,
        n_vertices: int,
        allow_multiple_edges: bool = True,
        vertex_values: Optional[Sequence[Any]] = None,
    ):
        self._n = n_vertices
        self.allow_multiple_edges = allow_multiple_edges
        self.vertices = [
            Vertex(i, vertex_values[i] if vertex_values else None)
            for i in range(n_vertices)
        ]
        self._adj: List[List[Tuple[int, float]]] = [
            [] for _ in range(n_vertices)
        ]
        self._edges: List[Edge] = []
        self._table_dirty = True
        self._nbr_table: Optional[np.ndarray] = None
        self._wgt_table: Optional[np.ndarray] = None
        self._degrees: Optional[np.ndarray] = None

    # -- construction ---------------------------------------------------
    def add_edge(
        self, frm: int, to: int, weight: float = 1.0, directed: bool = False
    ) -> None:
        if not (0 <= frm < self._n and 0 <= to < self._n):
            raise IndexError(f"edge ({frm},{to}) out of range 0..{self._n}")
        if not self.allow_multiple_edges and any(
            t == to for t, _ in self._adj[frm]
        ):
            return
        self._edges.append(Edge(frm, to, weight, directed))
        self._adj[frm].append((to, weight))
        if not directed:
            self._adj[to].append((frm, weight))
        self._table_dirty = True

    # -- queries --------------------------------------------------------
    def num_vertices(self) -> int:
        return self._n

    def num_edges(self) -> int:
        return len(self._edges)

    def get_vertex(self, idx: int) -> Vertex:
        return self.vertices[idx]

    def get_connected_vertex_indices(self, idx: int) -> List[int]:
        return [t for t, _ in self._adj[idx]]

    def get_vertex_degree(self, idx: int) -> int:
        return len(self._adj[idx])

    def degrees(self) -> np.ndarray:
        self._build_tables()
        return self._degrees

    # -- vectorized adjacency ------------------------------------------
    def _build_tables(self) -> None:
        if not self._table_dirty:
            return
        deg = np.array([len(a) for a in self._adj], np.int64)
        max_deg = max(1, int(deg.max(initial=0)))
        nbr = np.zeros((self._n, max_deg), np.int64)
        wgt = np.zeros((self._n, max_deg), np.float64)
        for i, a in enumerate(self._adj):
            for j, (t, w) in enumerate(a):
                nbr[i, j] = t
                wgt[i, j] = w
        self._nbr_table, self._wgt_table, self._degrees = nbr, wgt, deg
        self._table_dirty = False

    def neighbor_table(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(neighbors [N, max_deg], weights [N, max_deg], degrees [N]) —
        the padded arrays all vectorized walkers index into."""
        self._build_tables()
        return self._nbr_table, self._wgt_table, self._degrees
