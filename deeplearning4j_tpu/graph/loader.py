"""Graph loaders: delimited edge lists, optionally weighted.

Capability mirror of reference graph data/{GraphLoader,
impl/DelimitedEdgeLineProcessor, impl/WeightedEdgeLineProcessor,
impl/DelimitedVertexLoader}.java.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.graph.api import Graph


class ParseException(Exception):
    pass


def load_undirected_graph(
    path: str, n_vertices: int, delimiter: str = ",",
) -> Graph:
    """Edge list "from<delim>to" per line (reference
    GraphLoader.loadUndirectedGraphEdgeListFile)."""
    g = Graph(n_vertices)
    with open(path) as f:
        for ln, line in enumerate(f):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(delimiter)
            if len(parts) != 2:
                raise ParseException(f"line {ln}: expected 2 fields: {line!r}")
            g.add_edge(int(parts[0]), int(parts[1]))
    return g


def load_weighted_edge_list(
    path: str,
    n_vertices: int,
    delimiter: str = ",",
    directed: bool = False,
) -> Graph:
    """Edge list "from<delim>to<delim>weight" (reference
    WeightedEdgeLineProcessor)."""
    g = Graph(n_vertices)
    with open(path) as f:
        for ln, line in enumerate(f):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(delimiter)
            if len(parts) != 3:
                raise ParseException(f"line {ln}: expected 3 fields: {line!r}")
            g.add_edge(
                int(parts[0]), int(parts[1]), float(parts[2]), directed
            )
    return g


def load_vertex_values(path: str, delimiter: Optional[str] = None):
    """"idx<delim>value" per line -> list of values ordered by idx
    (reference DelimitedVertexLoader)."""
    pairs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            idx, val = line.split(delimiter or ",", 1)
            pairs.append((int(idx), val))
    pairs.sort()
    return [v for _, v in pairs]
