"""Random-walk generation.

Capability mirror of reference graph iterator/{RandomWalkIterator,
WeightedRandomWalkIterator,GraphWalkIterator}.java + the parallel
providers. TPU-first inversion: instead of one Java iterator stepping a
single walker vertex-by-vertex, ALL walks advance in lockstep — each step
is one vectorized gather into the padded neighbor table + one batched
categorical draw, so generating the corpus for DeepWalk is O(walk_length)
numpy ops regardless of vertex count.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.api import Graph, NoEdgeHandling, NoEdgesException


def generate_walks(
    graph: Graph,
    walk_length: int,
    walks_per_vertex: int = 1,
    weighted: bool = False,
    no_edge_handling: NoEdgeHandling = (
        NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED
    ),
    seed: int = 12345,
) -> np.ndarray:
    """All walks as one [n_walks, walk_length+1] int array. Starts cover
    every vertex ``walks_per_vertex`` times in shuffled order."""
    nbr, wgt, deg = graph.neighbor_table()
    n = graph.num_vertices()
    rng = np.random.default_rng(seed)

    if (deg == 0).any():
        if no_edge_handling == NoEdgeHandling.EXCEPTION_ON_DISCONNECTED:
            bad = int(np.argmax(deg == 0))
            raise NoEdgesException(
                f"vertex {bad} has no edges "
                "(NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)"
            )

    starts = np.concatenate(
        [rng.permutation(n) for _ in range(walks_per_vertex)]
    )
    cur = starts.copy()
    out = np.empty((len(starts), walk_length + 1), np.int64)
    out[:, 0] = cur
    max_deg = nbr.shape[1]
    for t in range(1, walk_length + 1):
        d = deg[cur]  # [W]
        if weighted:
            w = wgt[cur].astype(np.float64)  # [W, max_deg]
            valid = np.arange(max_deg)[None, :] < d[:, None]
            w = np.where(valid, w, 0.0)
            tot = w.sum(1, keepdims=True)
            probs = np.where(tot > 0, w / np.maximum(tot, 1e-300), 0.0)
            # Batched categorical via inverse-CDF on uniform draws.
            u = rng.random(len(cur))[:, None]
            choice = (probs.cumsum(1) < u).sum(1)
            choice = np.minimum(choice, np.maximum(d - 1, 0))
        else:
            choice = rng.integers(0, np.maximum(d, 1))
        nxt = nbr[cur, choice]
        nxt = np.where(d > 0, nxt, cur)  # self-loop on disconnected
        out[:, t] = nxt
        cur = nxt
    return out


class RandomWalkIterator:
    """Iterator facade over :func:`generate_walks` (reference
    RandomWalkIterator API: next()/hasNext()/reset())."""

    def __init__(
        self,
        graph: Graph,
        walk_length: int,
        seed: int = 12345,
        no_edge_handling: NoEdgeHandling = (
            NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED
        ),
        weighted: bool = False,
    ):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self.weighted = weighted
        self._walks: Optional[np.ndarray] = None
        self._pos = 0

    def _ensure(self):
        if self._walks is None:
            self._walks = generate_walks(
                self.graph, self.walk_length, 1, self.weighted,
                self.no_edge_handling, self.seed,
            )

    def has_next(self) -> bool:
        self._ensure()
        return self._pos < len(self._walks)

    def next(self) -> np.ndarray:
        self._ensure()
        if self._pos >= len(self._walks):
            raise StopIteration
        w = self._walks[self._pos]
        self._pos += 1
        return w

    def reset(self) -> None:
        self._walks = None
        self._pos = 0
        self.seed += 1  # fresh walks per epoch, like re-seeded reference

    def __iter__(self) -> Iterator[np.ndarray]:
        self._ensure()
        while self.has_next():
            yield self.next()


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Transition probability proportional to edge weight (reference
    WeightedRandomWalkIterator)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 12345,
                 no_edge_handling: NoEdgeHandling = (
                     NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED
                 )):
        super().__init__(
            graph, walk_length, seed, no_edge_handling, weighted=True
        )
