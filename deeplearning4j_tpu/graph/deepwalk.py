"""DeepWalk: skip-gram embeddings over random walks.

Capability mirror of reference graph models/deepwalk/DeepWalk.java:37 +
GraphHuffman.java (Huffman codes over vertex DEGREES) +
InMemoryGraphLookupTable. Rides the framework's SequenceVectors engine
(nlp/sequence_vectors.py): walks become token sequences of vertex ids, so
the jitted batched hierarchical-softmax update — the TPU replacement for
the reference's per-pair iterateSample loop — is shared with Word2Vec.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graph.api import Graph, NoEdgeHandling
from deeplearning4j_tpu.graph.walker import generate_walks
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import VocabCache, assign_huffman_codes


class DeepWalk:
    """Builder-style API mirroring reference DeepWalk.Builder:
    vectorSize/windowSize/learningRate/seed, then
    ``initialize(graph)`` + ``fit(graph, walk_length)``."""

    def __init__(
        self,
        vector_size: int = 100,
        window_size: int = 5,
        learning_rate: float = 0.025,
        walks_per_vertex: int = 10,
        epochs: int = 1,
        weighted_walks: bool = False,
        no_edge_handling: NoEdgeHandling = (
            NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED
        ),
        seed: int = 12345,
        batch_size: int = 2048,
    ):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.walks_per_vertex = walks_per_vertex
        self.epochs = epochs
        self.weighted_walks = weighted_walks
        self.no_edge_handling = no_edge_handling
        self.seed = seed
        self.batch_size = batch_size
        self._sv: Optional[SequenceVectors] = None
        self._graph: Optional[Graph] = None

    # ------------------------------------------------------------------
    def initialize(self, graph: Graph) -> None:
        """Build the degree-weighted Huffman vocab (reference
        GraphHuffman: code lengths follow vertex degree, so hub vertices
        get short paths) and init weights."""
        self._graph = graph
        sv = SequenceVectors(
            layer_size=self.vector_size,
            window=self.window_size,
            learning_rate=self.learning_rate,
            min_word_frequency=0,
            subsampling=0.0,  # every vertex matters; no frequency cut
            epochs=1,  # epoch loop is ours (fresh walks each epoch)
            batch_size=self.batch_size,
            seed=self.seed,
        )
        vocab = VocabCache()
        deg = graph.degrees()
        for i in range(graph.num_vertices()):
            vocab.add_token(str(i), count=max(1, int(deg[i])))
        vocab.finalize_indices()
        assign_huffman_codes(vocab)
        sv.vocab = vocab
        sv._reset_weights()
        self._sv = sv

    def fit(self, graph: Optional[Graph] = None, walk_length: int = 40):
        if graph is not None and self._graph is not graph:
            self.initialize(graph)
        if self._sv is None:
            raise RuntimeError("call initialize(graph) first")
        g = self._graph
        for epoch in range(self.epochs):
            walks = generate_walks(
                g, walk_length, self.walks_per_vertex,
                self.weighted_walks, self.no_edge_handling,
                self.seed + epoch,
            )
            seqs = [[str(int(v)) for v in walk] for walk in walks]
            self._sv.fit(seqs)
        return self

    # ------------------------------------------------------------------
    # GraphVectors API (reference models/GraphVectors.java)
    # ------------------------------------------------------------------
    def get_vertex_vector(self, idx: int) -> np.ndarray:
        return self._sv.get_word_vector(str(idx))

    def similarity(self, a: int, b: int) -> float:
        return self._sv.similarity(str(a), str(b))

    def verts_nearest(self, idx: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in self._sv.words_nearest(str(idx), top_n)]

    def num_vertices(self) -> int:
        return self._graph.num_vertices() if self._graph else 0

    # -- serde (reference models/loader/GraphVectorSerializer) ----------
    def save_vectors(self, path: str) -> None:
        from deeplearning4j_tpu.nlp.serializer import write_word_vectors

        write_word_vectors(self._sv, path)
