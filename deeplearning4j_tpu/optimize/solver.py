"""Solver: line-search-based convex optimizers over flat parameters.

Mirror of reference optimize/Solver.java:42 + solvers/{BaseOptimizer.java:55
(main loop :163-226), LineGradientDescent, ConjugateGradient (91 LoC,
Polak-Ribiere), LBFGS (163 LoC, m=4 two-loop recursion),
BackTrackLineSearch.java (Armijo backtracking)}.

The SGD path is NOT here — it is fused into MultiLayerNetwork's jitted
train step. These optimizers evaluate a jitted flat ``value_and_grad`` from
a host-side loop; they exist for capability parity (CG/LBFGS training,
t-SNE, RBM fine-tuning experiments), not as the TPU hot loop.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.enums import OptimizationAlgorithm
from deeplearning4j_tpu.optimize.telemetry import (
    batch_counts,
    host_grad_health,
)
from deeplearning4j_tpu.optimize.terminations import DEFAULT_CONDITIONS

Array = jax.Array


def _first_conf(net):
    """The conf holding the solver knobs: confs[0] for MultiLayerNetwork;
    any layer-vertex conf for ComputationGraph (the knobs are global)."""
    confs = getattr(net.conf, "confs", None)
    if confs:
        return confs[0]
    return next(iter(net._layer_vertices.values())).conf


def backtrack_line_search(
    f: Callable[[Array], float],
    x: Array,
    fx: float,
    grad: Array,
    direction: Array,
    max_iterations: int = 5,
    initial_step: float = 1.0,
    c1: float = 1e-4,
    rho: float = 0.5,
    minimize: bool = True,
    move=None,
) -> Tuple[float, float]:
    """Armijo/Wolfe backtracking (reference BackTrackLineSearch.java).
    Returns (step, f(x + step*direction)).

    ``minimize=False`` is the reference's sufficient-INCREASE branch
    (BackTrackLineSearch.java:257-263) for score-ascent objectives — the
    reference selects it by step-function type (:163,
    minObjectiveFunction = stepFunction instanceof Negative*); here the
    caller states the objective sense directly because this solver's
    directions are always descent-oriented for minimize=True, and the
    maximize formulas (:304-330) are the minimize ones applied to -f,
    which is how they are evaluated here. Also mirrored: quadratic-then-
    cubic interpolation backtracking (:278-303, Numerical Recipes
    lnsrch) with the lambda in [0.1, 0.5]·lambda_prev clamp, best-step
    tracking for the max-iterations exit (:239-245), and scaling back
    non-finite jumps (:266-273). ``rho`` remains the fallback shrink
    when interpolation degenerates.

    ``move(x, direction, step)`` evaluates candidates with the SAME step
    function the optimizer will apply afterward (the reference's
    lineMaximizer runs the configured stepFunction on each probe), so
    the returned score describes the point actually stepped to — for
    the Negative* step functions the probes go along -direction and the
    caller passes minimize=False.
    """
    if move is None:
        move = lambda xx, d, s: xx + s * d  # noqa: E731
    sign = 1.0 if minimize else -1.0

    def phi(s: float) -> float:
        return sign * float(f(move(x, direction, s)))

    # Effective probe direction (linear step functions): slope of phi.
    delta = move(x, direction, 1.0) - x
    slope = sign * float(jnp.vdot(grad, delta))
    phi0 = sign * float(fx)
    step = float(initial_step)
    step_prev = phi_prev = None
    best_step, best_phi = 0.0, phi0
    for _ in range(max_iterations):
        phin = phi(step)
        if not np.isfinite(phin):
            # Jumped into unstable territory: scale back hard (:266-273)
            # and restart the interpolation history.
            step_prev = phi_prev = None
            step *= 0.2
            continue
        if phin < best_phi:
            best_step, best_phi = step, phin
        if phin <= phi0 + c1 * step * slope:  # sufficient decrease of phi
            return step, sign * phin
        # Interpolation backtrack: quadratic on the first shrink, cubic
        # through the last two points after.
        if step_prev is None:
            # First shrink: step-scaled quadratic model through phi(0),
            # phi'(0), phi(step) — exact for any step, not just step==1
            # (matters after a non-finite 0.2x restart). Clamped like
            # the cubic branch as a safety bound.
            denom = 2.0 * (phin - phi0 - slope * step)
            tmp = (-slope * step * step / denom
                   if denom != 0.0 else rho * step)
            tmp = min(tmp, 0.5 * step)
        else:
            rhs1 = phin - phi0 - step * slope
            rhs2 = phi_prev - phi0 - step_prev * slope
            a = (rhs1 / step**2 - rhs2 / step_prev**2) / (step - step_prev)
            b = (-step_prev * rhs1 / step**2
                 + step * rhs2 / step_prev**2) / (step - step_prev)
            if a == 0.0:
                tmp = -slope / (2.0 * b) if b != 0.0 else rho * step
            else:
                disc = b * b - 3.0 * a * slope
                if disc < 0.0:
                    tmp = 0.5 * step
                elif b <= 0.0:
                    tmp = (-b + np.sqrt(disc)) / (3.0 * a)
                else:
                    tmp = -slope / (b + np.sqrt(disc))
            tmp = min(tmp, 0.5 * step)  # lambda <= 0.5 lambda_1
        step_prev, phi_prev = step, phin
        if not np.isfinite(tmp):
            tmp = rho * step
        step = max(tmp, 0.1 * step)     # lambda >= 0.1 lambda_1
    if best_step > 0.0:
        # Max iterations: the best step observed (reference bestStepSize
        # exit, :239-245).
        return best_step, sign * best_phi
    # Nothing improved: deliberate deviation from the reference's 0.0
    # (keep params) — a zero step makes EpsTermination read the stalled
    # score as converged on the spot, whereas taking the smallest probed
    # step perturbs the iterate enough for CG/LBFGS to rebuild a descent
    # direction and keep optimizing (observed on the convergence tests).
    if step_prev is not None:
        return step_prev, sign * phi_prev
    return 0.0, fx


class FlatProblem:
    """Adapter exposing a network's loss on one batch as f(flat_params).

    The batch enters the jitted functions as ARGUMENTS (not trace-time
    constants), and the compiled fns are cached on the network, so
    iterating over many batches compiles once per batch shape rather than
    once per batch.
    """

    def __init__(self, net, ds):
        from jax.flatten_util import ravel_pytree

        net.init()
        self._net = net
        if hasattr(net, "_coerce_multi"):
            # ComputationGraph: inputs is a {name: array} pytree and
            # labels a per-output list — both jit-able arguments, and
            # graph._loss_fn has the same arity as the MLN one.
            (self._feats, self._labels, self._masks,
             self._lmasks) = net._coerce_multi(ds)
        else:
            self._feats = jnp.asarray(ds.features, net._dtype)
            self._labels = jnp.asarray(ds.labels, net._dtype)
            self._masks = (None if ds.features_mask is None
                           else jnp.asarray(ds.features_mask))
            self._lmasks = (None if ds.labels_mask is None
                            else jnp.asarray(ds.labels_mask))
        x0, unravel = ravel_pytree(net.params)
        self.x0 = x0
        self._unravel = unravel

        if not hasattr(net, "_flat_loss_cache"):
            def loss_flat(flat, state, feats, labels, masks, lmasks):
                params = unravel(flat)
                score, _ = net._loss_fn(
                    params, state, None, feats, labels, masks, lmasks
                )
                return score

            def hvp(flat, v, state, feats, labels, masks, lmasks):
                # Hessian-vector product by forward-over-reverse autodiff
                # — the jax-native form of the reference's R-op
                # (MultiLayerNetwork.computeDeltasR :728 used by
                # StochasticHessianFree.java)
                g = lambda f: jax.grad(loss_flat)(
                    f, state, feats, labels, masks, lmasks)
                return jax.jvp(g, (flat,), (v,))[1]

            net._flat_loss_cache = (
                jax.jit(jax.value_and_grad(loss_flat)),
                jax.jit(loss_flat),
                jax.jit(hvp),
            )
        self._vag, self._val, self._hvp = net._flat_loss_cache

    def value_and_grad(self, flat):
        return self._vag(flat, self._net.state, self._feats, self._labels,
                         self._masks, self._lmasks)

    def value(self, flat):
        return self._val(flat, self._net.state, self._feats, self._labels,
                         self._masks, self._lmasks)

    def hessian_vector_product(self, flat, v):
        return self._hvp(flat, v, self._net.state, self._feats,
                         self._labels, self._masks, self._lmasks)

    def write_back(self, flat: Array) -> None:
        self._net.params = self._unravel(flat)


class BaseOptimizer:
    """Shared loop (reference BaseOptimizer.optimize :163-226):
    gradientAndScore -> direction -> line search -> step -> listeners ->
    termination."""

    def __init__(self, net, max_iterations: Optional[int] = None,
                 terminations=DEFAULT_CONDITIONS, step_function=None,
                 problem_factory=None):
        from deeplearning4j_tpu.optimize import stepfunctions

        self.net = net
        conf = _first_conf(net)
        self.max_iterations = max_iterations or conf.num_iterations
        self.max_ls_iterations = conf.max_num_line_search_iterations
        self.terminations = list(terminations)
        self.step_function = (
            stepfunctions.from_name(step_function) if step_function
            else stepfunctions.DefaultStepFunction()
        )
        # Alternate problem representation (same value/grad/write_back
        # surface as FlatProblem): PipelineTrainer injects a stage-
        # sharded [S, K] problem here so CG/LBFGS run with 1/S of the
        # model per device — the solver math (vdot/axpy) is pure jnp,
        # so it runs sharded under GSPMD without further changes.
        self.problem_factory = problem_factory

    def direction(self, x, grad, it: int) -> Array:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def optimize(self, ds) -> float:
        problem = (self.problem_factory(self.net, ds)
                   if self.problem_factory is not None
                   else FlatProblem(self.net, ds))
        self._problem = problem  # direction() hooks may need hvp access
        x = problem.x0
        score = None
        self.reset()
        # Per-iteration telemetry: the solver loop is host-composed (it
        # syncs the score every iteration anyway), so phases merge into
        # one dispatch+eval wall and gradient health is lazy host-side
        # numpy on the flat vectors — zero extra executables.
        telemetry = getattr(self.net, "train_telemetry", None)
        feats = getattr(ds, "features", None)
        if isinstance(feats, (list, tuple)):
            feats = feats[0] if feats else None
        examples, tokens = batch_counts(feats)
        for it in range(self.max_iterations):
            t_step = time.perf_counter()
            x_prev = x
            score, grad = problem.value_and_grad(x)
            score = float(score)
            direction = self.direction(x, grad, it)
            # Probe with the configured step function so the reported
            # score always describes the point actually stepped to. The
            # reference's Negative* step functions SUBTRACT a
            # gradient-oriented direction to minimize
            # (minObjectiveFunction = instanceof Negative*,
            # BackTrackLineSearch.java:163); this port's solvers emit
            # descent-oriented directions, so a configured Negative*
            # step function gets the direction negated back to gradient
            # orientation — every reference step-function config
            # minimizes here exactly as it does there.
            from deeplearning4j_tpu.optimize import stepfunctions as SF

            if isinstance(self.step_function,
                          (SF.NegativeDefaultStepFunction,
                           SF.NegativeGradientStepFunction)):
                direction = -direction
            # Constant step functions (x +/- direction, step ignored):
            # phi(s) is flat in s, so probing more than once re-runs the
            # identical loss evaluation.
            ls_iters = self.max_ls_iterations
            if isinstance(self.step_function,
                          (SF.GradientStepFunction,
                           SF.NegativeGradientStepFunction)):
                ls_iters = 1
            step, new_score = backtrack_line_search(
                problem.value, x, score, grad, direction,
                ls_iters,
                move=self.step_function.step,
            )
            x = self.step_function.step(x, direction, step)
            self._ls_scores = (score, new_score)  # for adaptive hooks
            self._post_step(x, grad, direction, step)
            problem.write_back(x)
            if telemetry is not None:
                telemetry.record_step(
                    dispatch_s=time.perf_counter() - t_step,
                    examples=examples, tokens=tokens,
                    health=functools.partial(
                        host_grad_health, grad, x_prev, x))
            self.net.score_value = new_score
            self.net.iteration += 1
            for listener in self.net.listeners:
                listener.iteration_done(self.net, self.net.iteration)
            if any(
                t.terminate(new_score, score, np.asarray(direction))
                for t in self.terminations
            ):
                break
        return float(self.net.score_value)

    def _post_step(self, x, grad, direction, step) -> None:
        pass


class LineGradientDescent(BaseOptimizer):
    """Steepest descent + line search (reference
    solvers/LineGradientDescent.java)."""

    def direction(self, x, grad, it):
        return -grad


class ConjugateGradient(BaseOptimizer):
    """Nonlinear CG with Polak-Ribiere beta (reference
    solvers/ConjugateGradient.java)."""

    def reset(self):
        self._prev_grad = None
        self._prev_dir = None

    def direction(self, x, grad, it):
        if self._prev_grad is None:
            d = -grad
        else:
            y = grad - self._prev_grad
            beta = float(
                jnp.maximum(
                    0.0,
                    jnp.vdot(grad, y)
                    / jnp.maximum(jnp.vdot(self._prev_grad, self._prev_grad), 1e-12),
                )
            )
            d = -grad + beta * self._prev_dir
            if float(jnp.vdot(grad, d)) >= 0.0:
                # Non-descent direction: restart with steepest descent —
                # the reference reaches the same state through its
                # zero-step path (gamma = max(0, 0) -> -g next round,
                # ConjugateGradient.java:69-72).
                d = -grad
        self._prev_grad = grad
        self._prev_dir = d
        return d


class LBFGS(BaseOptimizer):
    """Limited-memory BFGS, m=4 history, two-loop recursion (reference
    solvers/LBFGS.java)."""

    m = 4

    def reset(self):
        self._s: List[Array] = []
        self._y: List[Array] = []
        self._prev_x = None
        self._prev_grad = None

    def direction(self, x, grad, it):
        if self._prev_x is not None:
            s = x - self._prev_x
            y = grad - self._prev_grad
            if float(jnp.vdot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self.m:
                    self._s.pop(0)
                    self._y.pop(0)
        self._prev_x = x
        self._prev_grad = grad
        q = grad
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / float(jnp.vdot(y, s))
            a = rho * float(jnp.vdot(s, q))
            alphas.append((a, rho, s, y))
            q = q - a * y
        if self._s:
            s, y = self._s[-1], self._y[-1]
            gamma = float(jnp.vdot(s, y) / jnp.maximum(jnp.vdot(y, y), 1e-12))
            q = gamma * q
        for a, rho, s, y in reversed(alphas):
            b = rho * float(jnp.vdot(y, q))
            q = q + (a - b) * s
        return -q


class StochasticHessianFree(BaseOptimizer):
    """Hessian-free (truncated-Newton) optimization: the search direction
    solves (H + λI) d = -grad by conjugate gradient using only
    Hessian-vector products (reference solvers/StochasticHessianFree.java,
    261 LoC, R-op via MultiLayerNetwork.computeDeltasR :728 — here the
    R-op is jax.jvp over the gradient, one extra forward-mode pass).
    λ adapts Levenberg-Marquardt-style on the reduction ratio."""

    def __init__(self, net, max_iterations: Optional[int] = None,
                 terminations=DEFAULT_CONDITIONS, cg_iterations: int = 50,
                 initial_lambda: float = 0.01, problem_factory=None):
        super().__init__(net, max_iterations, terminations,
                         problem_factory=problem_factory)
        self.cg_iterations = cg_iterations
        self.lam = initial_lambda
        self._last_quad = 0.0

    def direction(self, x, grad, it):
        lam = self.lam
        hvp = self._problem.hessian_vector_product

        def av(v):
            return hvp(x, v) + lam * v

        # CG on A d = -grad starting from 0
        d = jnp.zeros_like(x)
        r = -grad  # residual = b - A d with d = 0
        p = r
        rs = jnp.vdot(r, r)
        for _ in range(self.cg_iterations):
            ap = av(p)
            denom = float(jnp.vdot(p, ap))
            if denom <= 0:
                # nonpositive curvature: truncated-Newton CG stops here;
                # further iterations would burn full-batch HVPs for
                # nothing. Fall back to steepest descent if no progress.
                if float(jnp.vdot(d, d)) == 0.0:
                    d = -grad
                break
            alpha = rs / denom
            d = d + alpha * p
            r = r - alpha * ap
            rs_new = jnp.vdot(r, r)
            if float(rs_new) < 1e-10:
                break
            p = r + (rs_new / rs) * p
            rs = rs_new
        # quadratic-model reduction for the λ update in _post_step
        self._last_quad = float(
            jnp.vdot(grad, d) + 0.5 * jnp.vdot(d, av(d)))
        return d

    def _post_step(self, x, grad, direction, step) -> None:
        # Levenberg-Marquardt: compare ACTUAL score reduction (from the
        # line-search evaluation) to the CG quadratic model's prediction
        # (Martens 2010; the reference's damping role). rho near 1 ⇒
        # model trusted, relax damping; small/negative rho ⇒ re-damp.
        before, after = self._ls_scores
        predicted = self._last_quad  # <= 0 when CG made progress
        if predicted >= -1e-12:
            self.lam = min(1e6, self.lam * 1.5)
            return
        rho = (after - before) / predicted
        if rho > 0.75:
            self.lam = max(1e-6, self.lam * (2 / 3))
        elif rho < 0.25:
            self.lam = min(1e6, self.lam * 1.5)


_OPTIMIZERS = {
    OptimizationAlgorithm.LINE_GRADIENT_DESCENT: LineGradientDescent,
    OptimizationAlgorithm.CONJUGATE_GRADIENT: ConjugateGradient,
    OptimizationAlgorithm.LBFGS: LBFGS,
    OptimizationAlgorithm.HESSIAN_FREE: StochasticHessianFree,
}


class Solver:
    """Facade: build the right optimizer from the conf and run it
    (reference optimize/Solver.java:42)."""

    def __init__(self, net):
        self.net = net

    def optimize(self, ds) -> float:
        algo = _first_conf(self.net).optimization_algo
        if algo == OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            fit = getattr(self.net, "_fit_batch", None) or self.net._fit_one
            fit(ds)
            return float(self.net.score_value)
        try:
            cls = _OPTIMIZERS[algo]
        except KeyError:
            raise ValueError(f"Unsupported optimization algorithm {algo}")
        return cls(self.net).optimize(ds)
