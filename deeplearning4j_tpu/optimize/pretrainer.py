"""Greedy layer-wise unsupervised pretraining.

Mirror of reference MultiLayerNetwork.pretrain(DataSetIterator) :150-226
(§3.3 call stack): for each pretrainable layer, feed data forward through
the already-trained stack, then run that layer's unsupervised update
(RBM CD-k / denoising-AE gradient) for conf.numIterations iterations per
batch. Each layer's update is one jitted computation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import PRETRAIN_LAYER_TYPES
from deeplearning4j_tpu.nn.updater.updaters import resolve_lr


def pretrain_network(net, data_iter) -> None:
    # jitted steps are cached on the network so repeated pretrain() calls
    # reuse the compiled executable instead of retracing. The cache key
    # includes the conf's serialized form, so editing hyperparameters
    # (k, corruption_level, ...) between calls correctly retraces.
    from deeplearning4j_tpu.nn.conf.serde import to_json as _conf_json

    cache = getattr(net, "_pretrain_step_cache", None)
    if cache is None:
        cache = net._pretrain_step_cache = {}
    for i, (conf, impl) in enumerate(zip(net.conf.confs, net._impls)):
        if not isinstance(conf.layer, PRETRAIN_LAYER_TYPES):
            continue
        key = (i, _conf_json(conf, indent=None))
        step = cache.get(key)
        if step is None:
            step = cache[key] = _make_pretrain_step(net, i, conf, impl)
        data_iter.reset()
        n_iter = max(1, conf.num_iterations)
        for ds in data_iter:
            x = jnp.asarray(ds.features, net._dtype)
            x_in = _activate_to(net, i, x)
            for _ in range(n_iter):
                net._key, sub = jax.random.split(net._key)
                si = str(i)
                # lr resolved host-side per call so conf edits between
                # pretrain() passes take effect despite the cached jit.
                lr = resolve_lr(conf, net.iteration)
                (
                    net.params[si],
                    net.updater_state[si],
                    score,
                ) = step(net.params[si], net.updater_state[si],
                         net.iteration, lr, sub, x_in)
                net.score_value = score
                net.iteration += 1
                for listener in net.listeners:
                    listener.iteration_done(net, net.iteration)


def _activate_to(net, layer_idx: int, x):
    """Input activations for layer ``layer_idx`` (reference
    activationFromPrevLayer :199-226), inference mode."""
    if layer_idx == 0:
        pp = net.conf.preprocessor_for(0)
        return pp.pre_process(x) if pp is not None else x
    acts, _, _ = net._forward_fn(
        net.params, net.state, x, None, False, collect=True
    )
    out = acts[layer_idx - 1]
    pp = net.conf.preprocessor_for(layer_idx)
    return pp.pre_process(out) if pp is not None else out


def _make_pretrain_step(net, i: int, conf, impl):
    upd = net._updaters[i]

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(layer_params, upd_state, iteration, lr, rng, x):
        score, grads = impl.pretrain_value_and_grad(conf, layer_params, x, rng)
        updates, new_upd = upd.update(grads, upd_state, lr, iteration)
        new_params = jax.tree.map(lambda p, u: p - u, layer_params, updates)
        return new_params, new_upd, score

    return step
