"""Greedy layer-wise unsupervised pretraining.

Mirror of reference MultiLayerNetwork.pretrain(DataSetIterator) :150-226
(§3.3 call stack) and ComputationGraph.pretrain :341-427: for each
pretrainable unit (layer index / layer vertex), feed data forward through
the already-trained stack to that unit's input, then run the unit's
unsupervised update (RBM CD-k / denoising-AE gradient) for
conf.numIterations iterations per batch. Each unit's update is one jitted
computation, shared between the MLN and graph paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import PRETRAIN_LAYER_TYPES
from deeplearning4j_tpu.nn.updater.updaters import resolve_lr


def pretrain_network(net, data_iter) -> None:
    """Greedy pretrain of a MultiLayerNetwork's RBM/AE layers."""
    for i, (conf, impl) in enumerate(zip(net.conf.confs, net._impls)):
        if not isinstance(conf.layer, PRETRAIN_LAYER_TYPES):
            continue

        def get_input(ds, _i=i):
            x = jnp.asarray(ds.features, net._dtype)
            return _activate_to(net, _i, x)

        _pretrain_unit(net, str(i), conf, impl, net._updaters[i],
                       get_input, data_iter)


def pretrain_graph(net, data_iter) -> None:
    """Greedy pretrain of a ComputationGraph's pretrainable layer
    vertices, in topological order (reference ComputationGraph.pretrain
    :341-427)."""
    from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex

    for name in net.order:
        vertex = net.conf.vertices[name]
        if not (isinstance(vertex, LayerVertex)
                and isinstance(vertex.conf.layer, PRETRAIN_LAYER_TYPES)):
            continue

        def get_input(ds, _n=name):
            return net._pretrain_input(_n, ds)

        _pretrain_unit(net, name, vertex.conf, net._impls[name],
                       net._updaters[name], get_input, data_iter)


def _pretrain_unit(net, key_name, conf, impl, upd, get_input,
                   data_iter) -> None:
    """Pretrain one unit whose params live at net.params[key_name].

    Jitted steps are cached on the network so repeated pretrain() calls
    reuse the compiled executable instead of retracing. The cache key
    includes the conf's serialized form, so editing hyperparameters
    (k, corruption_level, ...) between calls correctly retraces.
    """
    from deeplearning4j_tpu.nn.conf.serde import to_json as _conf_json

    cache = getattr(net, "_pretrain_step_cache", None)
    if cache is None:
        cache = net._pretrain_step_cache = {}
    key = (key_name, _conf_json(conf, indent=None))
    step = cache.get(key)
    if step is None:
        step = cache[key] = _make_pretrain_step(conf, impl, upd)
    data_iter.reset()
    n_iter = max(1, conf.num_iterations)
    for ds in data_iter:
        x_in = get_input(ds)
        for _ in range(n_iter):
            net._key, sub = jax.random.split(net._key)
            # lr resolved host-side per call so conf edits between
            # pretrain() passes take effect despite the cached jit.
            lr = resolve_lr(conf, net.iteration)
            (
                net.params[key_name],
                net.updater_state[key_name],
                score,
            ) = step(net.params[key_name], net.updater_state[key_name],
                     net.iteration, lr, sub, x_in)
            net.score_value = score
            net.iteration += 1
            for listener in net.listeners:
                listener.iteration_done(net, net.iteration)


def _activate_to(net, layer_idx: int, x):
    """Input activations for layer ``layer_idx`` (reference
    activationFromPrevLayer :199-226), inference mode."""
    if layer_idx == 0:
        pp = net.conf.preprocessor_for(0)
        return pp.pre_process(x) if pp is not None else x
    acts, _, _ = net._forward_fn(
        net.params, net.state, x, None, False, collect=True
    )
    out = acts[layer_idx - 1]
    pp = net.conf.preprocessor_for(layer_idx)
    return pp.pre_process(out) if pp is not None else out


def _make_pretrain_step(conf, impl, upd):
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(layer_params, upd_state, iteration, lr, rng, x):
        score, grads = impl.pretrain_value_and_grad(conf, layer_params, x, rng)
        updates, new_upd = upd.update(grads, upd_state, lr, iteration)
        new_params = jax.tree.map(lambda p, u: p - u, layer_params, updates)
        return new_params, new_upd, score

    return step
