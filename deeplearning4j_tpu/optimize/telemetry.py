"""Training telemetry: per-step phase clock, gradient-health outputs,
and the headless JSONL metrics sink (ISSUE 8).

The serving stack's flight recorder (serving/engine.py, ISSUE 7) made
every request's latency breakdown legible; this module is the TRAINING
half of the same discipline. Three pieces, deliberately tiny:

- :func:`grad_health` — global grad norm, update/param ratio, param
  norm, and nonfinite-grad count computed as EXTRA OUTPUTS inside the
  networks' existing jitted train steps. Because the health scalars are
  always traced into the step (attached listener or not), the
  telemetry-on and telemetry-off executables are the SAME executable:
  zero new compiles, zero retraces, bit-identical params by
  construction. The scalars ride back as lazy device arrays and are
  only fetched at the step's one existing host sync (the listener's
  score fetch).
- :class:`TrainTelemetry` — a host-side phase accumulator every network
  owns (``net.train_telemetry``): data-wait (iterator fetch), dispatch
  wall, step/example/token counts, and the latest health pytree. The
  fit loops stamp it with ~two ``perf_counter`` calls per step; nobody
  reads it unless a :class:`TracingIterationListener
  <deeplearning4j_tpu.optimize.listeners.TracingIterationListener>`
  (or other consumer) drains a window. Phases are disjoint
  sub-intervals of the window wall, so phase sums <= wall holds
  STRUCTURALLY, mirroring the serving _PhaseClock contract.
- :class:`MetricsLog` — a line-per-record JSONL sink for headless runs
  (no UiServer, no tracer): one ``json.dumps`` per listener fire,
  trivially greppable/pandas-loadable.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: The five training histogram tracks (ISSUE 8 tentpole): latency-style
#: phases in seconds plus gradient-health value distributions.
TRAIN_HISTOGRAMS = (
    "train_step_s",
    "train_data_wait_s",
    "train_grad_norm",
    "train_update_ratio",
    "train_param_norm",
)

#: Host-sync wall also keeps a histogram so the latency report's live
#: mode can answer sync quantiles; it rides beside the five core tracks.
TRAIN_SYNC_HISTOGRAM = "train_sync_s"

#: ``# HELP`` text per training track (the serving SERVING_TRACK_HELP
#: counterpart), applied via ``Tracer.describe``.
TRAIN_TRACK_HELP: Dict[str, str] = {
    "train_step_s": "per-step wall time (window wall / steps)",
    "train_data_wait_s": "per-step host wait on the data iterator",
    "train_sync_s": "host-sync wall at the listener's score fetch",
    "train_grad_norm": "global L2 norm of the step gradient",
    "train_update_ratio":
        "L2 norm of the applied parameter delta / new param norm",
    "train_param_norm": "global L2 norm of the post-step parameters",
    "train_examples_per_sec": "training throughput over the last window",
    "train_tokens_per_sec":
        "token throughput over the last window (time-series batches)",
    "train_score": "latest training score (loss)",
    "train_steps_total": "cumulative training steps observed",
    "train_nonfinite_grads":
        "cumulative count of non-finite gradient elements seen",
    "train_early_stop": "early-stopping terminations fired",
}

#: Gradient-health leaf names, in the order every producer emits them.
HEALTH_KEYS = ("grad_norm", "update_ratio", "param_norm",
               "nonfinite_grads")

#: Norm-valued histograms span 1e-8 .. 1e4 (4 log buckets/decade): grad
#: and param norms roam far outside the latency default of 100us..100s.
VALUE_BOUNDS = tuple(10.0 ** (e / 4.0) for e in range(-32, 17))


def grad_health(grads, params, new_params):
    """Gradient-health scalars, traced INSIDE the jitted train step.

    Returns ``{grad_norm, update_ratio, param_norm, nonfinite_grads}``
    as f32 device scalars. ``update_ratio`` uses the actually-applied
    delta (old minus new params), so it reflects the post-normalization
    post-LR update the step really took, not the raw gradient. All
    reductions accumulate in f32 so bf16 training reports stable norms.
    """
    import jax
    import jax.numpy as jnp

    def sumsq(tree):
        total = jnp.zeros((), jnp.float32)
        for leaf in jax.tree.leaves(tree):
            total = total + jnp.sum(
                jnp.square(leaf.astype(jnp.float32)))
        return total

    g_leaves = jax.tree.leaves(grads)
    nonfinite = jnp.zeros((), jnp.float32)
    for leaf in g_leaves:
        nonfinite = nonfinite + jnp.sum(
            (~jnp.isfinite(leaf)).astype(jnp.float32))
    param_sq = sumsq(new_params)
    delta_sq = jnp.zeros((), jnp.float32)
    for old, new in zip(jax.tree.leaves(params),
                        jax.tree.leaves(new_params)):
        delta_sq = delta_sq + jnp.sum(jnp.square(
            old.astype(jnp.float32) - new.astype(jnp.float32)))
    param_norm = jnp.sqrt(param_sq)
    return {
        "grad_norm": jnp.sqrt(sumsq(grads)),
        "update_ratio": jnp.sqrt(delta_sq)
        / jnp.maximum(param_norm, 1e-12),
        "param_norm": param_norm,
        "nonfinite_grads": nonfinite,
    }


def host_grad_health(grad, x_old, x_new):
    """Host-side (numpy) variant for the line-search solver loop
    (optimize/solver.py): the solver is host-composed — it already
    fetches the score every iteration — so health there is plain numpy
    on the flat vectors, adding zero executables."""
    import numpy as np

    g = np.asarray(grad)
    new = np.asarray(x_new)
    param_norm = float(np.linalg.norm(new))
    return {
        "grad_norm": float(np.linalg.norm(g)),
        "update_ratio": float(
            np.linalg.norm(new - np.asarray(x_old))
            / max(param_norm, 1e-12)),
        "param_norm": param_norm,
        "nonfinite_grads": float(np.count_nonzero(~np.isfinite(g))),
    }


def fetch_health(health) -> Optional[Dict[str, List[float]]]:
    """Normalize a recorded health payload to ``{key: [floats]}``:
    accepts a dict of device/host scalars, a dict of [K] per-step
    arrays (the fit_scan window shape), a zero-arg callable producing
    either, or None. Flattening happens HERE, at the consumer's sync
    point — producers never pay a fetch."""
    import numpy as np

    if health is None:
        return None
    if callable(health):
        health = health()
    if health is None:
        return None
    out: Dict[str, List[float]] = {}
    for key, value in health.items():
        arr = np.asarray(value, dtype=np.float64).ravel()
        out[key] = [float(v) for v in arr]
    return out


class TrainTelemetry:
    """Host-side phase accumulator for one training loop.

    Every network owns one (``net.train_telemetry``). The fit loops add
    disjoint measured intervals — data-wait around the iterator fetch,
    dispatch wall around the jitted call — plus step/example/token
    counts and the step's health outputs. A consumer (the tracing
    listener) drains the window with :meth:`consume`; the window wall
    is measured at drain time, AFTER the consumer's score sync, so
    ``data_wait + dispatch + sync <= wall`` is guaranteed by interval
    containment rather than by luck.
    """

    __slots__ = ("wall_start", "data_wait_s", "dispatch_s", "steps",
                 "examples", "tokens", "health", "_active")

    def __init__(self) -> None:
        self._reset(time.perf_counter())

    def _reset(self, now: float) -> None:
        self.wall_start = now
        self.data_wait_s = 0.0
        self.dispatch_s = 0.0
        self.steps = 0
        self.examples = 0
        self.tokens = 0
        self.health: Any = None
        self._active = False

    def _anchor(self, elapsed: float) -> None:
        """Re-anchor the wall origin at the START of a window's first
        measured event (``elapsed`` seconds ago). Without this, the
        first window's wall would stretch back to network CONSTRUCTION
        — dataset downloads and conf building between init and the
        first fit would read as step time."""
        if not self._active:
            self.wall_start = time.perf_counter() - elapsed
            self._active = True

    def add_data_wait(self, seconds: float) -> None:
        self._anchor(seconds)
        self.data_wait_s += seconds

    def record_step(self, dispatch_s: float = 0.0, steps: int = 1,
                    examples: int = 0, tokens: int = 0,
                    health=None) -> None:
        """Stamp one dispatch: ``steps`` optimizer iterations covered
        (K for a fused fit_scan window), batch sizes, and the step's
        health outputs (device pytree, [K]-leaf pytree, or a lazy
        callable — kept un-fetched until a consumer drains)."""
        self._anchor(dispatch_s)
        self.dispatch_s += dispatch_s
        self.steps += steps
        self.examples += examples
        self.tokens += tokens
        if health is not None:
            self.health = health

    def consume(self) -> Optional[Dict[str, Any]]:
        """Drain the window: returns ``{wall_s, data_wait_s,
        dispatch_s, steps, examples, tokens, health}`` and starts a new
        window. None when no step landed since the last drain (a
        listener firing twice at one iteration must not emit an empty
        sample) — an empty drain leaves the window UNTOUCHED, so
        accrued data-wait and the wall origin survive into the window
        that finally carries a step (phase sums <= wall stays an
        interval-containment fact)."""
        now = time.perf_counter()
        if self.steps == 0:
            return None
        snap = {
            "wall_s": now - self.wall_start,
            "data_wait_s": self.data_wait_s,
            "dispatch_s": self.dispatch_s,
            "steps": self.steps,
            "examples": self.examples,
            "tokens": self.tokens,
            "health": self.health,
        }
        self._reset(now)
        return snap


def batch_counts(features) -> tuple:
    """(examples, tokens) of one batch: tokens is B*T for EXACTLY
    rank-3 ([B, C, T]) time-series features; any other rank (2-D
    dense, 4-D conv images) counts tokens == examples — a [B, C, H, W]
    image batch must not report B*H as a token rate."""
    shape = getattr(features, "shape", None)
    if not shape:
        return 0, 0
    examples = int(shape[0])
    tokens = examples * int(shape[2]) if len(shape) == 3 else examples
    return examples, tokens


def window_counts(shape) -> tuple:
    """(steps, examples, tokens) of one stacked fit_scan window
    ([K, B, ...]; tokens = K*B*T only for exactly [K, B, C, T] time
    series, mirroring :func:`batch_counts`). Shape-only — never slices
    a device array (a host-side ``feats[0]`` would dispatch a gather
    executable just to read a shape)."""
    k = int(shape[0])
    examples = k * int(shape[1])
    tokens = (examples * int(shape[3]) if len(shape) == 4
              else examples)
    return k, examples, tokens


def emit_step_span(tracer, dispatch_s: float,
                   args: Dict[str, Any]) -> None:
    """One ``train.parallel_step`` complete span ending now, carrying
    the trainer's mesh-config ``args`` — the shared emitter behind
    every parallel trainer's per-step Perfetto track."""
    if tracer is None:
        return
    dur_us = dispatch_s * 1e6
    tracer.complete("train.parallel_step", tracer.now_us() - dur_us,
                    dur_us, **args)


def mesh_args(mesh, trainer: str, **extra) -> Dict[str, Any]:
    """JSON-safe span annotation for a parallel trainer's step spans:
    mesh shape by axis name plus the trainer kind and any active-axis
    assignments — what makes a MULTICHIP sweep's per-combo Chrome
    traces comparable side by side in Perfetto."""
    args: Dict[str, Any] = {
        "trainer": trainer,
        "mesh": {str(name): int(size)
                 for name, size in dict(mesh.shape).items()},
        "devices": int(mesh.devices.size),
    }
    for key, value in extra.items():
        if value is not None:
            args[key] = value
    return args


class MetricsLog:
    """Append-only JSONL metrics sink for headless training runs.

    One JSON object per line; ``write`` is thread-safe and flushes so a
    crashed run keeps every completed record. Reader side:
    ``MetricsLog.read(path)`` returns the parsed records (skipping a
    torn final line, which only an OS-level crash can leave).
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            if self._f.closed:
                raise ValueError(f"MetricsLog {self.path} is closed")
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __enter__(self) -> "MetricsLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @staticmethod
    def read(path: str) -> List[Dict[str, Any]]:
        records = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    break  # torn tail from a hard crash
        return records
