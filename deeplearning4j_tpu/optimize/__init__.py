"""Optimization: solver loop, line-search optimizers, listeners, terminations.

Mirror of reference optimize/** (Solver.java:42, BaseOptimizer.java:55,
solvers/{StochasticGradientDescent,ConjugateGradient,LBFGS,
BackTrackLineSearch}.java, api/IterationListener.java). The SGD path lives
inside MultiLayerNetwork's jitted train step; the second-order paths here
drive jitted flat-parameter value_and_grad evaluations from a host loop
(they are capability-parity paths, not the TPU hot loop).
"""

from deeplearning4j_tpu.optimize.listeners import (
    ComposableIterationListener,
    IterationListener,
    ScoreIterationListener,
    TracingIterationListener,
)
from deeplearning4j_tpu.optimize.telemetry import (
    MetricsLog,
    TrainTelemetry,
)
from deeplearning4j_tpu.optimize.stepfunctions import (
    DefaultStepFunction,
    GradientStepFunction,
    NegativeDefaultStepFunction,
    NegativeGradientStepFunction,
    StepFunction,
)
