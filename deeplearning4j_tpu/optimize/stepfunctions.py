"""Parameter step functions for the optimizer loop.

TPU-native equivalent of the reference step-function SPI (reference
optimize/stepfunctions/{DefaultStepFunction,GradientStepFunction,
NegativeDefaultStepFunction,NegativeGradientStepFunction}.java and the
nn/conf/stepfunctions beans): how ``params`` moves along the search
direction after line search. Pure functions over jax/numpy arrays so they
stay inside the jitted/flat optimizer path.
"""

from __future__ import annotations


class StepFunction:
    """``step(x, direction, step_size) -> new x``."""

    def step(self, x, direction, step_size: float = 1.0):
        raise NotImplementedError


class DefaultStepFunction(StepFunction):
    """x + step * direction (reference DefaultStepFunction.java)."""

    def step(self, x, direction, step_size: float = 1.0):
        return x + step_size * direction


class GradientStepFunction(StepFunction):
    """x + direction, ignoring the line-search scale (reference
    GradientStepFunction.java)."""

    def step(self, x, direction, step_size: float = 1.0):
        return x + direction


class NegativeDefaultStepFunction(StepFunction):
    """x - step * direction, for ascent-convention directions (reference
    NegativeDefaultStepFunction.java)."""

    def step(self, x, direction, step_size: float = 1.0):
        return x - step_size * direction


class NegativeGradientStepFunction(StepFunction):
    """x - direction (reference NegativeGradientStepFunction.java)."""

    def step(self, x, direction, step_size: float = 1.0):
        return x - direction


_REGISTRY = {
    "default": DefaultStepFunction,
    "gradient": GradientStepFunction,
    "negative_default": NegativeDefaultStepFunction,
    "negative_gradient": NegativeGradientStepFunction,
}


def from_name(name) -> StepFunction:
    """Resolve a step function from its conf name (reference
    StepFunctions.java factory)."""
    if isinstance(name, StepFunction):
        return name
    key = str(name).lower().replace("stepfunction", "").strip("_")
    key = {"negativedefault": "negative_default",
           "negativegradient": "negative_gradient"}.get(key, key)
    if key not in _REGISTRY:
        raise ValueError(
            f"unknown step function {name!r}; one of {sorted(_REGISTRY)}")
    return _REGISTRY[key]()
