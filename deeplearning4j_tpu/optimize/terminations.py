"""Termination conditions for the iterative optimizers.

Mirror of reference optimize/terminations/{EpsTermination,Norm2Termination,
ZeroDirection}.java, checked at the end of each optimizer iteration
(BaseOptimizer.java:222).
"""

from __future__ import annotations

import numpy as np


class TerminationCondition:
    def terminate(self, cost: float, old_cost: float, direction) -> bool:
        raise NotImplementedError


class EpsTermination(TerminationCondition):
    def __init__(self, eps: float = 1e-4, tolerance: float = 1e-8):
        self.eps = eps
        self.tolerance = tolerance

    def terminate(self, cost, old_cost, direction) -> bool:
        if old_cost == 0.0:
            return abs(cost - old_cost) < self.tolerance
        return abs(cost - old_cost) / abs(old_cost) < self.eps


class Norm2Termination(TerminationCondition):
    def __init__(self, gradient_tolerance: float = 1e-6):
        self.gradient_tolerance = gradient_tolerance

    def terminate(self, cost, old_cost, direction) -> bool:
        return float(np.linalg.norm(np.asarray(direction))) < self.gradient_tolerance


class ZeroDirection(TerminationCondition):
    def terminate(self, cost, old_cost, direction) -> bool:
        return float(np.abs(np.asarray(direction)).max()) == 0.0


DEFAULT_CONDITIONS = (ZeroDirection(), EpsTermination())
