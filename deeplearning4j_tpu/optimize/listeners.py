"""Iteration listeners.

Mirror of reference optimize/api/IterationListener.java + listeners/
{ScoreIterationListener.java:31, ParamAndGradientIterationListener.java,
ComposableIterationListener.java}. Invoked from the host loop after each
optimizer iteration (the one host sync point per step).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

log = logging.getLogger(__name__)


class IterationListener:
    """SPI: ``iteration_done(model, iteration)``."""

    invoked_every: int = 1

    def iteration_done(self, model, iteration: int) -> None:
        raise NotImplementedError


def fire_crossed(listeners, model, start: int, end: int) -> None:
    """Fused K-step (fit_scan) listener cadence, shared by every scanned
    trainer path: fire each listener once per call iff the (start, end]
    iteration window crossed a multiple of its ``invoked_every`` — the
    same cadence per-step fit() would show, coalesced per call.

    Pinned edge semantics (ISSUE 8 satellite, unit-tested directly):
    ``invoked_every <= 1`` (including 0 and negatives) means every
    call, matching the per-step loops' ``invoked_every <= 1`` branch;
    ``start == end`` (an empty window) never fires; a window crossing
    SEVERAL multiples of the cadence fires exactly once per call — the
    listener sees the window's final iteration, the coalesced
    equivalent of the per-step cadence."""
    for listener in listeners:
        n = max(1, listener.invoked_every)
        if end // n > start // n:
            listener.iteration_done(model, end)


class ScoreIterationListener(IterationListener):
    """Log the score every N iterations (reference
    ScoreIterationListener.java:31)."""

    def __init__(self, print_iterations: int = 10):
        self.invoked_every = max(1, print_iterations)

    def iteration_done(self, model, iteration: int) -> None:
        log.info("Score at iteration %d is %s", iteration, float(model.score_value))


class ComposableIterationListener(IterationListener):
    def __init__(self, *listeners: IterationListener):
        self.listeners: List[IterationListener] = list(listeners)

    def iteration_done(self, model, iteration: int) -> None:
        for listener in self.listeners:
            listener.iteration_done(model, iteration)


class CollectScoresIterationListener(IterationListener):
    """Accumulate (iteration, score) pairs in memory (reference
    CollectScoresIterationListener)."""

    def __init__(self, frequency: int = 1):
        self.invoked_every = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration: int) -> None:
        self.scores.append((iteration, float(model.score_value)))


class ParamAndGradientIterationListener(IterationListener):
    """Log parameter norms each iteration (reference
    ParamAndGradientIterationListener.java)."""

    def __init__(self, iterations: int = 1):
        self.invoked_every = max(1, iterations)

    def iteration_done(self, model, iteration: int) -> None:
        import jax.numpy as jnp

        for key, p in model.param_table().items():
            log.info(
                "iter %d param %s: mean=%.6f l2=%.6f",
                iteration, key, float(jnp.mean(p)),
                float(jnp.linalg.norm(p.ravel())),
            )


class TimeIterationListener(IterationListener):
    """Wall-clock per-iteration logging."""

    def __init__(self):
        self._last: Optional[float] = None

    def iteration_done(self, model, iteration: int) -> None:
        now = time.time()
        if self._last is not None:
            log.info("iteration %d took %.4fs", iteration, now - self._last)
        self._last = now


class LambdaIterationListener(IterationListener):
    def __init__(self, fn: Callable, every: int = 1):
        self._fn = fn
        self.invoked_every = max(1, every)

    def iteration_done(self, model, iteration: int) -> None:
        self._fn(model, iteration)


class TracingIterationListener(IterationListener):
    """Feed the per-step phase breakdown, throughput, and gradient
    health into a :class:`~deeplearning4j_tpu.profiler.tracer.Tracer`
    and/or a JSONL :class:`~deeplearning4j_tpu.optimize.telemetry
    .MetricsLog` through the standard listener SPI (ISSUE 8 tentpole).

    The listener OWNS the training histograms (works with
    ``tracer=None`` — a headless JSONL-only run still gets quantiles
    via :meth:`quantile`) and registers them on the tracer by
    reference, the same adopt-by-reference contract the serving engine
    uses. Each fire drains the model's ``train_telemetry`` window:

    - times the score fetch (THE one host sync a training loop has —
      telemetry adds no second one) as the ``sync`` phase,
    - observes ``train_step_s`` / ``train_data_wait_s`` with the
      batched ``observe(value, n=steps)`` form so a fused fit_scan
      window of K steps costs one lock acquisition,
    - fetches the step's gradient-health outputs (computed INSIDE the
      already-run jitted step; the fetch rides the same sync domain),
    - emits a ``train.step`` span carrying the full breakdown in its
      args plus contiguous ``train.data_wait`` / ``train.dispatch`` /
      ``train.sync`` child spans for Perfetto,
    - appends one JSONL record to the metrics log.

    Works on fused scan paths through the ``fire_crossed`` cadence: a
    K-step window that crossed the cadence fires once, with all K
    per-step health values observed from the window's stacked arrays.
    """

    def __init__(self, tracer=None, frequency: int = 1,
                 metrics_log=None):
        from deeplearning4j_tpu.optimize import telemetry as T
        from deeplearning4j_tpu.profiler.tracer import Histogram

        self.tracer = tracer
        self.invoked_every = max(1, frequency)
        self.metrics_log = metrics_log
        value_tracks = ("train_grad_norm", "train_update_ratio",
                        "train_param_norm")
        self.hists = {
            name: Histogram(T.VALUE_BOUNDS
                            if name in value_tracks else None)
            for name in T.TRAIN_HISTOGRAMS + (T.TRAIN_SYNC_HISTOGRAM,)
        }
        if tracer is not None:
            for name, hist in self.hists.items():
                tracer.register_histogram(name, hist)
            for name, help_text in T.TRAIN_TRACK_HELP.items():
                tracer.describe(name, help_text)

    def quantile(self, name: str, q: float) -> float:
        """Quantile of one owned histogram track (``train_step_s``,
        ...) — the headless counterpart of a Prometheus query."""
        return self.hists[name].quantile(q)

    def iteration_done(self, model, iteration: int) -> None:
        from deeplearning4j_tpu.optimize import telemetry as T

        t0 = time.perf_counter()
        score = float(model.score_value)  # the existing host sync
        sync_s = time.perf_counter() - t0
        telemetry = getattr(model, "train_telemetry", None)
        snap = telemetry.consume() if telemetry is not None else None
        record = {"iteration": int(iteration), "score": score,
                  "sync_s": sync_s, "time": time.time()}
        self.hists["train_sync_s"].observe(sync_s)
        if snap is not None:
            steps = snap["steps"]
            wall = snap["wall_s"]
            self.hists["train_step_s"].observe(wall / steps, steps)
            self.hists["train_data_wait_s"].observe(
                snap["data_wait_s"] / steps, steps)
            health = T.fetch_health(snap["health"])
            nonfinite = 0.0
            if health:
                for key, track in (
                        ("grad_norm", "train_grad_norm"),
                        ("update_ratio", "train_update_ratio"),
                        ("param_norm", "train_param_norm")):
                    for value in health.get(key, ()):
                        self.hists[track].observe(value)
                nonfinite = sum(health.get("nonfinite_grads", ()))
                for key in ("grad_norm", "update_ratio", "param_norm"):
                    if health.get(key):
                        record[key] = health[key][-1]
                record["nonfinite_grads"] = nonfinite
            record.update(
                steps=steps, wall_s=wall, step_s=wall / steps,
                data_wait_s=snap["data_wait_s"],
                dispatch_s=snap["dispatch_s"],
                examples_per_sec=snap["examples"] / max(wall, 1e-9),
                tokens_per_sec=snap["tokens"] / max(wall, 1e-9),
            )
            if self.tracer is not None:
                self._emit_trace(iteration, score, snap, sync_s,
                                 nonfinite)
        elif self.tracer is not None:
            self.tracer.counter("train_score", score)
        if self.metrics_log is not None:
            self.metrics_log.write(record)

    def _emit_trace(self, iteration, score, snap, sync_s,
                    nonfinite) -> None:
        tracer = self.tracer
        wall_us = snap["wall_s"] * 1e6
        end_us = tracer.now_us()
        start_us = end_us - wall_us
        tracer.complete(
            "train.step", start_us, wall_us, iteration=int(iteration),
            steps=snap["steps"], score=score,
            data_wait_s=snap["data_wait_s"],
            dispatch_s=snap["dispatch_s"], sync_s=sync_s,
            examples=snap["examples"], tokens=snap["tokens"])
        # Contiguous phase child spans: positions are the canonical
        # wait->dispatch->sync order (approximate inside multi-step
        # windows), durations exact — the Perfetto-visible breakdown.
        tracer.complete("train.data_wait", start_us,
                        snap["data_wait_s"] * 1e6)
        tracer.complete("train.dispatch",
                        start_us + snap["data_wait_s"] * 1e6,
                        snap["dispatch_s"] * 1e6)
        tracer.complete("train.sync", end_us - sync_s * 1e6,
                        sync_s * 1e6)
        tracer.counter("train_score", score)
        tracer.rate("train_examples_per_sec", snap["examples"],
                    snap["wall_s"])
        if snap["tokens"]:
            tracer.rate("train_tokens_per_sec", snap["tokens"],
                        snap["wall_s"])
        tracer.incr("train_steps_total", snap["steps"])
        if nonfinite:
            tracer.incr("train_nonfinite_grads", nonfinite)


class BestScoreIterationListener(IterationListener):
    """Track the best (lowest) score seen (reference Spark
    BestScoreAccumulator / BestScoreIterationListener roles)."""

    def __init__(self, frequency: int = 1):
        self.invoked_every = max(1, frequency)
        self.best_score = float("inf")
        self.best_iteration = -1

    def iteration_done(self, model, iteration: int) -> None:
        score = float(model.score_value)
        if score < self.best_score:
            self.best_score = score
            self.best_iteration = iteration
