"""Iteration listeners.

Mirror of reference optimize/api/IterationListener.java + listeners/
{ScoreIterationListener.java:31, ParamAndGradientIterationListener.java,
ComposableIterationListener.java}. Invoked from the host loop after each
optimizer iteration (the one host sync point per step).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

log = logging.getLogger(__name__)


class IterationListener:
    """SPI: ``iteration_done(model, iteration)``."""

    invoked_every: int = 1

    def iteration_done(self, model, iteration: int) -> None:
        raise NotImplementedError


def fire_crossed(listeners, model, start: int, end: int) -> None:
    """Fused K-step (fit_scan) listener cadence, shared by every scanned
    trainer path: fire each listener once per call iff the [start, end]
    iteration window crossed a multiple of its ``invoked_every`` — the
    same cadence per-step fit() would show, coalesced per call."""
    for listener in listeners:
        n = max(1, listener.invoked_every)
        if end // n > start // n:
            listener.iteration_done(model, end)


class ScoreIterationListener(IterationListener):
    """Log the score every N iterations (reference
    ScoreIterationListener.java:31)."""

    def __init__(self, print_iterations: int = 10):
        self.invoked_every = max(1, print_iterations)

    def iteration_done(self, model, iteration: int) -> None:
        log.info("Score at iteration %d is %s", iteration, float(model.score_value))


class ComposableIterationListener(IterationListener):
    def __init__(self, *listeners: IterationListener):
        self.listeners: List[IterationListener] = list(listeners)

    def iteration_done(self, model, iteration: int) -> None:
        for listener in self.listeners:
            listener.iteration_done(model, iteration)


class CollectScoresIterationListener(IterationListener):
    """Accumulate (iteration, score) pairs in memory (reference
    CollectScoresIterationListener)."""

    def __init__(self, frequency: int = 1):
        self.invoked_every = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration: int) -> None:
        self.scores.append((iteration, float(model.score_value)))


class ParamAndGradientIterationListener(IterationListener):
    """Log parameter norms each iteration (reference
    ParamAndGradientIterationListener.java)."""

    def __init__(self, iterations: int = 1):
        self.invoked_every = max(1, iterations)

    def iteration_done(self, model, iteration: int) -> None:
        import jax.numpy as jnp

        for key, p in model.param_table().items():
            log.info(
                "iter %d param %s: mean=%.6f l2=%.6f",
                iteration, key, float(jnp.mean(p)),
                float(jnp.linalg.norm(p.ravel())),
            )


class TimeIterationListener(IterationListener):
    """Wall-clock per-iteration logging."""

    def __init__(self):
        self._last: Optional[float] = None

    def iteration_done(self, model, iteration: int) -> None:
        now = time.time()
        if self._last is not None:
            log.info("iteration %d took %.4fs", iteration, now - self._last)
        self._last = now


class LambdaIterationListener(IterationListener):
    def __init__(self, fn: Callable, every: int = 1):
        self._fn = fn
        self.invoked_every = max(1, every)

    def iteration_done(self, model, iteration: int) -> None:
        self._fn(model, iteration)


class BestScoreIterationListener(IterationListener):
    """Track the best (lowest) score seen (reference Spark
    BestScoreAccumulator / BestScoreIterationListener roles)."""

    def __init__(self, frequency: int = 1):
        self.invoked_every = max(1, frequency)
        self.best_score = float("inf")
        self.best_iteration = -1

    def iteration_done(self, model, iteration: int) -> None:
        score = float(model.score_value)
        if score < self.best_score:
            self.best_score = score
            self.best_iteration = iteration
