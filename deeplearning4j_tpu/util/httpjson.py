"""Shared JSON-over-HTTP scaffolding for control-plane services.

One base for the coordinator (scaleout/coordinator.py) and the UI server
(ui/server.py): a silenced BaseHTTPRequestHandler with JSON helpers and a
threaded server lifecycle wrapper. Handlers must compute their response
payload first (holding any state lock) and only then call ``send_json`` —
never write the socket while holding a lock, or one slow-reading client
stalls every other request (including heartbeats).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple


class JsonHandler(BaseHTTPRequestHandler):
    """Request handler base: JSON body parsing + JSON/bytes replies."""

    def log_message(self, fmt: str, *args: Any) -> None:  # silence
        pass

    def read_json(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length", 0))
        if n == 0:
            return {}
        return json.loads(self.rfile.read(n))

    def send_json(self, obj: Dict[str, Any], code: int = 200) -> None:
        self.send_bytes(json.dumps(obj).encode(), "application/json", code)

    def send_bytes(self, body: bytes, content_type: str,
                   code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class HttpService:
    """Threaded HTTP server lifecycle: build, start, address, stop.

    Subclasses (or callers) provide a concrete handler class; extra
    attributes are attached to a per-instance handler subclass so one
    process can run several services."""

    def __init__(self, handler_cls, host: str = "127.0.0.1", port: int = 0,
                 **handler_attrs: Any):
        handler = type(handler_cls.__name__, (handler_cls,), handler_attrs)
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5.0)
