"""Shared JSON-over-HTTP scaffolding for control-plane services.

One base for the coordinator (scaleout/coordinator.py), the UI server
(ui/server.py), and the serving gateway (serving/gateway.py): a silenced
BaseHTTPRequestHandler with JSON helpers, chunked-transfer streaming,
and a threaded server lifecycle wrapper. Handlers must compute their
response payload first (holding any state lock) and only then call
``send_json`` — never write the socket while holding a lock, or one
slow-reading client stalls every other request (including heartbeats).

Connection lifetime is BOUNDED (ISSUE 5 satellite): every handler
carries a socket ``timeout`` (class attribute, overridable per service
via ``HttpService(..., timeout=...)``), so a half-open client that
connects and never sends a request — or stops reading mid-response —
cannot pin a ``ThreadingHTTPServer`` thread forever: the blocked read
times out, ``BaseHTTPRequestHandler`` flags ``close_connection``, and
the thread exits. One-shot responses can additionally advertise
``Connection: close`` (``send_json(..., close=True)``) so well-behaved
clients don't hold keep-alive sockets the service will never reuse.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

#: Dapper-style trace-context carrier (ISSUE 10): the router mints
#: ``<trace_id>/<span_id>`` per request attempt and every hop
#: (router → gateway → engine) forwards it, so one fleet-level id
#: stitches a request's spans across processes. One definition here —
#: the client sends it, every JSON service reads it — so the wire
#: name can never drift between the two sides.
TRACE_HEADER = "X-DL4J-Trace"


class JsonHandler(BaseHTTPRequestHandler):
    """Request handler base: JSON body parsing + JSON/bytes replies +
    chunked-transfer streaming (``start_stream``/``send_chunk``/
    ``end_stream`` — requires ``protocol_version = "HTTP/1.1"`` on the
    subclass; under HTTP/1.0 the stream falls back to
    read-until-close framing)."""

    #: per-connection socket timeout in seconds (socketserver applies
    #: it in ``setup()``): bounds how long a stalled or vanished client
    #: can hold a server thread between reads. None = unbounded (the
    #: pre-ISSUE-5 behavior; no service uses it).
    timeout: Optional[float] = 30.0

    def log_message(self, fmt: str, *args: Any) -> None:  # silence
        pass

    def trace_context(self) -> Optional[str]:
        """The request's :data:`TRACE_HEADER` value (None when the
        caller sent no trace context). Bounded: a hostile header
        cannot grow server-side bookkeeping past 256 chars."""
        value = self.headers.get(TRACE_HEADER)
        if value is None:
            return None
        value = value.strip()
        return value[:256] or None

    def read_json(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length", 0))
        if n == 0:
            return {}
        return json.loads(self.rfile.read(n))

    # -- bounded binary request/response (ISSUE 14 satellite) ----------
    def read_binary(self, max_bytes: int) -> Optional[bytes]:
        """Read a raw (non-JSON) request body with a HARD size cap —
        the KV-transfer import endpoint rides this. The cap is checked
        against ``Content-Length`` BEFORE any byte is read, so an
        oversized payload answers **413** without ever buffering (no
        base64 round-trip, no OOM from a hostile length); a missing
        length answers **411** (chunked uploads are not accepted — the
        cap must be checkable up front). Returns the body, or ``None``
        when a rejection was already sent (the caller just returns).
        A body shorter than its declared length (peer died mid-send)
        answers **400**."""
        length = self.headers.get("Content-Length")
        if length is None:
            self.send_json({"error": "Content-Length required for "
                                     "binary uploads"}, 411,
                           close=True)
            return None
        try:
            n = int(length)
        except ValueError:
            self.send_json({"error": f"bad Content-Length "
                                     f"{length!r}"}, 400, close=True)
            return None
        if n < 0 or n > max_bytes:
            self.send_json(
                {"error": f"payload {n} bytes exceeds the "
                          f"{max_bytes}-byte cap", "max_bytes":
                 int(max_bytes)}, 413, close=True)
            return None
        body = self.rfile.read(n)
        if len(body) != n:
            self.send_json(
                {"error": f"truncated body: {len(body)} of {n} "
                          "declared bytes arrived"}, 400, close=True)
            return None
        return body

    def send_binary(self, body: bytes, code: int = 200) -> None:
        """Raw-bytes response (``application/octet-stream``) — the
        export half of the bounded binary path. One-shot by design
        (``Connection: close``): a transfer payload is fetched once,
        never pipelined."""
        self.send_bytes(body, "application/octet-stream", code,
                        close=True)

    def send_json(self, obj: Dict[str, Any], code: int = 200,
                  close: bool = False,
                  headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        self.send_bytes(json.dumps(obj).encode(), "application/json",
                        code, close=close, headers=headers)

    def send_bytes(self, body: bytes, content_type: str,
                   code: int = 200, close: bool = False,
                   headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in headers:
            self.send_header(name, str(value))
        if close:
            # explicit is kinder than implicit: the client learns the
            # socket is one-shot instead of discovering it at EOF
            self.send_header("Connection", "close")
            self.close_connection = True
        self.end_headers()
        self.wfile.write(body)

    # -- incremental (chunked-transfer) responses ----------------------
    def start_stream(self, content_type: str = "text/event-stream",
                     code: int = 200,
                     headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        """Open an incremental response: headers go out now, the body
        arrives in ``send_chunk`` pieces, ``end_stream`` terminates it.
        When BOTH sides speak HTTP/1.1 the body is
        chunked-transfer-encoded (each piece is a delimited chunk a
        client can act on as it lands); for an HTTP/1.0 peer — where
        chunked framing does not exist and RFC 7230 forbids sending
        it — the pieces stream raw and end-of-body is the connection
        closing."""
        self._stream_chunked = (self.protocol_version >= "HTTP/1.1"
                                and self.request_version >= "HTTP/1.1")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Cache-Control", "no-cache")
        for name, value in headers:
            self.send_header(name, str(value))
        if self._stream_chunked:
            self.send_header("Transfer-Encoding", "chunked")
        else:
            self.send_header("Connection", "close")
        # a stream monopolizes its connection until it ends; never
        # leave it open for a pipelined follow-up request
        self.close_connection = True
        self.end_headers()

    def send_chunk(self, data: bytes) -> None:
        if not data:
            return  # a zero-length chunk would terminate the stream
        if self._stream_chunked:
            self.wfile.write(b"%X\r\n" % len(data) + data + b"\r\n")
        else:
            self.wfile.write(data)
        self.wfile.flush()

    def end_stream(self) -> None:
        if self._stream_chunked:
            self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()

    def send_trace_events(self, events, next_seq=None) -> None:
        """Stream a Chrome trace-event document in 512-event chunks
        (one wire format for every trace export: the gateway's
        ``/v1/trace`` and the router's stitched fleet variant must
        never drift). A large window never materializes as one giant
        bytes object; ``next_seq`` prefixes the incremental-scrape
        cursor (ISSUE 10). A vanished client is swallowed — there is
        nothing to release on a read-only export."""
        try:
            self.start_stream("application/json")
            if next_seq is not None:
                self.send_chunk(b'{"nextSeq":%d,"traceEvents":['
                                % int(next_seq))
            else:
                self.send_chunk(b'{"traceEvents":[')
            for lo in range(0, len(events), 512):
                piece = ",".join(json.dumps(e)
                                 for e in events[lo:lo + 512])
                if lo:
                    piece = "," + piece
                self.send_chunk(piece.encode())
            self.send_chunk(b"]}")
            self.end_stream()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    # -- SSE framing (one definition for every streaming service:
    # the gateway and the router must never drift on the wire format)
    def send_event(self, obj: Dict[str, Any],
                   event_id: Optional[int] = None) -> None:
        """One SSE data event. ``event_id`` (ISSUE 15) rides as the
        standard ``id:`` field — the serving streams use the
        cumulative delivered-token count, so a client that
        reconnects with ``Last-Event-ID: N`` resumes at exactly
        token N: monotone, gap-free, duplicate-free by the SSE
        contract itself."""
        frame = b""
        if event_id is not None:
            frame += b"id: %d\n" % int(event_id)
        self.send_chunk(frame + b"data: "
                        + json.dumps(obj).encode() + b"\n\n")

    def send_ping(self) -> None:
        # SSE comment line: ignored by clients, but the write probes
        # whether the peer is still there (a vanished client surfaces
        # as a send error)
        self.send_chunk(b": ping\n\n")

    def read_resume_cursor(self, path: str, query: str
                           ) -> Optional[Tuple[int, int]]:
        """Parse a ``GET /v1/requests/<rid>/stream`` resume request
        into ``(rid, cursor)`` — the cursor from ``Last-Event-ID``
        (the SSE-standard reconnect carrier) or the ``?from=``
        query fallback, defaulting to 0. ONE definition (ISSUE 15):
        the request-side twin of :meth:`send_event`'s ``id:``
        framing, shared by the gateway's and the router's resume
        endpoints so the two cannot drift. Sends the **400** itself
        and returns ``None`` on a malformed id/cursor — the caller
        just returns."""
        tail = path[len("/v1/requests/"):-len("/stream")]
        try:
            rid = int(tail)
        except ValueError:
            self.send_json({"error": f"bad request id {tail!r}"},
                           400, close=True)
            return None
        last_id = self.headers.get("Last-Event-ID")
        if last_id is None:
            for part in query.split("&"):
                if part.startswith("from="):
                    last_id = part[len("from="):]
        try:
            cursor = int(last_id) if last_id is not None else 0
        except ValueError:
            self.send_json(
                {"error": f"bad Last-Event-ID {last_id!r}"}, 400,
                close=True)
            return None
        if cursor < 0:
            self.send_json(
                {"error": f"negative resume cursor {cursor}"}, 400,
                close=True)
            return None
        return rid, cursor

    def follow_stream(
            self, rid: int, cursor: int,
            poll: Callable[[], Tuple[List[int], bool,
                                     Optional[Dict[str, Any]]]],
            wait: Callable[[float], Any],
            keepalive_s: float) -> None:
        """The response half of a stream resume (ISSUE 15), shared by
        the gateway's and the router's endpoints so the cursor math,
        event-id monotonicity, and keepalive cadence cannot drift —
        the body-side twin of :meth:`read_resume_cursor`.

        ``poll(cursor) -> (tail, total, done, terminal)``: the
        delivered tokens PAST the cursor (never the whole list — a
        long stream's follower must not copy O(n) per tick), the
        total delivered count, whether the request is finished, and
        the terminal dict to emit (None = end WITHOUT a terminal:
        the underlying request was dropped or the server stopped).
        ``wait(timeout_s)`` blocks until progress may have happened
        (typically the entry's done-Event wait). Emits the head event
        at ``cursor``, replays/follows everything past it — each
        event's ``id:`` is the cumulative token count, so a resumed
        stream is itself resumable — pings at ``keepalive_s`` cadence
        while idle (waking on a shorter quantum so followed tokens
        flow per-delta), and finishes with the terminal. The usual
        OSError family propagates when the consumer vanishes; the
        caller decides what that means."""
        self.start_stream("text/event-stream")
        self.send_event({"id": rid, "resumed": True,
                         "from": cursor}, event_id=cursor)
        last_ping = time.monotonic()
        quantum = min(keepalive_s, 0.05)
        while True:
            tail, total, done, terminal = poll(cursor)
            if tail:
                # a cursor AHEAD of the tokens (the client saw
                # tokens a crash window lost) yields an empty tail
                # and waits below: deterministic replay regrows the
                # list past it
                self.send_event({"id": rid, "tokens": tail},
                                event_id=cursor + len(tail))
                cursor += len(tail)
                continue
            if done:
                if terminal is not None:
                    out = dict(terminal)
                    out["done"] = True
                    self.send_event(out, event_id=total)
                self.end_stream()
                return
            now = time.monotonic()
            if now - last_ping >= keepalive_s:
                self.send_ping()
                last_ping = now
            wait(quantum)


class _QuietThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that treats a vanished peer as routine.

    The stock ``handle_error`` dumps a full traceback to stderr for
    EVERY connection-level failure — but a client that disconnects
    mid-response (health scraper timing out, streaming consumer
    closing early, a killed process's half-open socket) is normal
    operation for a long-lived service, not an error worth a dump.
    Handler-code bugs still print."""

    def handle_error(self, request, client_address):  # noqa: N802
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            ConnectionAbortedError, socket.timeout)):
            return
        super().handle_error(request, client_address)


class HttpService:
    """Threaded HTTP server lifecycle: build, start, address, stop.

    Subclasses (or callers) provide a concrete handler class; extra
    attributes are attached to a per-instance handler subclass so one
    process can run several services (e.g. ``timeout=5.0`` to tighten
    the per-connection read timeout for a test)."""

    def __init__(self, handler_cls, host: str = "127.0.0.1", port: int = 0,
                 **handler_attrs: Any):
        handler = type(handler_cls.__name__, (handler_cls,), handler_attrs)
        self._httpd = _QuietThreadingHTTPServer((host, port), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"http-{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5.0)

    def hard_stop(self) -> None:
        """Chaos helper (ISSUE 9): die the way a SIGKILL'd process
        looks from the network — close the LISTENING socket first so
        new connections are refused immediately, then stop the serve
        loop without any graceful notice to in-flight handlers (their
        next socket write hits a dead/raw fd and raises, exactly like
        writing into a killed process's half of a connection). Used by
        the router chaos soak to simulate replica death in-process;
        production shutdown is :meth:`stop` (or the gateway's
        drain-then-close)."""
        try:
            self._httpd.socket.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # already closed / never connected
        self._httpd.server_close()
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5.0)
