"""Disk-spilling FIFO queue (mirror of reference util/DiskBasedQueue.java).

Items beyond ``memory_capacity`` are pickled to per-item files in a
spill directory and transparently re-hydrated on dequeue; used by data
pipelines whose working set exceeds host RAM. Thread-safe.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import threading
import uuid
from collections import deque
from typing import Any, Optional


class DiskBasedQueue:
    def __init__(self, directory: Optional[str] = None,
                 memory_capacity: int = 1000):
        self._dir = directory or tempfile.mkdtemp(prefix="dl4j_queue_")
        self._own_dir = directory is None
        os.makedirs(self._dir, exist_ok=True)
        self.memory_capacity = memory_capacity
        self._lock = threading.Lock()
        # FIFO of entries: ("mem", obj) or ("disk", path)
        self._entries: deque = deque()
        self._in_memory = 0

    def add(self, item: Any) -> None:
        with self._lock:
            if self._in_memory < self.memory_capacity:
                self._entries.append(("mem", item))
                self._in_memory += 1
            else:
                path = os.path.join(self._dir, uuid.uuid4().hex + ".pkl")
                with open(path, "wb") as f:
                    pickle.dump(item, f)
                self._entries.append(("disk", path))

    def poll(self) -> Optional[Any]:
        """Dequeue head or None if empty."""
        with self._lock:
            if not self._entries:
                return None
            kind, payload = self._entries.popleft()
            if kind == "mem":
                self._in_memory -= 1
                return payload
            with open(payload, "rb") as f:
                item = pickle.load(f)
            os.unlink(payload)
            return item

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def is_empty(self) -> bool:
        return len(self) == 0

    def close(self) -> None:
        """Drop remaining items and the spill dir (if owned)."""
        with self._lock:
            for kind, payload in self._entries:
                if kind == "disk" and os.path.exists(payload):
                    os.unlink(payload)
            self._entries.clear()
            self._in_memory = 0
        if self._own_dir and os.path.isdir(self._dir):
            shutil.rmtree(self._dir, ignore_errors=True)
