"""Version compatibility shims for jax API moves.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace; depending on the pinned jax this tree runs
against, only one of the two spellings exists. Every in-repo user
imports it from here so the whole package keeps importing (and tier-1
keeps collecting) on either side of the move.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export
    from jax import shard_map  # type: ignore[attr-defined]

    NATIVE_SHARD_MAP = True
except ImportError:  # older jax: experimental namespace, older kwargs
    from jax.experimental.shard_map import shard_map as _shard_map

    # Fallback caveat (tests skipif on this): the experimental
    # shard_map's partial-manual mode (`auto=`, our `axis_names=`)
    # emits PartitionId ops that 0.4.x XLA cannot SPMD-partition —
    # multi-axis compositions (pp x tp, sp x tp, dp x pp x sp) raise
    # UNIMPLEMENTED or abort the process outright. Fully-manual
    # shard_map (no axis_names) is fine on both sides.
    NATIVE_SHARD_MAP = False

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  axis_names=None):
        """Adapt the current-jax calling convention to the experimental
        signature: ``check_vma`` was ``check_rep``, and partial-manual
        ``axis_names`` (the axes the body handles manually) was its
        complement ``auto`` (the axes left to GSPMD)."""
        kwargs = dict(mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
        if axis_names is not None:
            kwargs["auto"] = (
                frozenset(mesh.axis_names) - frozenset(axis_names))
        return _shard_map(f, **kwargs)

# jax.export exists as a MODULE on both sides of the attribute-access
# deprecation (plain `jax.export.export(...)` raises AttributeError on
# the versions where the lazy top-level attribute was dropped).
import jax.export as jax_export  # noqa: E402

try:  # newer jax re-exports at top level
    from jax import enable_x64  # type: ignore[attr-defined]
except ImportError:
    from jax.experimental import enable_x64

# The 0.4.x CPU backend has no cross-process collectives: a sharded
# device_put across two CPU-backend processes dies with "Multiprocess
# computations aren't implemented on the CPU backend". The two-process
# integration tests skip where that holds.
import jax as _jax  # noqa: E402


def _version_tuple(v: str):
    parts = []
    for p in v.split(".")[:2]:
        digits = "".join(ch for ch in p if ch.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


CPU_MULTIPROCESS_COLLECTIVES = _version_tuple(_jax.__version__) >= (0, 5)

try:  # lax.axis_size arrived after 0.4.x
    from jax.lax import axis_size
except ImportError:

    def axis_size(axis_name):
        """Size of a mapped mesh axis, via the collective identity
        psum(1) — valid anywhere lax.axis_size is."""
        import jax

        return jax.lax.psum(1, axis_name)

__all__ = ["shard_map", "jax_export", "enable_x64", "axis_size",
           "NATIVE_SHARD_MAP", "CPU_MULTIPROCESS_COLLECTIVES"]
