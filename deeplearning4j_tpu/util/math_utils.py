"""Scalar/stat helpers (capability mirror of reference util/MathUtils.java).

Only the members with semantics beyond plain numpy are kept; callers
use numpy directly for elementwise work (the reference predates that
option on the JVM).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def entropy(probs: Sequence[float]) -> float:
    """Shannon entropy in nats of a (possibly unnormalized) histogram."""
    p = np.asarray(probs, np.float64)
    p = p[p > 0]
    p = p / p.sum()
    return float(-(p * np.log(p)).sum())


def information_gain(labels: Sequence[int],
                     split: Sequence[int]) -> float:
    """Entropy(labels) - Σ_v p(split=v) * Entropy(labels | split=v)."""
    labels = np.asarray(labels)
    split = np.asarray(split)
    base = entropy(np.bincount(labels))
    cond = 0.0
    for v in np.unique(split):
        sel = labels[split == v]
        cond += (len(sel) / len(labels)) * entropy(np.bincount(sel))
    return base - cond


def euclidean_distance(a, b) -> float:
    return float(np.linalg.norm(np.asarray(a, np.float64)
                                - np.asarray(b, np.float64)))


def manhattan_distance(a, b) -> float:
    return float(np.abs(np.asarray(a, np.float64)
                        - np.asarray(b, np.float64)).sum())


def correlation(a, b) -> float:
    """Pearson correlation coefficient."""
    return float(np.corrcoef(np.asarray(a, np.float64),
                             np.asarray(b, np.float64))[0, 1])


def normalize(values, new_min: float = 0.0,
              new_max: float = 1.0) -> np.ndarray:
    """Min-max rescale to [new_min, new_max]; constant input maps to
    new_min (reference MathUtils.normalize)."""
    v = np.asarray(values, np.float64)
    span = v.max() - v.min()
    if span == 0:
        return np.full_like(v, new_min)
    return (v - v.min()) / span * (new_max - new_min) + new_min


def next_power_of_2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (int(n - 1).bit_length())


def roulette_wheel(weights, rng: Optional[np.random.Generator] = None) -> int:
    """Fitness-proportional random index selection."""
    w = np.asarray(weights, np.float64)
    if (w < 0).any() or w.sum() <= 0:
        raise ValueError("weights must be non-negative with positive sum")
    rng = rng or np.random.default_rng()
    return int(rng.choice(len(w), p=w / w.sum()))


def discretize(value: float, lo: float, hi: float, bins: int) -> int:
    """Map a value in [lo, hi] to a bin index in [0, bins)."""
    if hi <= lo:
        raise ValueError("hi must exceed lo")
    frac = (min(max(value, lo), hi) - lo) / (hi - lo)
    return min(int(frac * bins), bins - 1)
