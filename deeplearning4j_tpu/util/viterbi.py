"""Viterbi decoding for label-sequence smoothing.

Mirror of reference util/Viterbi.java: an HMM decode over a noisy
sequence of observed labels, with a self-transition-favoring chain
(``metastability`` on the diagonal) and an emission model where the
observed label equals the true state with probability ``p_correct``.
Used to clean up per-timestep classifier outputs. Also exposes the
general log-space decode for arbitrary transition/emission matrices.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


def viterbi_decode(log_init: np.ndarray, log_trans: np.ndarray,
                   log_emit: np.ndarray) -> Tuple[float, np.ndarray]:
    """General Viterbi: ``log_init`` [S], ``log_trans`` [S, S] (from→to),
    ``log_emit`` [T, S] per-step observation log-likelihoods. Returns
    (best path log-prob, state sequence [T])."""
    T, S = log_emit.shape
    delta = log_init + log_emit[0]
    back = np.zeros((T, S), np.int64)
    for t in range(1, T):
        # scores[i, j] = delta[i] + log_trans[i, j]
        scores = delta[:, None] + log_trans
        back[t] = scores.argmax(axis=0)
        delta = scores.max(axis=0) + log_emit[t]
    path = np.zeros(T, np.int64)
    path[-1] = int(delta.argmax())
    for t in range(T - 2, -1, -1):
        path[t] = back[t + 1, path[t + 1]]
    return float(delta.max()), path


class Viterbi:
    """Label-sequence smoother (reference util/Viterbi.java semantics:
    sticky self-transitions + mostly-correct observations)."""

    def __init__(self, num_states: int, meta_stability: float = 0.9,
                 p_correct: float = 0.99):
        if num_states < 2:
            raise ValueError("need >= 2 states")
        self.num_states = num_states
        s = num_states
        off_t = (1.0 - meta_stability) / (s - 1)
        self.log_trans = np.full((s, s), np.log(off_t))
        np.fill_diagonal(self.log_trans, np.log(meta_stability))
        off_e = (1.0 - p_correct) / (s - 1)
        self._log_emit_correct = np.log(p_correct)
        self._log_emit_wrong = np.log(off_e)
        self.log_init = np.full(s, -np.log(s))

    def decode(self, observed: Sequence[int]) -> Tuple[float, np.ndarray]:
        """Observed label sequence → (log-prob, smoothed sequence)."""
        obs = np.asarray(observed, np.int64)
        if len(obs) and (obs.min() < 0 or obs.max() >= self.num_states):
            raise ValueError(
                f"observed labels outside [0, {self.num_states})")
        T = len(obs)
        log_emit = np.full((T, self.num_states), self._log_emit_wrong)
        log_emit[np.arange(T), obs] = self._log_emit_correct
        return viterbi_decode(self.log_init, self.log_trans, log_emit)
