"""Sliding-window sub-matrix extraction (reference
util/MovingWindowMatrix.java): all window_rows x window_cols sub-matrices
of a 2-D matrix, optionally augmented with 90-degree rotations.
"""

from __future__ import annotations

from typing import List

import numpy as np


def moving_window_matrices(
    matrix: np.ndarray,
    window_rows: int,
    window_cols: int,
    rotate: int = 0,
) -> List[np.ndarray]:
    """Every aligned window of the given shape (stride = window size,
    matching the reference's non-overlapping windows), each followed by
    ``rotate`` extra 90-degree rotations of itself."""
    mat = np.asarray(matrix)
    r, c = mat.shape
    if window_rows > r or window_cols > c:
        raise ValueError(
            f"window {window_rows}x{window_cols} larger than matrix {r}x{c}"
        )
    out: List[np.ndarray] = []
    for i in range(0, r - window_rows + 1, window_rows):
        for j in range(0, c - window_cols + 1, window_cols):
            w = mat[i:i + window_rows, j:j + window_cols]
            out.append(w)
            cur = w
            for _ in range(rotate):
                cur = np.rot90(cur)
                out.append(cur)
    return out
