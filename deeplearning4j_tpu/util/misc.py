"""Small utility classes completing the reference util/berkeley surface.

TPU-native equivalents of reference utilities (reference
deeplearning4j-core/.../util/{SetUtils,ArchiveUtils,SummaryStatistics,
FingerPrintKeyer,StringCluster,StringGrid}.java, berkeley/SloppyMath.java,
rbm/MultiDimensionalMap-style keyed maps used by the recursive nets).
Host-side helpers — no device work.
"""

from __future__ import annotations

import math
import os
import re
import unicodedata
from collections import defaultdict
from typing import Dict, Hashable, Iterable, List, Optional, Set, Tuple

import numpy as np


class SetUtils:
    """Set algebra helpers (reference SetUtils.java)."""

    @staticmethod
    def intersection(a: Iterable, b: Iterable) -> Set:
        return set(a) & set(b)

    @staticmethod
    def union(a: Iterable, b: Iterable) -> Set:
        return set(a) | set(b)

    @staticmethod
    def difference(a: Iterable, b: Iterable) -> Set:
        return set(a) - set(b)

    @staticmethod
    def intersection_p(a: Set, b: Iterable) -> bool:
        return any(x in a for x in b)


class SloppyMath:
    """Numerically-safe log-space arithmetic (reference berkeley
    SloppyMath.java)."""

    LOG_TOLERANCE = 30.0

    @staticmethod
    def log_add(lx: float, ly: float) -> float:
        if lx == -math.inf:
            return ly
        if ly == -math.inf:
            return lx
        hi, lo = (lx, ly) if lx > ly else (ly, lx)
        if hi - lo > SloppyMath.LOG_TOLERANCE:
            return hi
        return hi + math.log1p(math.exp(lo - hi))

    @staticmethod
    def log_add_all(values: Iterable[float]) -> float:
        out = -math.inf
        for v in values:
            out = SloppyMath.log_add(out, v)
        return out

    @staticmethod
    def sloppy_exp(x: float) -> float:
        if x > 50.0:
            return math.inf
        if x < -50.0:
            return 0.0
        return math.exp(x)


class SummaryStatistics:
    """Streaming min/max/mean/variance (reference SummaryStatistics.java,
    Welford accumulation instead of sum-of-squares)."""

    def __init__(self):
        self.n = 0
        self._mean = 0.0
        self._m2 = 0.0
        self.min = math.inf
        self.max = -math.inf

    def add(self, value: float) -> None:
        v = float(value)
        self.n += 1
        d = v - self._mean
        self._mean += d / self.n
        self._m2 += d * (v - self._mean)
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def add_all(self, values) -> "SummaryStatistics":
        for v in np.asarray(values).ravel():
            self.add(float(v))
        return self

    @property
    def mean(self) -> float:
        return self._mean if self.n else float("nan")

    @property
    def variance(self) -> float:
        return self._m2 / (self.n - 1) if self.n > 1 else 0.0

    @property
    def standard_deviation(self) -> float:
        return math.sqrt(self.variance)

    @staticmethod
    def summary_stats(values) -> "SummaryStatistics":
        return SummaryStatistics().add_all(values)

    def __repr__(self) -> str:
        return (f"SummaryStatistics(n={self.n}, mean={self.mean:.6g}, "
                f"min={self.min:.6g}, max={self.max:.6g}, "
                f"std={self.standard_deviation:.6g})")


class MultiDimensionalMap:
    """Pair-keyed map (reference rnn MultiDimensionalMap<K1,K2,V>)."""

    def __init__(self):
        self._d: Dict[Tuple[Hashable, Hashable], object] = {}

    def put(self, k1, k2, value) -> None:
        self._d[(k1, k2)] = value

    def get(self, k1, k2, default=None):
        return self._d.get((k1, k2), default)

    def contains(self, k1, k2) -> bool:
        return (k1, k2) in self._d

    def remove(self, k1, k2):
        return self._d.pop((k1, k2), None)

    def key_set(self) -> Set[Tuple[Hashable, Hashable]]:
        return set(self._d)

    def values(self):
        return list(self._d.values())

    def size(self) -> int:
        return len(self._d)

    def __len__(self) -> int:
        return len(self._d)


class MultiDimensionalSet:
    """Pair set (reference MultiDimensionalSet<K1,K2>)."""

    def __init__(self):
        self._s: Set[Tuple[Hashable, Hashable]] = set()

    def add(self, k1, k2) -> None:
        self._s.add((k1, k2))

    def contains(self, k1, k2) -> bool:
        return (k1, k2) in self._s

    def remove(self, k1, k2) -> None:
        self._s.discard((k1, k2))

    def size(self) -> int:
        return len(self._s)

    def __len__(self) -> int:
        return len(self._s)


class FingerPrintKeyer:
    """Canonical key for fuzzy string matching (reference
    FingerPrintKeyer.java, OpenRefine fingerprint): strip accents and
    punctuation, lowercase, sort unique tokens."""

    def key(self, s: str) -> str:
        s = unicodedata.normalize("NFKD", s)
        s = "".join(c for c in s if not unicodedata.combining(c))
        s = re.sub(r"[^\w\s]", "", s.lower()).strip()
        return " ".join(sorted(set(s.split())))


class StringCluster:
    """Cluster strings by fingerprint key; clusters sorted by size
    (reference StringCluster.java)."""

    def __init__(self, items: Iterable[str]):
        keyer = FingerPrintKeyer()
        groups: Dict[str, Dict[str, int]] = defaultdict(dict)
        for s in items:
            k = keyer.key(s)
            groups[k][s] = groups[k].get(s, 0) + 1
        self.clusters: List[Dict[str, int]] = sorted(
            groups.values(),
            key=lambda g: (-sum(g.values()), sorted(g)),
        )

    def get_clusters(self) -> List[Dict[str, int]]:
        return self.clusters


class StringGrid:
    """Grid of string rows with fuzzy row dedup by column fingerprint
    (reference StringGrid.java)."""

    def __init__(self, sep: str, rows: Iterable[List[str]] = ()):
        self.sep = sep
        self.rows: List[List[str]] = [list(r) for r in rows]
        if self.rows:
            n = len(self.rows[0])
            if any(len(r) != n for r in self.rows):
                raise ValueError("ragged rows")

    @classmethod
    def from_lines(cls, sep: str, lines: Iterable[str]) -> "StringGrid":
        return cls(sep, [line.split(sep) for line in lines])

    def num_rows(self) -> int:
        return len(self.rows)

    def get_column(self, col: int) -> List[str]:
        return [r[col] for r in self.rows]

    def get_row(self, i: int) -> List[str]:
        return list(self.rows[i])

    def filter_rows_by_column(self, col: int,
                              allowed: Iterable[str]) -> "StringGrid":
        ok = set(allowed)
        return StringGrid(self.sep,
                          [r for r in self.rows if r[col] in ok])

    def dedup_by_column_fingerprint(self, col: int) -> None:
        keyer = FingerPrintKeyer()
        seen: Set[str] = set()
        kept = []
        for r in self.rows:
            k = keyer.key(r[col])
            if k in seen:
                continue
            seen.add(k)
            kept.append(r)
        self.rows = kept


class ArchiveUtils:
    """Extract .zip/.tar.gz/.tgz/.tar/.gz archives (reference
    ArchiveUtils.java, used by dataset fetchers)."""

    @staticmethod
    def unzip_file_to(archive: str, dest: str) -> None:
        os.makedirs(dest, exist_ok=True)
        root = os.path.realpath(dest)

        def _check(member: str) -> None:
            target = os.path.realpath(os.path.join(dest, member))
            if target != root and not target.startswith(root + os.sep):
                raise ValueError(f"unsafe archive member path: {member}")

        if archive.endswith(".zip"):
            import zipfile

            with zipfile.ZipFile(archive) as z:
                for m in z.namelist():
                    _check(m)
                z.extractall(dest)
        elif archive.endswith((".tar.gz", ".tgz", ".tar")):
            import tarfile

            mode = "r:gz" if archive.endswith(("gz", "tgz")) else "r"
            with tarfile.open(archive, mode) as t:
                for m in t.getmembers():
                    _check(m.name)
                try:
                    t.extractall(dest, filter="data")
                except TypeError:  # Python < 3.12 without filter=
                    t.extractall(dest)
        elif archive.endswith(".gz"):
            import gzip
            import shutil

            out = os.path.join(
                dest, os.path.basename(archive)[:-3] or "out")
            with gzip.open(archive, "rb") as fin, open(out, "wb") as fout:
                shutil.copyfileobj(fin, fout)
        else:
            raise ValueError(f"unknown archive format: {archive}")
