"""Time-series shape utilities.

TPU-native equivalent of reference util/TimeSeriesUtils.java (3d<->2d
reshapes used around masked RNN losses) plus the variable-length padding
the reference handles via per-batch masks (TestVariableLengthTS pattern):
padding to a static max length + mask is THE jit-friendly form — dynamic
lengths would retrigger XLA compilation per shape.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def reshape_3d_to_2d(x: np.ndarray) -> np.ndarray:
    """[N, C, T] activations -> [N*T, C] rows (reference
    TimeSeriesUtils.reshape3dTo2d: time-distributed loss form)."""
    x = np.asarray(x)
    if x.ndim != 3:
        raise ValueError(f"expected [N, C, T], got shape {x.shape}")
    n, c, t = x.shape
    return np.transpose(x, (0, 2, 1)).reshape(n * t, c)


def reshape_2d_to_3d(x: np.ndarray, batch: int) -> np.ndarray:
    """[N*T, C] rows -> [N, C, T] (reference reshape2dTo3d)."""
    x = np.asarray(x)
    if x.ndim != 2 or x.shape[0] % batch:
        raise ValueError(
            f"rows {x.shape} not divisible into batch {batch}")
    t = x.shape[0] // batch
    return np.transpose(x.reshape(batch, t, x.shape[1]), (0, 2, 1))


def reshape_mask_to_vector(mask: np.ndarray) -> np.ndarray:
    """[N, T] time mask -> [N*T] row mask, aligned with
    reshape_3d_to_2d's row order (reference
    reshapeTimeSeriesMaskToVector)."""
    mask = np.asarray(mask)
    if mask.ndim != 2:
        raise ValueError(f"expected [N, T], got {mask.shape}")
    return mask.reshape(-1)


def reshape_vector_to_mask(vec: np.ndarray, batch: int) -> np.ndarray:
    """[N*T] -> [N, T] (reference reshapeVectorToTimeSeriesMask)."""
    vec = np.asarray(vec)
    if vec.ndim != 1 or vec.shape[0] % batch:
        raise ValueError(f"vector {vec.shape} not divisible by {batch}")
    return vec.reshape(batch, -1)


def moving_average(values, n: int) -> np.ndarray:
    """Simple trailing moving average of a 1-D series (reference
    TimeSeriesUtils.movingAverage): output[i] = mean(values[i-n+1..i]),
    defined from index n-1 on (length len(values)-n+1)."""
    v = np.asarray(values, np.float64)
    if n < 1 or n > len(v):
        raise ValueError(f"window {n} invalid for length {len(v)}")
    c = np.cumsum(np.concatenate([[0.0], v]))
    return (c[n:] - c[:-n]) / n


def pad_sequences(
    sequences: Sequence[np.ndarray],
    max_length: int = 0,
    pad_value: float = 0.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Stack variable-length [C, T_i] sequences into a static
    ([N, C, T_max], [N, T_max] mask) pair — the jit-stable encoding of
    variable lengths (masks flow through fit/eval per SURVEY §5.7; the
    reference builds these masks by hand in TestVariableLengthTS)."""
    seqs: List[np.ndarray] = [np.asarray(s) for s in sequences]
    if not seqs:
        raise ValueError("no sequences")
    if any(s.ndim != 2 for s in seqs):
        raise ValueError("each sequence must be [C, T_i]")
    c = seqs[0].shape[0]
    if any(s.shape[0] != c for s in seqs):
        raise ValueError("inconsistent channel counts")
    t_max = max_length or max(s.shape[1] for s in seqs)
    out = np.full((len(seqs), c, t_max), pad_value, seqs[0].dtype)
    mask = np.zeros((len(seqs), t_max), np.float32)
    for i, s in enumerate(seqs):
        t = min(s.shape[1], t_max)
        out[i, :, :t] = s[:, :t]
        mask[i, :t] = 1.0
    return out, mask
