"""Counting/priority collections + thread-parallel helpers.

Capability mirror of the reference's vendored Berkeley-NLP utilities
(berkeley/{Counter,CounterMap,PriorityQueue,Pair,Triple}.java, SURVEY.md
§2.6) and the Akka thread-parallelism helper
(scaleout-akka/.../parallel/Parallelization.java:37). Python has stdlib
near-equivalents (collections.Counter, heapq, tuples); these classes keep
the reference's richer API surface — argmax, normalization, conditional
counts, peek/priority introspection — that callers like vocab
construction, GloVe co-occurrence and DeepWalk rely on, without forcing
each call site to re-derive it.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import (Callable, Dict, Generic, Iterable, Iterator, List,
                    Mapping, NamedTuple, Optional, Sequence, Tuple,
                    TypeVar)

K = TypeVar("K")
K2 = TypeVar("K2")
T = TypeVar("T")
R = TypeVar("R")


class Pair(NamedTuple):
    first: object
    second: object


class Triple(NamedTuple):
    first: object
    second: object
    third: object


class Counter(Generic[K]):
    """Map key -> float count with argmax/normalize/sample conveniences."""

    def __init__(self, initial: Optional[Iterable[K]] = None):
        self._counts: Dict[K, float] = {}
        if isinstance(initial, Mapping):
            for k, v in initial.items():
                self.increment_count(k, float(v))
        elif initial is not None:
            for k in initial:
                self.increment_count(k, 1.0)

    def get_count(self, key: K) -> float:
        return self._counts.get(key, 0.0)

    def set_count(self, key: K, count: float) -> None:
        self._counts[key] = float(count)

    def increment_count(self, key: K, amount: float = 1.0) -> float:
        c = self._counts.get(key, 0.0) + amount
        self._counts[key] = c
        return c

    def increment_all(self, other: "Counter[K]", scale: float = 1.0) -> None:
        for k, v in other.items():
            self.increment_count(k, v * scale)

    def remove_key(self, key: K) -> float:
        return self._counts.pop(key, 0.0)

    def contains_key(self, key: K) -> bool:
        return key in self._counts

    def key_set(self):
        return self._counts.keys()

    def items(self):
        return self._counts.items()

    def size(self) -> int:
        return len(self._counts)

    def is_empty(self) -> bool:
        return not self._counts

    def total_count(self) -> float:
        return float(sum(self._counts.values()))

    def arg_max(self) -> Optional[K]:
        if not self._counts:
            return None
        return max(self._counts, key=self._counts.get)

    def max_count(self) -> float:
        return max(self._counts.values()) if self._counts else 0.0

    def normalize(self) -> None:
        total = self.total_count()
        if total != 0.0:
            for k in self._counts:
                self._counts[k] /= total

    def scale(self, factor: float) -> None:
        for k in self._counts:
            self._counts[k] *= factor

    def keep_top_n_keys(self, n: int) -> None:
        if len(self._counts) <= n:
            return
        keep = heapq.nlargest(n, self._counts, key=self._counts.get)
        self._counts = {k: self._counts[k] for k in keep}

    def sorted_keys(self, descending: bool = True) -> List[K]:
        return sorted(self._counts, key=self._counts.get,
                      reverse=descending)

    def as_priority_queue(self) -> "PriorityQueue[K]":
        pq: PriorityQueue[K] = PriorityQueue()
        for k, v in self._counts.items():
            pq.put(k, v)
        return pq

    def __iter__(self) -> Iterator[K]:
        return iter(self._counts)

    def __len__(self) -> int:
        return len(self._counts)

    def __repr__(self) -> str:
        top = ", ".join(
            f"{k}:{self._counts[k]:g}" for k in self.sorted_keys()[:10])
        return f"Counter[{top}]"


class CounterMap(Generic[K, K2]):
    """Two-level conditional counts: (key, sub-key) -> float."""

    def __init__(self):
        self._maps: Dict[K, Counter[K2]] = {}

    def get_counter(self, key: K) -> Counter[K2]:
        c = self._maps.get(key)
        if c is None:
            c = Counter()
            self._maps[key] = c
        return c

    def get_count(self, key: K, sub: K2) -> float:
        c = self._maps.get(key)
        return c.get_count(sub) if c is not None else 0.0

    def set_count(self, key: K, sub: K2, count: float) -> None:
        self.get_counter(key).set_count(sub, count)

    def increment_count(self, key: K, sub: K2,
                        amount: float = 1.0) -> None:
        self.get_counter(key).increment_count(sub, amount)

    def contains_key(self, key: K) -> bool:
        return key in self._maps

    def key_set(self):
        return self._maps.keys()

    def total_count(self) -> float:
        return float(sum(c.total_count() for c in self._maps.values()))

    def total_size(self) -> int:
        return sum(c.size() for c in self._maps.values())

    def normalize(self) -> None:
        """Row-normalize: each inner counter becomes a distribution."""
        for c in self._maps.values():
            c.normalize()

    def __iter__(self) -> Iterator[K]:
        return iter(self._maps)

    def __len__(self) -> int:
        return len(self._maps)


class PriorityQueue(Generic[T]):
    """Max-priority queue with stable ordering and lazy deletion.

    Mirrors berkeley/PriorityQueue.java (peek/getPriority/put/next);
    built on heapq with negated priorities.
    """

    def __init__(self):
        self._heap: List[Tuple[float, int, T]] = []
        self._tie = itertools.count()

    def put(self, item: T, priority: float) -> None:
        heapq.heappush(self._heap, (-priority, next(self._tie), item))

    def peek(self) -> T:
        if not self._heap:
            raise IndexError("empty priority queue")
        return self._heap[0][2]

    def get_priority(self) -> float:
        if not self._heap:
            raise IndexError("empty priority queue")
        return -self._heap[0][0]

    def next(self) -> T:
        if not self._heap:
            raise IndexError("empty priority queue")
        return heapq.heappop(self._heap)[2]

    def is_empty(self) -> bool:
        return not self._heap

    def size(self) -> int:
        return len(self._heap)

    def __len__(self) -> int:
        return len(self._heap)

    def __iter__(self) -> Iterator[T]:
        """Drains in priority order (like the reference's iterator)."""
        while self._heap:
            yield self.next()


# ---------------------------------------------------------------------
# Thread-level parallelism helper (Parallelization.java equivalent).
# Used host-side only — device math goes through jit/pjit, but vocab
# scans, random-walk generation and co-occurrence counting are
# CPU-bound iterator work where a thread pool is the right tool.
# ---------------------------------------------------------------------

def run_in_parallel(tasks: Sequence[Callable[[], R]],
                    max_workers: Optional[int] = None) -> List[R]:
    """Run independent thunks on a thread pool; results in input order.

    Reference ``Parallelization.runInParallel`` (Parallelization.java:37)
    dispatched Runnables on an Akka dispatcher; here a plain executor.
    Raises the first exception encountered, like the reference's
    fail-fast await.
    """
    if not tasks:
        return []
    with ThreadPoolExecutor(max_workers=max_workers) as ex:
        return list(ex.map(lambda f: f(), tasks))


def iterate_in_parallel(items: Iterable[T], fn: Callable[[T], R],
                        max_workers: Optional[int] = None) -> List[R]:
    """Apply ``fn`` to each item concurrently; results in input order."""
    with ThreadPoolExecutor(max_workers=max_workers) as ex:
        return list(ex.map(fn, items))


class AtomicDouble:
    """Lock-guarded accumulator for cross-thread score/count merging."""

    def __init__(self, value: float = 0.0):
        self._value = float(value)
        self._lock = threading.Lock()

    def add_and_get(self, delta: float) -> float:
        with self._lock:
            self._value += delta
            return self._value

    def get(self) -> float:
        with self._lock:
            return self._value

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
