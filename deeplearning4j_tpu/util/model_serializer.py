"""Single-file model serialization.

TPU-native counterpart of the reference checkpoint triple — (conf JSON,
flat params, serialized updater) — written by
earlystopping/saver/LocalFileModelSaver.java:76-86 and restored via the
``MultiLayerNetwork(String conf, INDArray params)`` ctor
(nn/multilayer/MultiLayerNetwork.java:107). Here the triple is packed into
ONE zip archive so a model travels as a single artifact:

    model.zip
    ├── type                conf-class marker (multilayer | graph)
    ├── conf.json           configuration (the wire format, SURVEY.md §5.6)
    ├── params.npz          param pytree, keys "layer␟name" flattened
    └── extras.pkl          updater state + layer state + iteration

Arrays go through numpy ``.npz`` (portable, no pickle needed for params);
only updater/layer state uses pickle because its pytree structure is
heterogeneous.

This module is the SINGLE serialization implementation: network
``save/load`` methods and the CheckpointManager both delegate here
(``snapshot``/``write_snapshot`` split the host-copy step from the disk
write so async checkpointing can snapshot on the training thread and
write on a background one).
"""

from __future__ import annotations

import io
import os
import pickle
import zipfile
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

_SEP = "␟"  # unit-separator-ish key joiner, never in param names


def _flatten(tree: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{_SEP}{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split(_SEP)
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(v)
    return out


def _merge_into(dst: Dict[str, Any], src: Dict[str, Any]) -> Dict[str, Any]:
    """Overlay loaded leaves onto a freshly-init'd tree. Param-less layers
    (e.g. Subsampling) have empty dicts that npz flattening drops; merging
    keeps their keys so the forward pass still finds every layer."""
    out = dict(dst)
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge_into(out[k], v)
        else:
            out[k] = v
    return out


def snapshot(net) -> Dict[str, Any]:
    """Host-side copy of everything needed to reconstruct ``net``.
    Cheap device→host transfer on the caller's thread; the result is
    immutable w.r.t. further training steps."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net.init()
    return {
        "kind": (
            "multilayer" if isinstance(net, MultiLayerNetwork) else "graph"
        ),
        "conf_json": net.conf.to_json(),
        "params": jax.tree.map(np.asarray, net.params),
        "updater_state": jax.tree.map(np.asarray, net.updater_state),
        "state": jax.tree.map(np.asarray, net.state),
        "iteration": net.iteration,
    }


def write_snapshot(snap: Dict[str, Any], path: str) -> None:
    """Write a snapshot dict to ``path`` as one zip, atomically."""
    buf = io.BytesIO()
    np.savez(buf, **_flatten(snap["params"]))
    extras = {
        "updater_state": snap["updater_state"],
        "state": snap["state"],
        "iteration": snap["iteration"],
    }
    tmp = path + ".tmp"
    with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("type", snap["kind"])
        z.writestr("conf.json", snap["conf_json"])
        z.writestr("params.npz", buf.getvalue())
        z.writestr("extras.pkl", pickle.dumps(extras))
    os.replace(tmp, path)  # atomic commit: no torn checkpoints on crash


def write_model(net, path: str) -> None:
    """Serialize a MultiLayerNetwork or ComputationGraph to one zip file."""
    write_snapshot(snapshot(net), path)


def restore_model(path: str):
    """Load a model zip back into the right network class."""
    with zipfile.ZipFile(path) as z:
        kind = z.read("type").decode()
        conf_json = z.read("conf.json").decode()
        npz = np.load(io.BytesIO(z.read("params.npz")))
        params = _unflatten({k: npz[k] for k in npz.files})
        extras = pickle.loads(z.read("extras.pkl"))

    if kind == "multilayer":
        from deeplearning4j_tpu.nn.conf.multi_layer import (
            MultiLayerConfiguration,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(conf_json)
        ).init()
    else:
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        net = ComputationGraph(
            ComputationGraphConfiguration.from_json(conf_json)
        ).init()

    net.params = _merge_into(net.params, params)
    net.updater_state = jax.tree.map(jnp.asarray, extras["updater_state"])
    net.state = jax.tree.map(jnp.asarray, extras["state"])
    net.iteration = int(extras["iteration"])
    return net
