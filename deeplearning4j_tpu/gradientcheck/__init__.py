"""Gradient checking (finite differences vs analytic autodiff)."""

from deeplearning4j_tpu.gradientcheck.gradient_check_util import check_gradients
