"""Central finite-difference gradient checker.

Mirror of reference gradientcheck/GradientCheckUtil.java:48 (217 LoC):
perturb each parameter +-epsilon, compare the centered difference of the
score against the analytic gradient. In the reference the analytic side is
hand-written backprop; here it is ``jax.grad`` of the same jitted loss, so
the check validates loss/regularization/masking wiring rather than chain
rules — the same role it plays in the reference's test suite
(SURVEY.md §4 "Math/gradient correctness").

Double precision is enabled per-call via ``jax.enable_x64`` like the
reference's requirement that gradient checks run in double precision.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.util.jax_compat import enable_x64


def check_gradients(
    net,
    ds,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-3,
    min_abs_error: float = 1e-8,
    max_params_to_check: Optional[int] = None,
    print_results: bool = False,
    seed: int = 0,
) -> bool:
    """True iff all (sampled) parameters pass the relative-error gate.

    rel_err = |analytic - numeric| / (|analytic| + |numeric|), skipped when
    both magnitudes are below ``min_abs_error`` — same gating as the
    reference's GradientCheckUtil.
    """
    from jax.flatten_util import ravel_pytree

    net.init()
    with enable_x64(True):
        params64 = jax.tree.map(
            lambda p: jnp.asarray(np.asarray(p), jnp.float64), net.params
        )
        state64 = jax.tree.map(
            lambda p: jnp.asarray(np.asarray(p), jnp.float64), net.state
        )
        feats = jnp.asarray(np.asarray(ds.features), jnp.float64)
        labels = jnp.asarray(np.asarray(ds.labels), jnp.float64)
        fm = (
            None
            if ds.features_mask is None
            else jnp.asarray(np.asarray(ds.features_mask), jnp.float64)
        )
        lm = (
            None
            if ds.labels_mask is None
            else jnp.asarray(np.asarray(ds.labels_mask), jnp.float64)
        )

        flat0, unravel = ravel_pytree(params64)

        def loss_flat(flat):
            params = unravel(flat)
            # Deterministic loss: no rng -> no dropout/sampling.
            score, _ = net._loss_fn(
                params, state64, None, feats, labels, fm, lm
            )
            return score

        loss_jit = jax.jit(loss_flat)
        analytic = np.asarray(jax.jit(jax.grad(loss_flat))(flat0))
        flat0 = np.asarray(flat0)

        n = flat0.shape[0]
        if max_params_to_check is not None and max_params_to_check < n:
            rng = np.random.default_rng(seed)
            idxs = rng.choice(n, size=max_params_to_check, replace=False)
        else:
            idxs = np.arange(n)

        n_pass = n_fail = 0
        max_err = 0.0
        for i in idxs:
            e = np.zeros_like(flat0)
            e[i] = epsilon
            s_plus = float(loss_jit(jnp.asarray(flat0 + e)))
            s_minus = float(loss_jit(jnp.asarray(flat0 - e)))
            numeric = (s_plus - s_minus) / (2.0 * epsilon)
            a = float(analytic[i])
            denom = abs(a) + abs(numeric)
            if denom < min_abs_error:
                n_pass += 1
                continue
            rel = abs(a - numeric) / denom
            max_err = max(max_err, rel)
            if rel > max_rel_error:
                n_fail += 1
                if print_results:
                    print(
                        f"param[{i}] FAIL rel={rel:.3e} "
                        f"analytic={a:.6e} numeric={numeric:.6e}"
                    )
            else:
                n_pass += 1
        if print_results:
            print(
                f"Gradient check: {n_pass} passed, {n_fail} failed, "
                f"max rel err {max_err:.3e}"
            )
        return n_fail == 0
