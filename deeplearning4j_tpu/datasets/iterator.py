"""DataSetIterator SPI + combinators.

Mirror of reference datasets/iterator/** — DataSetIterator.java:54 contract
(next(num), totalExamples, inputColumns, reset, preprocessor hook),
AsyncDataSetIterator (background prefetch thread + blocking queue),
MultipleEpochsIterator, SamplingDataSetIterator, ListDataSetIterator, and
the TestDataSetIterator wrapper (datasets/test/TestDataSetIterator.java).

Iterators are Python iterables of :class:`DataSet`; ``reset()`` rewinds.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Base contract (reference DataSetIterator.java:54)."""

    def __init__(self, batch_size: int = 10):
        self.batch = batch_size
        self.preprocessor: Optional[Callable[[DataSet], DataSet]] = None

    # -- iteration ------------------------------------------------------
    def __iter__(self) -> "DataSetIterator":
        self.reset()
        return self

    def __next__(self) -> DataSet:
        ds = self.next()
        if ds is None:
            raise StopIteration
        return ds

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    # -- metadata -------------------------------------------------------
    def total_examples(self) -> int:
        raise NotImplementedError

    def input_columns(self) -> int:
        raise NotImplementedError

    def total_outcomes(self) -> int:
        raise NotImplementedError

    def set_preprocessor(self, fn: Callable[[DataSet], DataSet]) -> None:
        self.preprocessor = fn

    def _post(self, ds: Optional[DataSet]) -> Optional[DataSet]:
        if ds is not None and self.preprocessor is not None:
            ds = self.preprocessor(ds)
        return ds

    # -- resumable position (improvement over the reference, which never
    # checkpoints iterator position — SURVEY.md §5.4) -------------------
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict) -> None:
        pass

    def skip_batches(self, n: int) -> int:
        """Advance past ``n`` batches without delivering them — the
        replay primitive async wrappers use to restore an exactly-once
        position (native_rt/iterator.py): rewind the base to a known
        point, then skip what the consumer already trained on.
        Default reads and discards; iterators with a seekable cursor
        override with O(1) arithmetic (datasets/streaming.py). Returns
        the number of batches actually skipped (short at end of
        data)."""
        skipped = 0
        for _ in range(int(n)):
            if self.next() is None:
                break
            skipped += 1
        return skipped


class BaseDataSetIterator(DataSetIterator):
    """Cursor-over-in-memory-arrays base (reference BaseDatasetIterator +
    fetcher split)."""

    def __init__(self, batch_size: int, dataset: DataSet):
        super().__init__(batch_size)
        self._data = dataset
        self._cursor = 0

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        n = num or self.batch
        if self._cursor >= self._data.num_examples():
            return None
        ds = self._data.get_range(
            self._cursor, min(self._cursor + n, self._data.num_examples())
        )
        self._cursor += n
        return self._post(ds)

    def reset(self) -> None:
        self._cursor = 0

    def state_dict(self) -> dict:
        return {"cursor": self._cursor}

    def load_state_dict(self, state: dict) -> None:
        self._cursor = int(state["cursor"])

    def total_examples(self) -> int:
        return self._data.num_examples()

    def input_columns(self) -> int:
        return self._data.num_inputs()

    def total_outcomes(self) -> int:
        return self._data.num_outcomes()


class ListDataSetIterator(DataSetIterator):
    """Iterate a pre-built list of DataSets (reference
    ListDataSetIterator)."""

    def __init__(self, datasets: Iterable[DataSet], batch_size: int = 0):
        datasets = list(datasets)
        if batch_size and batch_size > 0:
            merged = DataSet.merge(datasets)
            datasets = merged.batch_by(batch_size)
        super().__init__(batch_size or (len(datasets) and datasets[0].num_examples()) or 1)
        self._list: List[DataSet] = datasets
        self._idx = 0

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        if self._idx >= len(self._list):
            return None
        ds = self._list[self._idx]
        self._idx += 1
        return self._post(ds)

    def reset(self) -> None:
        self._idx = 0

    def state_dict(self) -> dict:
        return {"idx": self._idx}

    def load_state_dict(self, state: dict) -> None:
        self._idx = int(state["idx"])

    def total_examples(self) -> int:
        return sum(d.num_examples() for d in self._list)

    def input_columns(self) -> int:
        return self._list[0].num_inputs()

    def total_outcomes(self) -> int:
        return self._list[0].num_outcomes()


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded blocking queue (reference
    AsyncDataSetIterator). Overlaps host-side batch preparation with device
    compute — the 2015 pattern that anticipates tf.data/grain prefetch."""

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 4):
        super().__init__(base.batch)
        self._base = base
        self._queue_size = queue_size
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # Serializes base.next() against state_dict() snapshots so a
        # checkpoint never observes the base iterator mid-advance.
        self._base_lock = threading.Lock()

    def _start(self, reset: bool = True) -> None:
        self._stop()
        if reset:
            self._base.reset()
        # The queue and stop-event are bound into the worker closure, so a
        # stale worker from before a reset() can never feed the new epoch's
        # queue. (It does still share self._base: a worker surviving the
        # join timeout — base.next() blocked >5s — could race the new
        # worker's cursor, a limitation shared with the reference's
        # AsyncDataSetIterator thread shutdown.)
        q: queue.Queue = queue.Queue(maxsize=self._queue_size)
        stop = threading.Event()
        self._queue = q
        self._stop_event = stop
        self._error = None

        def worker():
            try:
                while not stop.is_set():
                    with self._base_lock:
                        ds = self._base.next()
                    if ds is None:
                        break
                    while not stop.is_set():
                        try:
                            q.put(ds, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            except BaseException as e:  # surfaced on the consumer side
                self._error = e
            finally:
                # Deliver the sentinel unless we were told to stop (in which
                # case the consumer is draining, not reading).
                while not stop.is_set():
                    try:
                        q.put(self._SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def _stop(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._stop_event.set()
            # Drain so a producer blocked on put() can observe the event.
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)
        self._thread = None
        self._queue = None

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        if self._queue is None:
            self._start()
        item = self._queue.get()
        if item is self._SENTINEL:
            self._queue = None
            self._thread = None
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            return None
        return self._post(item)

    def reset(self) -> None:
        self._start()

    def state_dict(self) -> dict:
        # Prefetched-but-unconsumed batches count as consumed: resume
        # position is the base cursor, which is at most queue_size batches
        # ahead of the consumer. The lock guarantees the snapshot is
        # internally consistent (never mid-next()).
        with self._base_lock:
            return {"base": self._base.state_dict()}

    def load_state_dict(self, state: dict) -> None:
        self._stop()
        self._base.load_state_dict(state["base"])
        self._start(reset=False)

    def total_examples(self) -> int:
        return self._base.total_examples()

    def input_columns(self) -> int:
        return self._base.input_columns()

    def total_outcomes(self) -> int:
        return self._base.total_outcomes()


class MultipleEpochsIterator(DataSetIterator):
    """Replay a base iterator N times (reference MultipleEpochsIterator)."""

    def __init__(self, num_epochs: int, base: DataSetIterator):
        super().__init__(base.batch)
        self._base = base
        self.num_epochs = num_epochs
        self._epoch = 0

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        ds = self._base.next(num)
        if ds is None:
            self._epoch += 1
            if self._epoch >= self.num_epochs:
                return None
            self._base.reset()
            ds = self._base.next(num)
        return self._post(ds)

    def reset(self) -> None:
        self._epoch = 0
        self._base.reset()

    def state_dict(self) -> dict:
        return {"epoch": self._epoch, "base": self._base.state_dict()}

    def load_state_dict(self, state: dict) -> None:
        self._epoch = int(state["epoch"])
        self._base.load_state_dict(state["base"])

    def total_examples(self) -> int:
        return self._base.total_examples() * self.num_epochs

    def input_columns(self) -> int:
        return self._base.input_columns()

    def total_outcomes(self) -> int:
        return self._base.total_outcomes()


class SamplingDataSetIterator(DataSetIterator):
    """Sample batches with replacement from one DataSet (reference
    SamplingDataSetIterator)."""

    def __init__(
        self,
        dataset: DataSet,
        batch_size: int,
        total_num_samples: int,
        seed: int = 123,
    ):
        super().__init__(batch_size)
        self._data = dataset
        self._total = total_num_samples
        self._given = 0
        self._rng = np.random.default_rng(seed)

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        n = num or self.batch
        if self._given >= self._total:
            return None
        idx = self._rng.integers(0, self._data.num_examples(), size=n)
        self._given += n
        return self._post(self._data.get_examples(idx))

    def reset(self) -> None:
        self._given = 0

    def state_dict(self) -> dict:
        return {"given": self._given, "rng": self._rng.bit_generator.state}

    def load_state_dict(self, state: dict) -> None:
        self._given = int(state["given"])
        self._rng.bit_generator.state = state["rng"]

    def total_examples(self) -> int:
        return self._total

    def input_columns(self) -> int:
        return self._data.num_inputs()

    def total_outcomes(self) -> int:
        return self._data.num_outcomes()


class TestDataSetIterator(DataSetIterator):
    """Wrapper that tracks call counts for iterator-contract tests
    (reference datasets/test/TestDataSetIterator.java)."""

    def __init__(self, base: DataSetIterator):
        super().__init__(base.batch)
        self._base = base
        self.next_calls = 0
        self.reset_calls = 0

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        self.next_calls += 1
        return self._post(self._base.next(num))

    def reset(self) -> None:
        self.reset_calls += 1
        self._base.reset()

    def state_dict(self) -> dict:
        return self._base.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self._base.load_state_dict(state)

    def total_examples(self) -> int:
        return self._base.total_examples()

    def input_columns(self) -> int:
        return self._base.input_columns()

    def total_outcomes(self) -> int:
        return self._base.total_outcomes()


class ReconstructionDataSetIterator(DataSetIterator):
    """Wraps an iterator, replacing labels with the features themselves —
    autoencoder/reconstruction training (reference datasets/iterator/
    ReconstructionDataSetIterator.java)."""

    def __init__(self, base: DataSetIterator):
        super().__init__(base.batch)
        self.base = base

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        ds = self.base.next(num)
        if ds is None:
            return None
        return self._post(
            DataSet(ds.features, ds.features,
                    ds.features_mask, ds.features_mask)
        )

    def reset(self) -> None:
        self.base.reset()

    def total_examples(self) -> int:
        return self.base.total_examples()

    def input_columns(self) -> int:
        return self.base.input_columns()

    def total_outcomes(self) -> int:
        return self.base.input_columns()

    def state_dict(self) -> dict:
        return self.base.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.base.load_state_dict(state)


class MovingWindowDataSetIterator(BaseDataSetIterator):
    """Slides a (rows x cols) window over each example matrix, emitting
    each window as one flattened feature row (reference
    datasets/iterator/MovingWindowBaseDataSetIterator.java backed by
    util/MovingWindowMatrix)."""

    def __init__(self, data: DataSet, window_rows: int, window_cols: int,
                 batch_size: int = 10, rotate: int = 0):
        from deeplearning4j_tpu.util.moving_window import (
            moving_window_matrices,
        )

        rows = []
        labels = []
        for i in range(data.num_examples()):
            mat = np.asarray(data.features[i])
            if mat.ndim == 1:
                side = int(np.sqrt(mat.shape[0]))
                if side * side != mat.shape[0]:
                    raise ValueError(
                        f"1-D feature rows must have square length to "
                        f"window over; got {mat.shape[0]}"
                    )
                mat = mat.reshape(side, side)
            elif mat.ndim != 2:
                raise ValueError(
                    f"windowing needs [rows, cols] examples; got "
                    f"shape {mat.shape}"
                )
            for w in moving_window_matrices(
                mat, window_rows, window_cols, rotate=rotate
            ):
                rows.append(w.reshape(-1))
                if data.labels is not None:
                    labels.append(data.labels[i])
        feats = np.asarray(rows, np.float32)
        labs = np.asarray(labels, np.float32) if labels else None
        super().__init__(batch_size, DataSet(feats, labs))

    def total_outcomes(self) -> int:
        return (
            0 if self._data.labels is None else self._data.num_outcomes()
        )
