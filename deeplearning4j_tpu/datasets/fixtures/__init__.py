"""Loaders for the bundled real-data fixtures (see README.md here).

Round-4 VERDICT item 8: accuracy gates should run on REAL data when
possible, synthetic fallback otherwise. These loaders provide three
real datasets on a zero-egress machine:

- ``mnist200_datasets()`` — 200 real MNIST digits (reference fixture
  mnist_first_200.txt, converted to IDX; reference parses the same
  pixels via datasets/mnist/MnistImageFile.java).
- ``raw_sentences()`` — 97k real English sentences (reference fixture
  raw_sentences.txt, the Word2VecTests corpus).
- ``digits_dataset()`` — sklearn's 1,797 real 8x8 handwritten digits.

Round-5 additions (real image pixels for the CNN/ingestion paths):

- ``lfw_fixture_dir()`` — a REAL LFW subset (4 photos, 2 people), the
  same fixture tree the reference bundles
  (dl4j-test-resources/src/main/resources/lfwtest).
- ``real_patches_cifar()`` — 200 real-photograph 32x32 patches in the
  exact CIFAR-10 binary on-disk format (see
  scripts/make_image_fixtures.py for provenance).
"""

from __future__ import annotations

import gzip
import os
from typing import List, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

_HERE = os.path.dirname(os.path.abspath(__file__))


def _split(feats, onehot, n_test, seed):
    """Seeded shuffle -> (train, test) DataSets (shared by every
    fixture loader so split semantics cannot diverge)."""
    order = np.random.default_rng(seed).permutation(feats.shape[0])
    tr, te = order[n_test:], order[:n_test]
    return (DataSet(feats[tr], onehot[tr]),
            DataSet(feats[te], onehot[te]))


def mnist200_datasets(n_test: int = 40, seed: int = 0
                      ) -> Tuple[DataSet, DataSet]:
    """(train, test) split of the 200 bundled REAL MNIST digits.

    Features are flat [N, 784] in [0, 1]; labels one-hot [N, 10]. The
    split is a seeded shuffle so train/test class mixes stay stable.
    """
    from deeplearning4j_tpu.datasets.mnist import read_idx

    imgs = read_idx(os.path.join(_HERE, "mnist200-images-idx3-ubyte.gz"))
    labels = read_idx(os.path.join(_HERE, "mnist200-labels-idx1-ubyte.gz"))
    n = imgs.shape[0]
    feats = imgs.reshape(n, -1).astype(np.float32) / 255.0
    onehot = np.eye(10, dtype=np.float32)[labels]
    return _split(feats, onehot, n_test, seed)


def lfw_fixture_dir() -> str:
    """Root of the bundled real LFW subset (class-per-subdirectory jpg
    tree: 2 people, 4 images) — feed to ``load_lfw(root=...)``."""
    return os.path.join(_HERE, "lfw")


def real_patches_cifar(n_test: int = 40, seed: int = 0
                       ) -> Tuple[DataSet, DataSet]:
    """(train, test) split of 200 REAL 32x32 photograph patches stored
    in CIFAR-10 binary format (2 classes: which photo the patch came
    from). Decodes through the same native/numpy CIFAR parser as
    ``load_cifar``; features [N, 3, 32, 32] in [0, 1], labels one-hot
    [N, 2]."""
    from deeplearning4j_tpu.native_rt import read_cifar_bin, u8_to_f32

    imgs, labels = read_cifar_bin(
        os.path.join(_HERE, "real_patches_batch.bin"))
    feats = u8_to_f32(imgs)
    onehot = np.eye(2, dtype=np.float32)[labels]
    return _split(feats, onehot, n_test, seed)


def raw_sentences(limit: int = None) -> List[str]:
    """The bundled real-English corpus, one sentence per string."""
    path = os.path.join(_HERE, "raw_sentences.txt.gz")
    with gzip.open(path, "rt", encoding="utf-8") as f:
        lines = [ln.strip() for ln in f]
    lines = [ln for ln in lines if ln]
    return lines[:limit] if limit else lines


def digits_dataset(n_test: int = 360, seed: int = 0
                   ) -> Tuple[DataSet, DataSet]:
    """(train, test) split of sklearn's real 8x8 handwritten digits.

    Features [N, 64] scaled to [0, 1]; labels one-hot [N, 10]. 1,797
    real examples — large enough for a statistically meaningful
    held-out accuracy gate (360 test examples -> ~0.3% granularity).
    """
    from sklearn.datasets import load_digits

    d = load_digits()
    feats = (d.data / 16.0).astype(np.float32)
    onehot = np.eye(10, dtype=np.float32)[d.target]
    return _split(feats, onehot, n_test, seed)
