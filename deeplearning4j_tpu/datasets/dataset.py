"""DataSet: (features, labels, feature mask, label mask).

Mirror of ND4J's DataSet as used throughout the reference (merge at
IterativeReduceFlatMap.java:84, masks through MultiLayerNetwork.fit :1152).
Numpy-backed on host; conversion to device arrays happens at the jit
boundary.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class DataSet:
    def __init__(
        self,
        features,
        labels,
        features_mask=None,
        labels_mask=None,
    ):
        self.features = np.asarray(features)
        # feature-only datasets (e.g. predict inputs) carry labels=None;
        # np.asarray(None) would silently make a 0-d object array
        self.labels = None if labels is None else np.asarray(labels)
        self.features_mask = (
            None if features_mask is None else np.asarray(features_mask)
        )
        self.labels_mask = (
            None if labels_mask is None else np.asarray(labels_mask)
        )

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def num_inputs(self) -> int:
        return int(self.features.shape[1])

    def num_outcomes(self) -> int:
        return int(self.labels.shape[1])

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        """Concatenate along the example axis (reference DataSet.merge)."""

        def cat(parts):
            parts = [p for p in parts if p is not None]
            return np.concatenate(parts, axis=0) if parts else None

        return DataSet(
            cat([d.features for d in datasets]),
            cat([d.labels for d in datasets]),
            cat([d.features_mask for d in datasets]),
            cat([d.labels_mask for d in datasets]),
        )

    def split_test_and_train(
        self, n_train: int
    ) -> Tuple["DataSet", "DataSet"]:
        return self.get_range(0, n_train), self.get_range(
            n_train, self.num_examples()
        )

    def get_range(self, start: int, end: int) -> "DataSet":
        sl = slice(start, end)
        return DataSet(
            self.features[sl],
            None if self.labels is None else self.labels[sl],
            None if self.features_mask is None else self.features_mask[sl],
            None if self.labels_mask is None else self.labels_mask[sl],
        )

    def sample(self, n: int, rng: Optional[np.random.Generator] = None) -> "DataSet":
        rng = rng or np.random.default_rng()
        idx = rng.choice(self.num_examples(), size=n, replace=False)
        return self.get_examples(idx)

    def get_examples(self, idx) -> "DataSet":
        return DataSet(
            self.features[idx],
            None if self.labels is None else self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx],
        )

    def shuffle(self, seed: Optional[int] = None) -> None:
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        if self.labels is not None:
            self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        return [
            self.get_range(i, min(i + batch_size, self.num_examples()))
            for i in range(0, self.num_examples(), batch_size)
        ]

    def scale_0_1(self) -> None:
        mn, mx = self.features.min(), self.features.max()
        if mx > mn:
            self.features = (self.features - mn) / (mx - mn)

    def normalize_zero_mean_unit_variance(self) -> None:
        mu = self.features.mean(axis=0, keepdims=True)
        sd = self.features.std(axis=0, keepdims=True) + 1e-8
        self.features = (self.features - mu) / sd

    def __repr__(self) -> str:
        labels = None if self.labels is None else self.labels.shape
        return (
            f"DataSet(features={self.features.shape}, labels={labels})"
        )


class MultiDataSet:
    """Multi-input / multi-output example container for ComputationGraph
    training (reference: nd4j MultiDataSet as consumed by
    ComputationGraph.fit, produced by
    datasets/canova/RecordReaderMultiDataSetIterator.java).

    ``features`` / ``labels`` are lists of arrays ordered like the graph's
    ``network_inputs`` / ``network_outputs``; masks are parallel lists
    (entries may be None).
    """

    def __init__(self, features, labels, features_masks=None,
                 labels_masks=None):
        as_list = lambda xs: [np.asarray(x) for x in xs]
        self.features = as_list(features)
        self.labels = as_list(labels)
        self.features_masks = (
            None if features_masks is None
            else [None if m is None else np.asarray(m)
                  for m in features_masks]
        )
        self.labels_masks = (
            None if labels_masks is None
            else [None if m is None else np.asarray(m)
                  for m in labels_masks]
        )

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])

    def num_feature_arrays(self) -> int:
        return len(self.features)

    def num_labels_arrays(self) -> int:
        return len(self.labels)

    def get_range(self, start: int, end: int) -> "MultiDataSet":
        sl = slice(start, end)
        cut = lambda ms: (
            None if ms is None
            else [None if m is None else m[sl] for m in ms]
        )
        return MultiDataSet(
            [f[sl] for f in self.features],
            [y[sl] for y in self.labels],
            cut(self.features_masks),
            cut(self.labels_masks),
        )

    @staticmethod
    def merge(datasets: Sequence["MultiDataSet"]) -> "MultiDataSet":
        first = datasets[0]

        def cat_arrays(get, n):
            return [
                np.concatenate([get(d)[i] for d in datasets], axis=0)
                for i in range(n)
            ]

        def cat_masks(get, ref_get, n):
            # A dataset without masks means "all timesteps valid": mixing
            # masked and unmasked datasets must not drop the masks
            # (padded steps would train as real data), so absent masks
            # are expanded to ones of the matching shape.
            if all(get(d) is None for d in datasets):
                return None
            out = []
            for i in range(n):
                protos = [
                    get(d)[i] for d in datasets
                    if get(d) is not None and get(d)[i] is not None
                ]
                if not protos:
                    out.append(None)
                    continue
                proto = protos[0]
                cols = []
                for d in datasets:
                    ms = get(d)
                    m = None if ms is None else ms[i]
                    if m is None:
                        n_ex = ref_get(d)[i].shape[0]
                        m = np.ones((n_ex,) + proto.shape[1:],
                                    proto.dtype)
                    cols.append(m)
                out.append(np.concatenate(cols, axis=0))
            return out

        n_f, n_l = len(first.features), len(first.labels)
        for d in datasets[1:]:
            if len(d.features) != n_f or len(d.labels) != n_l:
                raise ValueError(
                    "cannot merge MultiDataSets with differing array counts"
                )
        return MultiDataSet(
            cat_arrays(lambda d: d.features, n_f),
            cat_arrays(lambda d: d.labels, n_l),
            cat_masks(lambda d: d.features_masks, lambda d: d.features, n_f),
            cat_masks(lambda d: d.labels_masks, lambda d: d.labels, n_l),
        )

    def __repr__(self) -> str:
        return (
            f"MultiDataSet(features={[f.shape for f in self.features]}, "
            f"labels={[y.shape for y in self.labels]})"
        )


def to_multi_data_set(ds: "DataSet") -> "MultiDataSet":
    """DataSet -> single-input/single-output MultiDataSet (reference
    ComputationGraphUtil.toMultiDataSet / spark DataSetToMultiDataSetFn)."""
    return MultiDataSet(
        features=[ds.features],
        labels=[ds.labels] if ds.labels is not None else [],
        features_masks=(
            [ds.features_mask] if ds.features_mask is not None else None),
        labels_masks=(
            [ds.labels_mask] if ds.labels_mask is not None else None),
    )
