"""Data pipeline: DataSet container, iterators, built-in datasets, records.

Mirror of reference datasets/** (DataSetIterator.java:54, mnist/*,
iterator/impl/*, canova adapters — SURVEY.md §2.4). Host-side, feeding
device transfers; the AsyncDataSetIterator overlaps host prep with device
compute exactly like the reference's prefetch thread.
"""

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator,
    DataSetIterator,
    ListDataSetIterator,
    MovingWindowDataSetIterator,
    MultipleEpochsIterator,
    ReconstructionDataSetIterator,
    SamplingDataSetIterator,
    TestDataSetIterator,
)
from deeplearning4j_tpu.datasets.rearrange import (
    LocalUnstructuredDataFormatter,
)
