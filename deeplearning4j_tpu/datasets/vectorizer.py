"""Vectorizers: raw inputs → DataSet (reference datasets/vectorizer/*).

The reference's Vectorizer SPI turns one unstructured input (an image
file) into a labeled DataSet row (ImageVectorizer.java); kept here with
the same tiny contract plus a matrix moving-window helper used by the
vision pipeline (util/MovingWindowMatrix.java's role).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class Vectorizer:
    """SPI: ``vectorize() -> DataSet`` (reference Vectorizer.java)."""

    def vectorize(self) -> DataSet:
        raise NotImplementedError


class ImageVectorizer(Vectorizer):
    """One image file + label → one-row DataSet (reference
    datasets/vectorizer/ImageVectorizer.java)."""

    def __init__(self, path: str, label: int, num_labels: int,
                 height: Optional[int] = None, width: Optional[int] = None):
        self.path = path
        self.label = label
        self.num_labels = num_labels
        self.height = height
        self.width = width

    def vectorize(self) -> DataSet:
        from PIL import Image

        img = Image.open(self.path).convert("L")
        if self.height and self.width:
            img = img.resize((self.width, self.height))
        feats = np.asarray(img, np.float32).ravel()[None, :] / 255.0
        labels = np.zeros((1, self.num_labels), np.float32)
        labels[0, self.label] = 1.0
        return DataSet(feats, labels)


def moving_window_matrix(arr: np.ndarray, window_rows: int,
                         window_cols: int, rotate: int = 0) -> np.ndarray:
    """All dense sliding windows of a 2-D array, flattened per window →
    [num_windows, window_rows*window_cols] (reference
    util/MovingWindowMatrix.java; ``rotate`` appends 90°-rotated copies
    of each window like the reference's addRotate)."""
    h, w = arr.shape
    if window_rows > h or window_cols > w:
        raise ValueError("window larger than matrix")
    if rotate > 0 and window_rows != window_cols:
        raise ValueError("rotate requires square windows")
    views = np.lib.stride_tricks.sliding_window_view(
        arr, (window_rows, window_cols))
    windows = views.reshape(-1, window_rows, window_cols)
    out = [windows]
    current = windows
    for _ in range(rotate):
        current = np.rot90(current, axes=(1, 2))
        out.append(current)
    stacked = np.concatenate(out) if len(out) > 1 else windows
    return stacked.reshape(stacked.shape[0], -1).copy()
