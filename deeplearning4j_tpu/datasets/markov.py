"""Synthetic Markov-chain language-modeling data with an ANALYTIC
entropy floor.

The flagship transformer bench (bench.py) needs a convergence gate that
is honest on a zero-egress machine: random-noise sequences (the old
utilization rows) have nothing to learn, and any tiny real corpus would
be memorized by a width-1024 model. An order-1 Markov chain solves both:
unlimited fresh data (no overfitting possible), real sequential
structure to learn, and a closed-form optimal loss — the conditional
entropy H = Σ_i π_i H(P_i·) in nats — that the model's held-out
cross-entropy (ops/losses.py MCXENT: mean nats/token) can be gated
against. A model that reaches the floor has provably learned the
transition structure; no memorization can beat it on held-out draws.

The reference frame for the gate itself is the accuracy-parity role of
eval/Evaluation.java:85 (reference trains to a known-quality target);
here the target is information-theoretic rather than a dataset artifact.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def make_chain(vocab: int, seed: int = 0, concentration: float = 1.5
               ) -> Tuple[np.ndarray, np.ndarray, float]:
    """Build a random row-stochastic transition matrix.

    Returns (P [V, V], stationary pi [V], conditional entropy in nats).
    ``concentration`` scales the logit spread: larger -> peakier rows ->
    lower entropy floor (more learnable signal below log V).
    """
    rng = np.random.default_rng(seed)
    logits = concentration * rng.standard_normal((vocab, vocab))
    logits -= logits.max(axis=1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(axis=1, keepdims=True)
    # Stationary distribution by power iteration (row-stochastic P:
    # pi P = pi).
    pi = np.full(vocab, 1.0 / vocab)
    for _ in range(200):
        nxt = pi @ p
        if np.abs(nxt - pi).max() < 1e-12:
            pi = nxt
            break
        pi = nxt
    row_h = -np.sum(p * np.log(p), axis=1)
    return p, pi, float(np.dot(pi, row_h))


def sample_tokens(p: np.ndarray, n_seq: int, seq_len: int,
                  seed: int = 1) -> np.ndarray:
    """Sample [n_seq, seq_len + 1] token ids (the +1 supplies next-token
    labels). Vectorized over sequences: one categorical draw per step.
    """
    rng = np.random.default_rng(seed)
    vocab = p.shape[0]
    cum = np.cumsum(p, axis=1)
    cum[:, -1] = 1.0  # guard fp drift
    toks = np.empty((n_seq, seq_len + 1), dtype=np.int32)
    toks[:, 0] = rng.integers(0, vocab, n_seq)
    u = rng.random((n_seq, seq_len))
    for t in range(seq_len):
        rows = cum[toks[:, t]]
        toks[:, t + 1] = (rows < u[:, t:t + 1]).sum(axis=1)
    return toks


def markov_lm_batches(vocab: int, n_seq: int, seq_len: int,
                      seed: int = 0, concentration: float = 1.5,
                      sample_seed: int = None,
                      ) -> Tuple[np.ndarray, np.ndarray, float]:
    """One-hot LM training tensors from a chain draw.

    Returns (features [n_seq, vocab, seq_len], labels [n_seq, vocab,
    seq_len], entropy_floor_nats). Features are tokens 0..T-1, labels
    tokens 1..T — the standard next-token setup on the framework's
    [N, C, T] recurrent layout.

    ``seed`` fixes the CHAIN (the language); ``sample_seed`` the draws.
    A held-out split must share ``seed`` and vary ``sample_seed`` —
    fresh sentences of the same language, the split the entropy-floor
    gate is defined on.
    """
    p, _, floor = make_chain(vocab, seed=seed, concentration=concentration)
    if sample_seed is None:
        sample_seed = seed + 1
    toks = sample_tokens(p, n_seq, seq_len, seed=sample_seed)
    eye = np.eye(vocab, dtype=np.float32)
    feats = eye[toks[:, :-1]].transpose(0, 2, 1)
    labels = eye[toks[:, 1:]].transpose(0, 2, 1)
    return feats, labels, floor
