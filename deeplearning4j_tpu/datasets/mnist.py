"""MNIST dataset: IDX parsing + iterator.

Mirror of reference datasets/mnist/** (MnistManager/MnistDbFile/
MnistImageFile/MnistLabelFile — gzip IDX parsing) + fetchers/
MnistDataFetcher.java + iterator/impl/MnistDataSetIterator.java:30.

The reference downloads MNIST at test time; this environment has no
network egress, so the fetcher looks for IDX files in
``$DL4J_TPU_DATA_DIR`` (or ``~/.cache/deeplearning4j_tpu/mnist``) and
otherwise falls back to a deterministic procedurally-generated stand-in
with the same shapes/classes (class-conditional glyph patterns + jitter +
noise), which is learnable to >97% by the baseline MLP so accuracy gates
stay meaningful offline.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import BaseDataSetIterator

NUM_EXAMPLES = 60000
NUM_EXAMPLES_TEST = 10000


def _data_dir() -> str:
    return os.environ.get(
        "DL4J_TPU_DATA_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "deeplearning4j_tpu"),
    )


def read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (optionally gzipped) — reference MnistDbFile.
    Delegates to native_rt.read_idx: native decode for plain uint8 files,
    full Python parser (gzip + all element types) otherwise."""
    from deeplearning4j_tpu.native_rt import read_idx as _read

    return _read(path)


def _find_idx(basenames) -> Optional[str]:
    root = os.path.join(_data_dir(), "mnist")
    for b in basenames:
        for ext in ("", ".gz"):
            p = os.path.join(root, b + ext)
            if os.path.exists(p):
                return p
    return None


_IMG_FILES = {
    True: ("train-images-idx3-ubyte", "train-images.idx3-ubyte"),
    False: ("t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"),
}
_LBL_FILES = {
    True: ("train-labels-idx1-ubyte", "train-labels.idx1-ubyte"),
    False: ("t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"),
}


def _synthetic_mnist(n: int, train: bool, seed: int = 6) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST stand-in: 10 fixed low-frequency glyphs,
    randomly shifted +-3px with pixel noise. Same dtype/range as MNIST."""
    rng = np.random.default_rng(seed)  # glyphs shared by train/test
    yy, xx = np.mgrid[0:28, 0:28].astype(np.float32) / 27.0
    glyphs = []
    for c in range(10):
        coeff = rng.normal(size=(3, 3))
        g = np.zeros((28, 28), np.float32)
        for i in range(3):
            for j in range(3):
                g += coeff[i, j] * np.sin(
                    np.pi * (i + 1) * yy + 0.3 * c
                ) * np.sin(np.pi * (j + 1) * xx + 0.1 * c)
        g = (g - g.min()) / (g.max() - g.min() + 1e-8)
        glyphs.append(g)
    glyphs = np.stack(glyphs)

    srng = np.random.default_rng(seed + (1 if train else 2))
    labels = srng.integers(0, 10, size=n)
    imgs = np.empty((n, 28, 28), np.float32)
    shifts = srng.integers(-3, 4, size=(n, 2))
    noise = srng.normal(0, 0.15, size=(n, 28, 28)).astype(np.float32)
    for i in range(n):
        g = np.roll(glyphs[labels[i]], tuple(shifts[i]), axis=(0, 1))
        imgs[i] = np.clip(g + noise[i], 0.0, 1.0)
    return (imgs * 255).astype(np.uint8), labels.astype(np.uint8)


def load_mnist(train: bool = True, num_examples: Optional[int] = None):
    """-> (images uint8 [N,28,28], labels uint8 [N]). Real data when IDX
    files exist, synthetic fallback otherwise."""
    img_path = _find_idx(_IMG_FILES[train])
    lbl_path = _find_idx(_LBL_FILES[train])
    if img_path and lbl_path:
        imgs = read_idx(img_path)
        labels = read_idx(lbl_path)
    else:
        total = NUM_EXAMPLES if train else NUM_EXAMPLES_TEST
        imgs, labels = _synthetic_mnist(
            num_examples or total, train
        )
    if num_examples is not None:
        imgs, labels = imgs[:num_examples], labels[:num_examples]
    return imgs, labels


def mnist_dataset(
    train: bool = True,
    num_examples: Optional[int] = None,
    binarize: bool = False,
    as_image: bool = False,
    seed: Optional[int] = None,
    normalize: bool = True,
) -> DataSet:
    from deeplearning4j_tpu.native_rt import one_hot, u8_to_f32

    imgs, labels = load_mnist(train, num_examples)
    x = u8_to_f32(imgs, scale=(1.0 / 255.0) if normalize else 1.0)
    if binarize:
        # threshold at half intensity in whichever scale is active
        x = (x > (0.5 if normalize else 127.5)).astype(np.float32)
    if as_image:
        x = x.reshape(-1, 1, 28, 28)  # [N, C, H, W]
    else:
        x = x.reshape(-1, 784)
    y = one_hot(labels.astype(int), 10)
    ds = DataSet(x, y)
    if seed is not None:
        ds.shuffle(seed)
    return ds


class MnistDataSetIterator(BaseDataSetIterator):
    """Reference datasets/iterator/impl/MnistDataSetIterator.java:30."""

    def __init__(
        self,
        batch_size: int,
        num_examples: Optional[int] = None,
        binarize: bool = False,
        train: bool = True,
        shuffle: bool = False,
        seed: int = 123,
        as_image: bool = False,
        normalize: bool = True,
    ):
        ds = mnist_dataset(
            train, num_examples, binarize, as_image,
            seed if shuffle else None, normalize=normalize,
        )
        super().__init__(batch_size, ds)


class RawMnistDataSetIterator(MnistDataSetIterator):
    """Raw 0-255 pixel values, no normalization (reference
    datasets/iterator/impl/RawMnistDataSetIterator.java)."""

    def __init__(self, batch_size: int,
                 num_examples: Optional[int] = None, train: bool = True):
        super().__init__(batch_size, num_examples, train=train,
                         normalize=False)
