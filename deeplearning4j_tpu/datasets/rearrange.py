"""Raw-directory → train/test split (reference
datasets/rearrange/LocalUnstructuredDataFormatter.java).

Input layout: ``root/<class-name>/<files...>``. Output layout::

    dest/split/train/<class-name>/<files...>
    dest/split/test/<class-name>/<files...>

Split is deterministic under ``seed``; files are copied (or moved).
"""

from __future__ import annotations

import enum
import os
import shutil
from typing import Dict, List

import numpy as np


class LabelingType(enum.Enum):
    DIRECTORY = "directory"  # class = parent dir name (only mode here)


class LocalUnstructuredDataFormatter:
    def __init__(
        self,
        dest_dir: str,
        src_dir: str,
        percent_train: float = 0.8,
        seed: int = 123,
        move: bool = False,
    ):
        if not 0.0 < percent_train < 1.0:
            raise ValueError("percent_train must be in (0, 1)")
        self.dest_dir = dest_dir
        self.src_dir = src_dir
        self.percent_train = percent_train
        self.seed = seed
        self.move = move
        self._counts: Dict[str, int] = {}

    def rearrange(self) -> None:
        self._counts = {}
        rng = np.random.default_rng(self.seed)
        classes = sorted(
            d for d in os.listdir(self.src_dir)
            if os.path.isdir(os.path.join(self.src_dir, d))
        )
        if not classes:
            raise ValueError(f"no class subdirectories in {self.src_dir}")
        for cls in classes:
            files: List[str] = sorted(
                f for f in os.listdir(os.path.join(self.src_dir, cls))
                if os.path.isfile(os.path.join(self.src_dir, cls, f))
            )
            perm = rng.permutation(len(files))
            n_train = max(1, int(round(len(files) * self.percent_train)))
            if len(files) > 1:
                n_train = min(n_train, len(files) - 1)
            for rank, idx in enumerate(perm):
                part = "train" if rank < n_train else "test"
                src = os.path.join(self.src_dir, cls, files[idx])
                dst_dir = os.path.join(self.dest_dir, "split", part, cls)
                os.makedirs(dst_dir, exist_ok=True)
                dst = os.path.join(dst_dir, files[idx])
                (shutil.move if self.move else shutil.copy2)(src, dst)
                self._counts[part] = self._counts.get(part, 0) + 1

    def num_examples_total(self) -> int:
        return sum(self._counts.values())

    def num_test_examples(self) -> int:
        return self._counts.get("test", 0)

    def get_train_dir(self) -> str:
        return os.path.join(self.dest_dir, "split", "train")

    def get_test_dir(self) -> str:
        return os.path.join(self.dest_dir, "split", "test")
