"""Disk-streaming DataSetIterators: batches read from on-disk binaries
at next() time, never materializing the dataset in memory.

The reference's L3 design feeds ``fit()`` from iterators backed by
files (datasets/iterator/impl/*DataSetIterator.java pulling from
fetchers/Canova readers), with AsyncDataSetIterator overlapping the
reads with training. These iterators are the disk half of that story on
the TPU build — wrap them in
``native_rt.NativeAsyncDataSetIterator`` (C++ prefetch ring) and feed
``MultiLayerNetwork.fit_stream`` for the full host-fed pipeline:

    disk -> producer thread -> C++ ring -> window stack -> one H2D
    -> fused fit_scan dispatch

Formats:
- CIFAR-10 binary batches (rows of [label u8][3072 px u8]) — the same
  files ``fetchers.load_cifar`` loads whole; here streamed by row range.
- Token-sequence files: ``DL4JTOK1`` header + u8/u16 token-id rows
  [n_seq, seq_len + 1] — the LM wire format (ids on disk and on the
  wire; one-hot only on device).
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator

_TOK_MAGIC = b"DL4JTOK1"


class StorageDataSetIterator(DataSetIterator):
    """Stream batches from shard files in a ``storage.backends``
    backend (S3/GCS/HDFS/local) into ``fit()`` — the reference's
    BaseS3DataSetIterator/BaseHdfsDataSetIterator role
    (deeplearning4j-aws BaseS3DataSetIterator.java:1): one shard is
    downloaded at a time, parsed, and batched; the next shard is
    fetched only when the current one drains, so the working set
    stays one shard regardless of dataset size.

    ``fmt``:
    - ``"cifar"`` — shards are CIFAR-10 binary batch files
      (u8 [B,3,32,32] features, one-hot labels),
    - ``"tokens"`` — DL4JTOK1 token files (LM id pairs),
    - ``"npz"`` — ``np.savez`` archives with ``features``/``labels``
      (+ optional ``features_mask``/``labels_mask``) arrays.

    Wrap in ``native_rt.NativeAsyncDataSetIterator`` to overlap the
    downloads with training (the reference pairs its S3 iterator with
    AsyncDataSetIterator the same way)."""

    def __init__(self, backend, prefix: str, batch_size: int,
                 fmt: str = "npz", num_classes: int = 10):
        super().__init__(batch_size)
        if fmt not in ("cifar", "tokens", "npz"):
            raise ValueError(f"unknown shard format {fmt!r}")
        self.backend = backend
        self.prefix = prefix
        self.fmt = fmt
        self.num_classes = num_classes
        self.keys = sorted(backend.list(prefix))
        if not self.keys:
            raise ValueError(
                f"no shards under prefix {prefix!r}")
        self._key_idx = 0
        self._inner: Optional[DataSetIterator] = None
        self._tmpdir = None
        self._current_local: Optional[str] = None
        self._schema: dict = {}

    def _local_copy(self, key: str) -> str:
        import tempfile

        if self._tmpdir is None:
            self._tmpdir = tempfile.mkdtemp(prefix="dl4j_storage_it_")
        local = os.path.join(
            self._tmpdir, os.path.basename(key) or "shard")
        return self.backend.get(key, local)

    def _drop_current(self) -> None:
        """Delete the drained shard's local copy — the working set is
        ONE shard, so an epoch over a dataset larger than local disk
        cannot fill it."""
        self._inner = None
        if self._current_local is not None:
            try:
                os.unlink(self._current_local)
            except OSError:
                pass
            self._current_local = None

    def close(self) -> None:
        import shutil

        self._drop_current()
        if self._tmpdir is not None:
            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _open(self, key: str) -> DataSetIterator:
        local = self._current_local = self._local_copy(key)
        return self._open_local(local)

    def _open_local(self, local: str) -> DataSetIterator:
        if self.fmt == "cifar":
            return CifarBinStreamIterator(
                [local], self.batch, num_classes=self.num_classes)
        if self.fmt == "tokens":
            return TokenSequenceFileIterator(local, self.batch)
        z = np.load(local)
        from deeplearning4j_tpu.datasets.iterator import (
            BaseDataSetIterator,
        )

        ds = DataSet(z["features"], z["labels"],
                     z["features_mask"] if "features_mask" in z else None,
                     z["labels_mask"] if "labels_mask" in z else None)
        return BaseDataSetIterator(self.batch, ds)

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        while True:
            if self._inner is None:
                if self._key_idx >= len(self.keys):
                    return None
                self._inner = self._open(self.keys[self._key_idx])
            ds = self._inner.next(num)
            if ds is not None:
                return self._post(ds)
            self._drop_current()
            self._key_idx += 1

    def reset(self) -> None:
        self._drop_current()
        self._key_idx = 0

    def total_examples(self) -> int:
        # would require opening every shard; the reference's S3
        # iterator returns the configured total as well
        raise NotImplementedError(
            "total_examples requires scanning every remote shard")

    def _schema_val(self, name: str) -> int:
        """Schema queries, cached after the first answer. A LIVE
        reader answers for free (its schema accessors are pure — safe
        even while a producer thread drives next()); with no shard
        open, the first shard is probed into a PRIVATE temp dir so
        nothing here mutates iterator state (an async producer may be
        mid-_open concurrently)."""
        if name not in self._schema:
            inner = self._inner  # snapshot: producer may swap it
            if inner is not None:
                schema = {"input_columns": inner.input_columns(),
                          "total_outcomes": inner.total_outcomes()}
            else:
                import tempfile

                with tempfile.TemporaryDirectory(
                        prefix="dl4j_storage_meta_") as d:
                    local = self.backend.get(
                        self.keys[0], os.path.join(d, "meta_shard"))
                    reader = self._open_local(local)
                    schema = {
                        "input_columns": reader.input_columns(),
                        "total_outcomes": reader.total_outcomes(),
                    }
            self._schema.update(schema)
        return self._schema[name]

    def input_columns(self) -> int:
        if self.fmt == "cifar":
            return 3 * 32 * 32
        return self._schema_val("input_columns")

    def total_outcomes(self) -> int:
        if self.fmt == "cifar":
            return self.num_classes
        return self._schema_val("total_outcomes")

    def state_dict(self) -> dict:
        return {
            "key_idx": self._key_idx,
            "inner": (None if self._inner is None
                      else self._inner.state_dict()),
        }

    def load_state_dict(self, state: dict) -> None:
        self._drop_current()  # unlink the open shard's local copy
        self._key_idx = int(state["key_idx"])
        if state.get("inner") is not None and self._key_idx < len(
                self.keys):
            self._inner = self._open(self.keys[self._key_idx])
            self._inner.load_state_dict(state["inner"])


class CifarBinStreamIterator(DataSetIterator):
    """Stream [label u8][3072 px u8] rows from CIFAR-binary files.

    Yields DataSet(features u8 [B, 3, 32, 32], labels one-hot f32
    [B, num_classes]); features stay u8 (the wire-minimal form —
    normalize on device, e.g. via ``fit_stream``'s ingest hook).
    Batches never span files (the on-disk batches are independent
    shards, like the reference's data_batch_1..5)."""

    def __init__(self, paths: Sequence[str], batch_size: int,
                 num_classes: int = 10):
        super().__init__(batch_size)
        self.paths: List[str] = list(paths)
        self.num_classes = num_classes
        self._rows_per_file = []
        for p in self.paths:
            size = os.path.getsize(p)
            if size == 0 or size % 3073:
                raise ValueError(
                    f"{p}: not a CIFAR-10 binary batch file")
            self._rows_per_file.append(size // 3073)
        self._file_idx = 0
        self._row = 0

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        n = num or self.batch
        while self._file_idx < len(self.paths):
            avail = self._rows_per_file[self._file_idx] - self._row
            if avail > 0:
                take = min(n, avail)
                mm = np.memmap(self.paths[self._file_idx],
                               dtype=np.uint8, mode="r")
                lo, hi = self._row * 3073, (self._row + take) * 3073
                rows = np.asarray(mm[lo:hi]).reshape(take, 3073)
                del mm
                self._row += take
                feats = rows[:, 1:].reshape(take, 3, 32, 32)
                labels = np.zeros((take, self.num_classes), np.float32)
                labels[np.arange(take), rows[:, 0]] = 1.0
                return self._post(DataSet(feats, labels))
            self._file_idx += 1
            self._row = 0
        return None

    def reset(self) -> None:
        self._file_idx = 0
        self._row = 0

    def skip_batches(self, n: int) -> int:
        """Seek-based skip: batches never span files, so the cursor
        advances with row arithmetic — no pixel is read (the async
        wrapper's exactly-once replay stays O(1) per batch)."""
        skipped = 0
        for _ in range(int(n)):
            while (self._file_idx < len(self.paths)
                   and self._row >= self._rows_per_file[self._file_idx]):
                self._file_idx += 1
                self._row = 0
            if self._file_idx >= len(self.paths):
                break
            avail = self._rows_per_file[self._file_idx] - self._row
            self._row += min(self.batch, avail)
            skipped += 1
        return skipped

    def total_examples(self) -> int:
        return int(sum(self._rows_per_file))

    def input_columns(self) -> int:
        return 3 * 32 * 32

    def total_outcomes(self) -> int:
        return self.num_classes

    def state_dict(self) -> dict:
        return {"file_idx": self._file_idx, "row": self._row}

    def load_state_dict(self, state: dict) -> None:
        self._file_idx = int(state["file_idx"])
        self._row = int(state["row"])


def write_token_file(path: str, tokens: np.ndarray, vocab: int) -> None:
    """Write [n_seq, row_len] token ids as a DL4JTOK1 binary (u8 rows
    for vocab <= 256, u16 otherwise)."""
    tokens = np.asarray(tokens)
    if tokens.ndim != 2:
        raise ValueError("tokens must be [n_seq, row_len]")
    if tokens.min() < 0 or tokens.max() >= vocab:
        raise ValueError(f"token ids outside [0, {vocab})")
    dtype = np.uint8 if vocab <= 256 else np.uint16
    with open(path, "wb") as f:
        f.write(_TOK_MAGIC)
        f.write(struct.pack("<IIII", tokens.shape[0], tokens.shape[1],
                            vocab, dtype().itemsize))
        f.write(np.ascontiguousarray(tokens, dtype).tobytes())


def read_token_file_header(path: str) -> Tuple[int, int, int, int]:
    """-> (n_seq, row_len, vocab, itemsize)."""
    with open(path, "rb") as f:
        if f.read(8) != _TOK_MAGIC:
            raise ValueError(f"{path}: not a DL4JTOK1 token file")
        return struct.unpack("<IIII", f.read(16))


class TokenSequenceFileIterator(DataSetIterator):
    """Stream next-token LM batches from a DL4JTOK1 file.

    Each row of [n_seq, T + 1] ids becomes (features = ids[:-1],
    labels = ids[1:]), both [B, T] integer arrays — the minimal wire
    form. One-hot/embedding happens on device (``fit_stream``'s
    ingest/ingest_labels hooks)."""

    def __init__(self, path: str, batch_size: int):
        super().__init__(batch_size)
        self.path = path
        (self.n_seq, self.row_len, self.vocab,
         self._itemsize) = read_token_file_header(path)
        self._dtype = np.uint8 if self._itemsize == 1 else np.uint16
        self._cursor = 0

    def next(self, num: Optional[int] = None) -> Optional[DataSet]:
        n = num or self.batch
        if self._cursor >= self.n_seq:
            return None
        take = min(n, self.n_seq - self._cursor)
        offset = 24 + self._cursor * self.row_len * self._itemsize
        rows = np.fromfile(self.path, dtype=self._dtype,
                           count=take * self.row_len, offset=offset
                           ).reshape(take, self.row_len)
        self._cursor += take
        return self._post(DataSet(rows[:, :-1], rows[:, 1:]))

    def reset(self) -> None:
        self._cursor = 0

    def skip_batches(self, n: int) -> int:
        """Seek-based skip: pure cursor arithmetic over the fixed-row
        file — no token is read."""
        skipped = 0
        for _ in range(int(n)):
            if self._cursor >= self.n_seq:
                break
            self._cursor = min(self.n_seq, self._cursor + self.batch)
            skipped += 1
        return skipped

    def total_examples(self) -> int:
        return self.n_seq

    def input_columns(self) -> int:
        return self.row_len - 1

    def total_outcomes(self) -> int:
        return self.vocab

    def state_dict(self) -> dict:
        return {"cursor": self._cursor}

    def load_state_dict(self, state: dict) -> None:
        self._cursor = int(state["cursor"])
